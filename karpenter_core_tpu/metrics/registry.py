"""Prometheus-compatible metrics (ref pkg/metrics/metrics.go,
constants.go): counters/gauges/histograms with label sets, exposable in
text format. Metric names mirror the reference's `karpenter_` namespace
so dashboards port over."""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

NAMESPACE = "karpenter"

# duration buckets (constants.go:24-60 DurationBuckets)
DURATION_BUCKETS = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600,
]


def latency_buckets() -> List[float]:
    """Decision-latency histogram buckets, env-tunable (ISSUE 10
    satellite): ``KARPENTER_TPU_LATENCY_BUCKETS_MS`` is a comma-
    separated millisecond list (e.g. "1,5,10,50,100,500,1000") so
    ms-scale fleet decisions and second-scale disruption decisions
    don't all pile into one bucket. Buckets are fixed at Histogram
    construction — the env is read when ``Metrics`` is built (operator
    start), not per observe. Unset/invalid → the reference's
    DurationBuckets."""
    raw = os.environ.get("KARPENTER_TPU_LATENCY_BUCKETS_MS", "")
    if not raw.strip():
        return DURATION_BUCKETS
    try:
        ms = sorted({float(part) for part in raw.split(",") if part.strip()})
    except ValueError:
        return DURATION_BUCKETS
    if not ms or any(b <= 0 for b in ms):
        return DURATION_BUCKETS
    return [b / 1000.0 for b in ms]


def _labels_key(labels: Dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    def __init__(self, name: str, help_: str = "", label_names: Iterable[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.values: Dict[tuple, float] = {}
        self._mu = threading.Lock()

    def inc(self, value: float = 1.0, **labels) -> None:
        key = _labels_key(labels)
        with self._mu:
            self.values[key] = self.values.get(key, 0.0) + value

    def get(self, **labels) -> float:
        with self._mu:
            return self.values.get(_labels_key(labels), 0.0)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}", f"# TYPE {self.name} counter"]
        with self._mu:
            items = sorted(self.values.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str = "", label_names: Iterable[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.values: Dict[tuple, float] = {}
        self._mu = threading.Lock()

    def set(self, value: float, **labels) -> None:
        with self._mu:
            self.values[_labels_key(labels)] = value

    def get(self, **labels) -> Optional[float]:
        with self._mu:
            return self.values.get(_labels_key(labels))

    def delete(self, **labels) -> None:
        with self._mu:
            self.values.pop(_labels_key(labels), None)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}", f"# TYPE {self.name} gauge"]
        with self._mu:
            items = sorted(self.values.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Histogram:
    def __init__(self, name: str, help_: str = "", buckets: Optional[List[float]] = None, label_names: Iterable[str] = ()):
        self.name = name
        self.help = help_
        self.buckets = buckets or DURATION_BUCKETS
        self.label_names = tuple(label_names)
        self.counts: Dict[tuple, List[int]] = {}
        self.sums: Dict[tuple, float] = {}
        self.totals: Dict[tuple, int] = {}
        # last exemplar per (labelset, bucket): OpenMetrics-style trace
        # anchors ("which trace_id filled this latency bucket last") —
        # served via /debug/decisions, NOT the text exposition (classic
        # Prometheus text format has no exemplar syntax; emitting it
        # would fail the textcheck gate and ordinary scrapers)
        self._exemplars: Dict[tuple, Dict[str, Tuple[str, float, float]]] = {}
        self._mu = threading.Lock()

    def observe(self, value: float, exemplar: Optional[str] = None, **labels) -> None:
        key = _labels_key(labels)
        with self._mu:
            if key not in self.counts:
                self.counts[key] = [0] * len(self.buckets)
                self.sums[key] = 0.0
                self.totals[key] = 0
            bucket = "+Inf"
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.counts[key][i] += 1
                    if bucket == "+Inf":
                        bucket = str(b)
            self.sums[key] += value
            self.totals[key] += 1
            if exemplar is not None:
                self._exemplars.setdefault(key, {})[bucket] = (
                    str(exemplar),
                    value,
                    time.time(),
                )

    def exemplars(self, **labels) -> Dict[str, Tuple[str, float, float]]:
        """{bucket le → (exemplar, value, wall ts)} for one label set."""
        with self._mu:
            return dict(self._exemplars.get(_labels_key(labels), {}))

    def time(self, **labels):
        """Context manager: `with h.time(): ...` (metrics.Measure helper)."""
        hist = self

        class _Timer:
            def __enter__(self):
                self.start = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self.start, **labels)
                return False

        return _Timer()

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}", f"# TYPE {self.name} histogram"]
        with self._mu:
            snapshot = sorted(self.counts)
            counts = {k: list(v) for k, v in self.counts.items()}
            sums = dict(self.sums)
            totals = dict(self.totals)
        for key in snapshot:
            for i, b in enumerate(self.buckets):
                out.append(f'{self.name}_bucket{_fmt_labels(key, le=str(b))} {counts[key][i]}')
            out.append(f'{self.name}_bucket{_fmt_labels(key, le="+Inf")} {totals[key]}')
            out.append(f"{self.name}_sum{_fmt_labels(key)} {sums[key]}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {totals[key]}")
        return out


def _escape_label_value(v) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline) — label values carry user-controlled strings (node names,
    error reasons)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(text: str) -> str:
    """HELP-line escaping per the exposition format (backslash, newline)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(key: tuple, **extra) -> str:
    pairs = list(key) + sorted(extra.items())
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + inner + "}"


class _TracerOrphanCollector:
    """Registry bridge for the tracer's process-global orphan-span
    counter (tracing/tracer.py): spans born on a thread with no active
    root vanish from every trace — with cross-thread context
    propagation in place the count should be zero, and the serving/
    fleet identity tests assert it. Read-only: the value lives in the
    tracer so instrumented code never needs a Metrics handle."""

    name = f"{NAMESPACE}_tpu_tracer_orphan_spans_total"
    help = (
        "Spans dropped because no trace was active on their thread "
        "(attribution bug once TraceContext propagation covers every lane)"
    )

    def collect(self) -> List[str]:
        from ..tracing.tracer import orphan_spans

        return [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} counter",
            f"{self.name} {float(orphan_spans())}",
        ]


class Registry:
    def __init__(self) -> None:
        self.metrics: List[object] = []
        self._mu = threading.Lock()

    def register(self, metric):
        with self._mu:
            self.metrics.append(metric)
        return metric

    def counter(self, name: str, help_: str = "", labels: Iterable[str] = ()) -> Counter:
        return self.register(Counter(name, help_, labels))

    def gauge(self, name: str, help_: str = "", labels: Iterable[str] = ()) -> Gauge:
        return self.register(Gauge(name, help_, labels))

    def histogram(self, name: str, help_: str = "", buckets=None, labels: Iterable[str] = ()) -> Histogram:
        return self.register(Histogram(name, help_, buckets, labels))

    def expose(self) -> str:
        """Prometheus text exposition format (the /metrics payload)."""
        with self._mu:
            metrics = list(self.metrics)
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"


class Metrics:
    """The reference's metric set (pkg/metrics/metrics.go:29-135 +
    per-package metrics), bound to one registry."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        ns = NAMESPACE
        self.nodeclaims_created = r.counter(f"{ns}_nodeclaims_created", "NodeClaims created", ["reason", "nodepool"])
        self.nodeclaims_terminated = r.counter(f"{ns}_nodeclaims_terminated", "NodeClaims terminated", ["reason", "nodepool"])
        self.nodeclaims_launched = r.counter(f"{ns}_nodeclaims_launched", "NodeClaims launched", ["nodepool"])
        self.nodeclaims_registered = r.counter(f"{ns}_nodeclaims_registered", "NodeClaims registered", ["nodepool"])
        self.nodeclaims_initialized = r.counter(f"{ns}_nodeclaims_initialized", "NodeClaims initialized", ["nodepool"])
        self.nodeclaims_disrupted = r.counter(f"{ns}_nodeclaims_disrupted", "NodeClaims disrupted", ["method"])
        self.nodeclaims_drifted = r.counter(f"{ns}_nodeclaims_drifted", "NodeClaims drifted", ["type"])
        self.nodes_created = r.counter(f"{ns}_nodes_created", "Nodes created", ["nodepool"])
        self.nodes_terminated = r.counter(f"{ns}_nodes_terminated", "Nodes terminated", ["nodepool"])
        self.scheduling_duration = r.histogram(
            f"{ns}_provisioner_scheduling_duration_seconds", "Scheduling duration"
        )
        self.simulation_duration = r.histogram(
            f"{ns}_provisioner_scheduling_simulation_duration_seconds", "Simulation duration"
        )
        self.disruption_evaluation_duration = r.histogram(
            f"{ns}_disruption_evaluation_duration_seconds", "Disruption evaluation duration", labels=["method"]
        )
        self.disruption_actions = r.counter(
            f"{ns}_disruption_actions_performed_total", "Disruption actions", ["method", "action"]
        )
        self.eligible_nodes = r.gauge(
            f"{ns}_disruption_eligible_nodes", "Disruption-eligible nodes", ["method"]
        )
        self.disruption_subsets = r.counter(
            f"{ns}_disruption_subsets_total",
            "Candidate node subsets processed by the disruption engine, by stage (screened | verified)",
            ["stage"],
        )
        self.consistency_errors = r.counter(f"{ns}_nodeclaims_consistency_errors", "Consistency errors")
        self.cloudprovider_duration = r.histogram(
            f"{ns}_cloudprovider_duration_seconds", "Cloud provider method duration", labels=["method", "provider"]
        )
        self.cloudprovider_errors = r.counter(
            f"{ns}_cloudprovider_errors_total", "Cloud provider errors", ["method", "provider"]
        )
        self.solver_duration = r.histogram(
            f"{ns}_tpu_solver_duration_seconds", "TPU solve wall time"
        )
        self.solver_parity = r.gauge(
            f"{ns}_tpu_solver_packing_parity", "TPU/oracle packing parity ratio"
        )
        self.solver_phase_duration = r.histogram(
            f"{ns}_tpu_solver_phase_duration_seconds",
            "TPU solve phase wall time, per tracing span (coarse: existing_pack/encode/pack/affinity_postpass; fine: encode.*/pack.*/device_wait/... — see tracing/)",
            labels=["phase"],
        )
        self.solver_device_duration = r.histogram(
            f"{ns}_tpu_solver_device_duration_seconds",
            "Device-attributable time per solve (dispatch + transfer + blocked-on-device)",
        )
        # steady-state incremental solve (solver/incremental.py): cross-
        # solve cache traffic, labeled by cache layer (catalog | compat |
        # route | job | merge | seeds | warmstart)
        self.solver_cache_hits = r.counter(
            f"{ns}_tpu_solver_cache_hits", "Cross-solve solver cache hits", ["cache"]
        )
        self.solver_cache_misses = r.counter(
            f"{ns}_tpu_solver_cache_misses", "Cross-solve solver cache misses", ["cache"]
        )
        self.solver_cache_evictions = r.counter(
            f"{ns}_tpu_solver_cache_evictions",
            "Cross-solve solver cache evictions (LRU caps, env-tunable)",
            ["cache"],
        )
        # plan-quality pack backends (solver/backends/): per pack job,
        # whether the LP-relaxation candidate beat FFD on plan cost
        # (lp_won) or the guard kept the FFD partition — split by
        # whether the optimality tier ran before the rejection (ISSUE
        # 19): ffd_kept_cold = no refinement/branching attempted,
        # ffd_kept_refined = FFD still won after the tier spent its
        # budget (legacy rounds without the split report ffd_kept)
        self.solver_lp_jobs = r.counter(
            f"{ns}_tpu_solver_lp_jobs",
            "Pack jobs through the LP-relaxation backend, by guard outcome "
            "(lp_won | ffd_kept_cold | ffd_kept_refined)",
            ["outcome"],
        )
        # restricted branch-and-bound (ISSUE 19): every considered
        # branch is accounted — pruned by its dual bound without
        # packing, explored (packed, did not beat the incumbent), or
        # won (became the incumbent). Pruning is never silent.
        self.solver_lp_branches = r.counter(
            f"{ns}_tpu_solver_lp_branches",
            "LP branch-and-bound branches, by outcome (pruned | explored | won)",
            ["outcome"],
        )
        # constraint tensorization (ISSUE 12): per-solve pod routing
        # split — how many pods ran on the tensor path vs parked
        # (post-pack affinity) vs the greedy-oracle fallback; the
        # oracle-routed share is the gated residue
        self.solver_route_pods = r.counter(
            f"{ns}_tpu_solver_route_pods",
            "Pods per solve by constraint route (tensor | parked | oracle)",
            ["route"],
        )
        # pod-axis sharded mega-solves (solver/sharding.py): mesh
        # padding is never silent — wasted slot fraction of the last
        # solve's pod-chunk padding and type-shard padding
        self.shard_padding_waste = r.gauge(
            f"{ns}_tpu_shard_padding_waste",
            "Padded-slot fraction wasted by the last sharded solve's mesh tiling (axis = pods | types)",
            ["axis"],
        )
        # warm-state persistence (solver/warmstore.py): per-plane
        # restore outcomes — every restored entry re-anchored against
        # the live world, every witness-failed entry dropped and
        # counted (restores are never silent, ISSUE 13)
        self.warmstore_restored = r.counter(
            f"{ns}_tpu_warmstore_restored_entries",
            "Warm-state snapshot entries restored per cache plane (re-anchored against the live catalog/cluster world)",
            ["plane"],
        )
        self.warmstore_dropped = r.counter(
            f"{ns}_tpu_warmstore_dropped_entries",
            "Warm-state snapshot entries dropped per cache plane (version/contract/fingerprint witness mismatch — never trusted)",
            ["plane"],
        )
        # device-plane observatory (tracing/deviceplane.py, ISSUE 16):
        # XLA compile events attributed per jit entry point and cause
        # (first | new_shape | new_config — trace_id exemplars ride
        # /debug/device and the stats device block, never the classic
        # text exposition), H2D/D2H transfer bytes per solve phase, and
        # the device-memory high-water mark of the last polled solve
        self.xla_compiles = r.counter(
            f"{ns}_tpu_xla_compiles_total",
            "XLA compiles observed at registered jit entry points, by function and cause (first | new_shape | new_config | prewarm_replay); trace_id exemplars via /debug/device",
            ["fn", "cause"],
        )
        self.transfer_bytes = r.counter(
            f"{ns}_tpu_solver_transfer_bytes_total",
            "Host<->device bytes moved by solver dispatches, by direction (h2d | d2h) and solve phase",
            ["direction", "phase"],
        )
        self.hbm_high_water = r.gauge(
            f"{ns}_tpu_hbm_high_water_bytes",
            "Device-memory high-water mark polled at the end of the last solve (peak_bytes_in_use; absent off-accelerator)",
        )
        # serving pipeline (serving/pipeline.py): the decision-latency
        # SLO (pod-pending → plan emitted), per-stage durations, and
        # stage-queue depths (backpressure visibility)
        self.serving_decision_latency = r.histogram(
            f"{ns}_serving_decision_latency_seconds",
            "Pod-pending to plan-emitted decision latency (serving SLO); buckets env-tunable via KARPENTER_TPU_LATENCY_BUCKETS_MS; exemplar trace_ids per bucket via /debug/decisions",
            buckets=latency_buckets(),
        )
        # decision telemetry plane (tracing/flightrec.py): SLO burn rate
        # (fraction of decisions over KARPENTER_TPU_SLO_TARGET_MS per
        # trailing window) and the tracer's orphan-span counter
        self.decision_slo_burn = r.gauge(
            f"{ns}_tpu_decision_slo_burn_rate",
            "Fraction of decisions over the latency SLO target in the trailing window (1m | 10m)",
            ["window"],
        )
        # real-apiserver watch loop (kube/restclient.py): relist and
        # retry traffic under 410 storms / stream drops — attached via
        # RestKubeClient.attach_watch_metrics (kube/ stays registry-
        # agnostic); retries are never silent (ISSUE 15)
        self.watch_relists = r.counter(
            f"{ns}_tpu_watch_relists_total",
            "Watch relists (initial list + 410/ERROR recovery), by kind",
            ["kind"],
        )
        self.watch_errors = r.counter(
            f"{ns}_tpu_watch_errors_total",
            "Watch stream errors, by kind and reason (410 | http | stream | error_event)",
            ["kind", "reason"],
        )
        self.watch_backoff_seconds = r.counter(
            f"{ns}_tpu_watch_backoff_seconds_total",
            "Seconds of capped+jittered watch-retry backoff slept, by kind (KARPENTER_TPU_WATCH_BACKOFF_{BASE,MAX}_MS)",
            ["kind"],
        )
        r.register(_TracerOrphanCollector())
        self.serving_stage_duration = r.histogram(
            f"{ns}_serving_stage_duration_seconds",
            "Serving pipeline stage wall time (batch_wait | plan)",
            labels=["stage"],
        )
        self.serving_queue_depth = r.gauge(
            f"{ns}_serving_queue_depth",
            "Serving pipeline stage-queue depth (caps are env-tunable, KARPENTER_TPU_SERVING_*_CAP)",
            ["stage"],
        )
        # fleet solver (fleet/): per-tenant solve traffic (tenant label
        # cardinality-capped, KARPENTER_TPU_FLEET_TENANT_LABELS — excess
        # tenants collapse to "_other"), mega-dispatch shape, and the
        # deficit-round-robin fairness pressure
        self.fleet_solves = r.counter(
            f"{ns}_tpu_fleet_solves_total",
            "Per-tenant fleet solves, by engine (batched | solo); tenant label capped",
            ["tenant", "engine"],
        )
        self.fleet_pods = r.counter(
            f"{ns}_tpu_fleet_pods_total",
            "Pods decided per tenant by the fleet engine; tenant label capped",
            ["tenant"],
        )
        self.fleet_batch_occupancy = r.gauge(
            f"{ns}_tpu_fleet_batch_occupancy",
            "Tenant pack calls coalesced into the last mega-dispatch flush",
        )
        self.fleet_padding_waste = r.gauge(
            f"{ns}_tpu_fleet_padding_waste",
            "Padded pod-slot fraction wasted by the last round's mega-dispatch size classes",
        )
        self.fleet_fairness_deficit = r.gauge(
            f"{ns}_tpu_fleet_fairness_deficit",
            "Largest per-tenant deficit-round-robin backlog credit after the last round",
        )
        self.fleet_decision_latency = r.histogram(
            f"{ns}_tpu_fleet_decision_latency_seconds",
            "Fleet pod-pending to plan-emitted decision latency, all tenants; buckets env-tunable via KARPENTER_TPU_LATENCY_BUCKETS_MS",
            buckets=latency_buckets(),
        )
        self.fleet_round_duration = r.histogram(
            f"{ns}_tpu_fleet_round_duration_seconds",
            "Fleet round wall time, by engine",
            labels=["engine"],
        )
        # node/nodepool/pod scrapers (metrics/{node,nodepool,pod})
        self.node_allocatable = r.gauge(f"{ns}_nodes_allocatable", "Node allocatable", ["node", "resource"])
        self.node_pod_requests = r.gauge(f"{ns}_nodes_total_pod_requests", "Node pod requests", ["node", "resource"])
        self.node_pod_limits = r.gauge(f"{ns}_nodes_total_pod_limits", "Node pod limits", ["node", "resource"])
        self.node_daemon_requests = r.gauge(f"{ns}_nodes_total_daemon_requests", "Node daemon requests", ["node", "resource"])
        self.node_daemon_limits = r.gauge(f"{ns}_nodes_total_daemon_limits", "Node daemon limits", ["node", "resource"])
        self.node_system_overhead = r.gauge(f"{ns}_nodes_system_overhead", "Node system overhead", ["node", "resource"])
        self.nodepool_limit = r.gauge(f"{ns}_nodepool_limit", "NodePool limit", ["nodepool", "resource"])
        self.nodepool_usage = r.gauge(f"{ns}_nodepool_usage", "NodePool usage", ["nodepool", "resource"])
        self.pod_state = r.gauge(f"{ns}_pods_state", "Pod state", ["name", "namespace", "phase"])
        self.pod_startup_time = r.histogram(f"{ns}_pods_startup_time_seconds", "Pod startup time")
        self.reconcile_duration = r.histogram(
            f"{ns}_controller_reconcile_duration_seconds", "Controller reconcile duration", labels=["controller"]
        )
        self.reconcile_errors = r.counter(
            f"{ns}_controller_reconcile_errors_total", "Controller reconcile errors", ["controller"]
        )
