"""A small Prometheus text-exposition-format checker, used by tier-1
tests to fail fast on metric-surface regressions (ISSUE 1 satellite:
HELP/TYPE pairing, label escaping, histogram bucket monotonicity).

This is deliberately a *checker*, not a parser-for-use: it validates the
subset of the format Registry.expose() emits (text format 0.0.4, no
exemplars/OM extensions) and returns human-readable problem strings.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# sample line: name{labels} value [timestamp]
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)(?:\s+(?P<ts>-?\d+))?$"
)

_VALID_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}


def _parse_labels(raw: str) -> Tuple[Optional[Dict[str, str]], Optional[str]]:
    """Parse a label body (the text between { and }) → (labels, error).
    Hand-rolled scanner so unescaped quotes/backslashes are *detected*
    rather than silently accepted."""
    labels: Dict[str, str] = {}
    i, n = 0, len(raw)
    while i < n:
        eq = raw.find("=", i)
        if eq < 0:
            return None, f"missing '=' in label body at offset {i}"
        name = raw[i:eq]
        if not _LABEL_NAME_RE.match(name):
            return None, f"bad label name {name!r}"
        if eq + 1 >= n or raw[eq + 1] != '"':
            return None, f"label {name!r} value not quoted"
        j = eq + 2
        value_chars: List[str] = []
        while j < n:
            c = raw[j]
            if c == "\\":
                if j + 1 >= n:
                    return None, f"label {name!r} has trailing backslash"
                esc = raw[j + 1]
                if esc not in ('"', "\\", "n"):
                    return None, f"label {name!r} has invalid escape \\{esc}"
                value_chars.append("\n" if esc == "n" else esc)
                j += 2
                continue
            if c == '"':
                break
            if c == "\n":
                return None, f"label {name!r} value contains raw newline"
            value_chars.append(c)
            j += 1
        else:
            return None, f"label {name!r} value unterminated"
        if name in labels:
            return None, f"duplicate label name {name!r}"
        labels[name] = "".join(value_chars)
        i = j + 1
        if i < n:
            if raw[i] != ",":
                return None, f"expected ',' after label {name!r}"
            i += 1
    return labels, None


def _family_of(sample_name: str, typed: Dict[str, str]) -> Optional[str]:
    """Map a sample name to its declared family, honoring histogram /
    summary suffixes."""
    if sample_name in typed:
        return sample_name
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if typed.get(base) in ("histogram", "summary"):
                return base
    return None


def check_exposition(text: str) -> List[str]:
    """Validate a /metrics payload; returns a list of problems (empty ⇒
    well-formed). Checks:

    - HELP/TYPE lines are well-formed, at most one of each per family,
      and TYPE precedes that family's samples
    - every sample belongs to a declared family (histogram suffixes
      resolved), names/labels are legal, label values legally escaped
    - sample values parse as floats ("+Inf"/"-Inf"/"NaN" allowed)
    - no duplicate (name, labels) series
    - per histogram series: ``le`` buckets are cumulative-monotone in
      ascending ``le`` order, the +Inf bucket exists and equals _count
    """
    problems: List[str] = []
    typed: Dict[str, str] = {}
    helped: Dict[str, bool] = {}
    seen_sample_of: Dict[str, bool] = {}
    series_seen: set = set()
    # histogram family → series key → list of (le, count); counts keyed
    # off the non-le label set
    buckets: Dict[str, Dict[tuple, List[Tuple[float, float]]]] = {}
    counts: Dict[str, Dict[tuple, float]] = {}

    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                if parts[1:2] and parts[1] in ("HELP", "TYPE"):
                    problems.append(f"line {ln}: malformed {parts[1]} line")
                continue  # arbitrary comments are legal
            kind, name = parts[1], parts[2]
            if not _NAME_RE.match(name):
                problems.append(f"line {ln}: bad metric name {name!r} in {kind}")
                continue
            if kind == "HELP":
                if helped.get(name):
                    problems.append(f"line {ln}: duplicate HELP for {name}")
                helped[name] = True
            else:
                t = parts[3].strip() if len(parts) > 3 else ""
                if t not in _VALID_TYPES:
                    problems.append(f"line {ln}: invalid TYPE {t!r} for {name}")
                if name in typed:
                    problems.append(f"line {ln}: duplicate TYPE for {name}")
                if seen_sample_of.get(name):
                    problems.append(
                        f"line {ln}: TYPE for {name} appears after its samples"
                    )
                typed[name] = t
            continue

        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {ln}: unparseable sample line {line!r}")
            continue
        name = m.group("name")
        family = _family_of(name, typed)
        if family is None:
            problems.append(f"line {ln}: sample {name} has no preceding TYPE")
            family = name
        seen_sample_of[family] = True
        if not helped.get(family):
            problems.append(f"line {ln}: sample {name} has no HELP for {family}")
            helped[family] = True  # report once per family
        labels: Dict[str, str] = {}
        if m.group("labels") is not None:
            labels, err = _parse_labels(m.group("labels"))
            if err is not None:
                problems.append(f"line {ln}: {err}")
                continue
        raw_value = m.group("value")
        try:
            value = float(raw_value)
        except ValueError:
            problems.append(f"line {ln}: unparseable value {raw_value!r}")
            continue
        series = (name, tuple(sorted(labels.items())))
        if series in series_seen:
            problems.append(f"line {ln}: duplicate series {name}{dict(labels)}")
        series_seen.add(series)

        if typed.get(family) == "histogram":
            base_labels = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            if name == family + "_bucket":
                le_raw = labels.get("le")
                if le_raw is None:
                    problems.append(f"line {ln}: bucket sample without le label")
                    continue
                try:
                    le = float(le_raw)
                except ValueError:
                    problems.append(f"line {ln}: unparseable le {le_raw!r}")
                    continue
                buckets.setdefault(family, {}).setdefault(base_labels, []).append(
                    (le, value)
                )
            elif name == family + "_count":
                counts.setdefault(family, {})[base_labels] = value

    for family, by_series in buckets.items():
        for base_labels, pairs in by_series.items():
            pairs.sort(key=lambda p: p[0])
            label_str = dict(base_labels) or ""
            last = -math.inf
            for le, count in pairs:
                if count < last:
                    problems.append(
                        f"{family}{label_str}: bucket le={le} count {count} < "
                        f"previous bucket's {last} (not cumulative)"
                    )
                last = count
            if not pairs or not math.isinf(pairs[-1][0]):
                problems.append(f"{family}{label_str}: missing +Inf bucket")
            else:
                total = counts.get(family, {}).get(base_labels)
                if total is not None and pairs[-1][1] != total:
                    problems.append(
                        f"{family}{label_str}: +Inf bucket {pairs[-1][1]} != "
                        f"_count {total}"
                    )
    return problems
