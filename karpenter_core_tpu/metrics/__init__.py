from .registry import Counter, Gauge, Histogram, Registry, Metrics
from .store import MetricsStore
from .textcheck import check_exposition
