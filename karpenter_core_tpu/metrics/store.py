"""Dedup-publishing metrics store + scrapers (ref pkg/metrics/store.go,
pkg/controllers/metrics/{node,nodepool,pod})."""

from __future__ import annotations

from typing import Dict, Set

from ..kube.quantity import NANO
from ..scheduling import resources
from .registry import Metrics


class MetricsStore:
    """store.go:32: tracks which label sets were published so stale series
    are deleted when objects disappear."""

    def __init__(self, metrics: Metrics):
        self.metrics = metrics
        self._published_nodes: Set[str] = set()
        self._published_pools: Set[str] = set()
        self._published_pods: Set[tuple] = set()
        self._startup_observed: Set[tuple] = set()

    # -- node scraper (metrics/node/controller.go:48-96) -------------------

    def scrape_nodes(self, cluster) -> None:
        seen = set()

        def visit(sn) -> bool:
            name = sn.name()
            seen.add(name)
            for res, qty in sn.allocatable().items():
                self.metrics.node_allocatable.set(qty / NANO, node=name, resource=res)
            for res, qty in sn.pod_request_total().items():
                self.metrics.node_pod_requests.set(qty / NANO, node=name, resource=res)
            for res, qty in sn.pod_limit_total().items():
                self.metrics.node_pod_limits.set(qty / NANO, node=name, resource=res)
            for res, qty in sn.daemonset_request_total().items():
                self.metrics.node_daemon_requests.set(qty / NANO, node=name, resource=res)
            for res, qty in sn.daemonset_limit_total().items():
                self.metrics.node_daemon_limits.set(qty / NANO, node=name, resource=res)
            overhead = resources.subtract(sn.capacity(), sn.allocatable())
            for res, qty in overhead.items():
                self.metrics.node_system_overhead.set(qty / NANO, node=name, resource=res)
            return True

        cluster.for_each_node(visit)
        for stale in self._published_nodes - seen:
            for gauge in (
                self.metrics.node_allocatable,
                self.metrics.node_pod_requests,
                self.metrics.node_pod_limits,
                self.metrics.node_daemon_requests,
                self.metrics.node_daemon_limits,
                self.metrics.node_system_overhead,
            ):
                for key in [k for k in gauge.values if ("node", stale) in k]:
                    gauge.values.pop(key, None)
        self._published_nodes = seen

    # -- nodepool scraper (metrics/nodepool/controller.go:49-64) -----------

    def scrape_nodepools(self, kube_client) -> None:
        seen = set()
        for np_ in kube_client.list("NodePool"):
            seen.add(np_.name)
            for res, qty in np_.spec.limits.items():
                self.metrics.nodepool_limit.set(qty / NANO, nodepool=np_.name, resource=res)
            for res, qty in np_.status.resources.items():
                self.metrics.nodepool_usage.set(qty / NANO, nodepool=np_.name, resource=res)
        for stale in self._published_pools - seen:
            for gauge in (self.metrics.nodepool_limit, self.metrics.nodepool_usage):
                for key in [k for k in gauge.values if ("nodepool", stale) in k]:
                    gauge.values.pop(key, None)
        self._published_pools = seen

    # -- pod scraper (metrics/pod/controller.go:59-71) ---------------------

    def scrape_pods(self, kube_client) -> None:
        seen = set()
        for pod in kube_client.list("Pod"):
            key = (pod.namespace, pod.name)
            seen.add(key)
            # a pod is in exactly one phase: drop the series for any phase
            # it moved out of, or a Pending→Running pod reports both
            for k in [
                k
                for k in self.metrics.pod_state.values
                if ("name", pod.name) in k
                and ("namespace", pod.namespace) in k
                and ("phase", pod.status.phase) not in k
            ]:
                self.metrics.pod_state.values.pop(k, None)
            self.metrics.pod_state.set(
                1.0, name=pod.name, namespace=pod.namespace, phase=pod.status.phase
            )
            # startup = creation → Running, observed once per pod
            # (metrics/pod/controller.go:63-71 pod_startup_time_seconds)
            if (
                pod.status.phase == "Running"
                and pod.status.start_time is not None
                and key not in self._startup_observed
            ):
                self._startup_observed.add(key)
                self.metrics.pod_startup_time.observe(
                    max(0.0, pod.status.start_time - pod.metadata.creation_timestamp)
                )
        for stale in self._published_pods - seen:
            for k in [
                k
                for k in self.metrics.pod_state.values
                if ("name", stale[1]) in k and ("namespace", stale[0]) in k
            ]:
                self.metrics.pod_state.values.pop(k, None)
        self._published_pods = seen
        # prune so deleted pods don't leak, and a recreated same-name pod
        # gets its startup observed again
        self._startup_observed &= seen
