"""Requirements: a keyed map of Requirement with karpenter's compatibility
rules (ref pkg/scheduling/requirements.go)."""

from __future__ import annotations

import functools
from typing import AbstractSet, Dict, Iterable, List, Optional

from ..apis import labels as wk
from ..kube.objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    Pod,
)
from .requirement import Requirement


class Requirements(Dict[str, Requirement]):
    """dict[key → Requirement]; Add intersects on duplicate keys
    (requirements.go:118)."""

    def __init__(self, *requirements: Requirement):
        super().__init__()
        self._fp = None
        self.add(*requirements)

    def __setitem__(self, key: str, value: Requirement) -> None:
        self._fp = None  # any write invalidates the cached fingerprint
        super().__setitem__(key, value)

    def __delitem__(self, key: str) -> None:
        self._fp = None
        super().__delitem__(key)

    def pop(self, key: str, *args):
        self._fp = None
        return super().pop(key, *args)

    # dict's C implementations of these bypass __setitem__ on subclasses —
    # override them all so no mutation path can serve a stale fingerprint
    def update(self, *args, **kwargs):
        self._fp = None
        super().update(*args, **kwargs)

    def setdefault(self, key: str, default=None):
        self._fp = None
        return super().setdefault(key, default)

    def clear(self) -> None:
        self._fp = None
        super().clear()

    def popitem(self):
        self._fp = None
        return super().popitem()

    def __ior__(self, other):
        self._fp = None
        return super().__ior__(other)

    def add(self, *requirements: Requirement) -> None:
        for req in requirements:
            existing = super().get(req.key)
            if existing is not None:
                req = req.intersection(existing)
            self[req.key] = req

    def fingerprint(self) -> tuple:
        """Canonical, hashable identity of the full requirement set
        (operator polarity, values, Gt/Lt bounds). Cached until the next
        write. Requirement objects are mostly immutable (intersection/
        copy return new instances) but ``Requirement.insert`` mutates
        ``values`` in place — the cheap (key count, value count) guard
        recomputes when one fires after caching. A same-count value
        *replacement* would evade the guard; nothing in the codebase
        does that."""
        guard = (len(self), sum(len(r.values) for r in self.values()))
        cached = self._fp
        if cached is not None and cached[0] == guard:
            return cached[1]
        fp = tuple(
            sorted(
                (r.key, r.complement, tuple(sorted(r.values)), r.greater_than, r.less_than)
                for r in self.values()
            )
        )
        self._fp = (guard, fp)
        return fp

    def fingerprint_digest(self) -> bytes:
        """Process-stable 128-bit digest of ``fingerprint()``, cached on
        the same write-invalidated slot (``self._fp`` rides a 3-tuple
        when the digest has been materialized). Hot fingerprint
        consumers (the per-solve catalog content check) feed this digest
        instead of re-walking the nested fingerprint tuple per call."""
        fp = self.fingerprint()  # revalidates/refreshes self._fp
        cached = self._fp
        if len(cached) == 3:
            return cached[2]
        from ..solver.stablehash import stable_hash

        digest = stable_hash(fp)
        self._fp = (cached[0], fp, digest)
        return digest

    def keys_set(self) -> frozenset:
        return frozenset(self.keys())

    def has(self, key: str) -> bool:
        return key in self

    def get_req(self, key: str) -> Requirement:
        """Missing keys behave as Exists (requirements.go:145)."""
        req = super().get(key)
        if req is None:
            return Requirement(key, OP_EXISTS)
        return req

    def values_list(self) -> List[Requirement]:
        return list(self.values())

    def copy(self) -> "Requirements":
        out = Requirements()
        for k, v in self.items():
            dict.__setitem__(out, k, v.copy())
        return out

    # -- compatibility (requirements.go:163-258) ---------------------------

    def compatible(
        self,
        incoming: "Requirements",
        allow_undefined: AbstractSet[str] = frozenset(),
        hint: bool = True,
    ) -> Optional[str]:
        """None if compatible, else an error string.

        Custom labels must intersect, and are denied when undefined on the
        receiver; labels in ``allow_undefined`` (well-known) must intersect
        only when defined. Mirrors Compatible + AllowUndefinedWellKnownLabels.
        ``hint=False`` skips the typo-hint edit-distance scan — for
        boolean screens that discard the error string.
        """
        errs = []
        for key in incoming.keys_set() - allow_undefined:
            if key in self:
                continue
            op = incoming.get_req(key).operator()
            if op in (OP_NOT_IN, OP_DOES_NOT_EXIST):
                continue
            suggestion = _label_hint(self, key, allow_undefined) if hint else ""
            errs.append(f'label "{key}" does not have known values{suggestion}')
        err = self.intersects(incoming)
        if err:
            errs.append(err)
        return "; ".join(errs) if errs else None

    def intersects(self, incoming: "Requirements") -> Optional[str]:
        """Error string unless all shared keys have overlapping values
        (requirements.go:241), with the NotIn/DoesNotExist carve-out."""
        errs = []
        for key in self.keys_set() & incoming.keys_set():
            existing = self.get_req(key)
            inc = incoming.get_req(key)
            if existing.intersection(inc).len() == 0:
                if inc.operator() in (OP_NOT_IN, OP_DOES_NOT_EXIST) and existing.operator() in (
                    OP_NOT_IN,
                    OP_DOES_NOT_EXIST,
                ):
                    continue
                errs.append(f"key {key}, {inc!r} not in {existing!r}")
        return "; ".join(errs) if errs else None

    def labels(self) -> Dict[str, str]:
        """Representative labels for launching (requirements.go:260)."""
        out = {}
        for key, req in self.items():
            if not wk.is_restricted_node_label(key):
                value = req.any()
                if value:
                    out[key] = value
        return out

    def __repr__(self) -> str:
        reqs = [repr(r) for k, r in self.items() if k not in wk.RESTRICTED_LABELS]
        return ", ".join(sorted(reqs))


def _edit_distance(s: str, t: str) -> int:
    """Levenshtein distance (same DP as requirements.go:177-209, including
    its quirk of ignoring index 0 — kept so hint thresholds agree)."""
    m, n = len(s), len(t)
    if m == 0:
        return n
    if n == 0:
        return m
    prev = list(range(n))
    prev[0] = 0
    cur = [0] * n
    for i in range(1, m):
        for j in range(1, n):
            diff = 0 if s[i] == t[j] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + diff)
        prev, cur = cur, prev
    return prev[n - 1]


def _suffix(key: str) -> str:
    _, sep, after = key.partition("/")
    return after if sep else key


@functools.lru_cache(maxsize=4096)
def _cached_hint(key: str, allow_undefined: frozenset, existing_keys: frozenset) -> str:
    # deliberate divergence from the Go: a key ending in "/" has an empty
    # suffix, which would endswith-match an arbitrary candidate
    suffix = _suffix(key)
    for pool in (allow_undefined, existing_keys):
        for candidate in pool:
            if key in candidate or _edit_distance(key, candidate) < len(candidate) // 5:
                return f' (typo of "{candidate}"?)'
            if suffix and candidate.endswith(suffix):
                return f' (typo of "{candidate}"?)'
    return ""


def _label_hint(existing: "Requirements", key: str, allow_undefined: AbstractSet[str]) -> str:
    """' (typo of "…"?)' when the unknown label is plausibly a typo of a
    well-known or already-defined label (requirements.go:216-233).
    Memoized — scheduling simulation retries the same miss thousands of
    times per solve, and the edit-distance sweep is the expensive part."""
    return _cached_hint(key, frozenset(allow_undefined), existing.keys_set())


# the live well-known set (providers may extend it at import time)
ALLOW_UNDEFINED_WELL_KNOWN_LABELS = wk.WELL_KNOWN_LABELS


def label_requirements(labels: Dict[str, str]) -> Requirements:
    """Labels → In-requirements (requirements.go:56)."""
    return Requirements(*(Requirement(k, OP_IN, [v]) for k, v in labels.items()))


def node_selector_requirements(reqs) -> Requirements:
    return Requirements(*(Requirement(r.key, r.operator, r.values) for r in reqs))


def _pod_requirements(pod: Pod, include_preferred: bool) -> Requirements:
    """Pod → requirements: nodeSelector + first required node-affinity term
    (+ heaviest preference when included). Ref requirements.go:81-101."""
    requirements = label_requirements(pod.spec.node_selector)
    aff = pod.spec.affinity
    if aff is None or aff.node_affinity is None:
        return requirements
    na = aff.node_affinity
    if include_preferred and na.preferred:
        heaviest = max(na.preferred, key=lambda t: t.weight)
        requirements.add(
            *node_selector_requirements(heaviest.preference.match_expressions).values_list()
        )
    if na.required is not None and na.required.node_selector_terms:
        requirements.add(
            *node_selector_requirements(
                na.required.node_selector_terms[0].match_expressions
            ).values_list()
        )
    return requirements


def pod_requirements(pod: Pod) -> Requirements:
    """Preferred treated as required; relaxed by the outer loop
    (requirements.go:65 NewPodRequirements)."""
    return _pod_requirements(pod, include_preferred=True)


def strict_pod_requirements(pod: Pod) -> Requirements:
    """Only true requirements (requirements.go:70 NewStrictPodRequirements)."""
    return _pod_requirements(pod, include_preferred=False)


def has_preferred_node_affinity(pod: Pod) -> bool:
    return (
        pod.spec.affinity is not None
        and pod.spec.affinity.node_affinity is not None
        and len(pod.spec.affinity.node_affinity.preferred) > 0
    )
