"""Host-port conflict tracking (ref pkg/scheduling/hostportusage.go)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..kube.objects import Pod

UNSPECIFIED = ("0.0.0.0", "::")


@dataclass(frozen=True)
class HostPort:
    ip: str
    port: int
    protocol: str

    def matches(self, rhs: "HostPort") -> bool:
        """Same proto+port; IPs conflict if equal or either is unspecified
        (hostportusage.go:49)."""
        if self.protocol != rhs.protocol or self.port != rhs.port:
            return False
        return self.ip == rhs.ip or self.ip in UNSPECIFIED or rhs.ip in UNSPECIFIED

    def __str__(self) -> str:
        return f"IP={self.ip} Port={self.port} Proto={self.protocol}"


def get_host_ports(pod: Pod) -> List[HostPort]:
    """Extract HostPorts from containers; empty hostIP defaults to 0.0.0.0
    (hostportusage.go:93 GetHostPorts)."""
    usage = []
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port == 0:
                continue
            usage.append(HostPort(ip=p.host_ip or "0.0.0.0", port=p.host_port, protocol=p.protocol))
    return usage


class HostPortUsage:
    """Per-node reservation map keyed by pod (hostportusage.go:34)."""

    def __init__(self) -> None:
        self.reserved: Dict[Tuple[str, str], List[HostPort]] = {}

    def add(self, pod: Pod, ports: List[HostPort]) -> None:
        self.reserved[(pod.namespace, pod.name)] = ports

    def conflicts(self, pod: Pod, ports: List[HostPort]) -> Optional[str]:
        key = (pod.namespace, pod.name)
        for new_entry in ports:
            for pod_key, entries in self.reserved.items():
                if pod_key == key:
                    continue
                for existing in entries:
                    if new_entry.matches(existing):
                        return f"{new_entry} conflicts with existing HostPort configuration {existing}"
        return None

    def delete_pod(self, namespace: str, name: str) -> None:
        self.reserved.pop((namespace, name), None)

    def copy(self) -> "HostPortUsage":
        out = HostPortUsage()
        # flat copy sharing the port lists: add() assigns a key's list
        # whole and nothing appends in place, so per-entry list copies
        # were pure cost (the hottest line of StateNode.deep_copy at
        # 100 pods/node before ISSUE 7)
        out.reserved = dict(self.reserved)
        return out
