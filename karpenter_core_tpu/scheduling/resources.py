"""Resource-list arithmetic (ref pkg/utils/resources/resources.go).

ResourceLists are plain ``dict[str, int]`` in integer nanos (see
``kube.quantity``): exact, fast, and trivially serialized to the TPU
tensorization layer.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

from ..kube.objects import Container, Pod, ResourceList, RESOURCE_PODS
from ..kube.quantity import NANO


def merge(*lists: ResourceList) -> ResourceList:
    """Sum resource lists (resources.go:49 Merge)."""
    result: ResourceList = {}
    for rl in lists:
        for name, qty in rl.items():
            result[name] = result.get(name, 0) + qty
    return result


def subtract(lhs: ResourceList, rhs: ResourceList) -> ResourceList:
    """lhs - rhs over lhs's keys (resources.go:83 Subtract)."""
    return {name: qty - rhs.get(name, 0) for name, qty in lhs.items()}


def max_resources(*lists: ResourceList) -> ResourceList:
    """Element-wise max (resources.go:116 MaxResources)."""
    result: ResourceList = {}
    for rl in lists:
        for name, qty in rl.items():
            if name not in result or qty > result[name]:
                result[name] = qty
    return result


def fits(candidate: ResourceList, total: ResourceList) -> bool:
    """candidate ≤ total element-wise; negative totals never fit
    (resources.go:162 Fits)."""
    for qty in total.values():
        if qty < 0:
            return False
    for name, qty in candidate.items():
        if qty > total.get(name, 0):
            return False
    return True


def merge_limits_into_requests(container: Container) -> ResourceList:
    """Limits become requests when requests are unset (resources.go:129)."""
    requests = dict(container.resources.requests)
    for name, qty in container.resources.limits.items():
        requests.setdefault(name, qty)
    return requests


def ceiling(pod: Pod) -> ResourceList:
    """Effective pod requests: sum of containers, max'd with each init
    container, plus overhead (resources.go:99 Ceiling, requests side)."""
    requests: ResourceList = {}
    for c in pod.spec.containers:
        requests = merge(requests, merge_limits_into_requests(c))
    for c in pod.spec.init_containers:
        requests = max_resources(requests, merge_limits_into_requests(c))
    if pod.spec.overhead:
        requests = merge(requests, pod.spec.overhead)
    return requests


def limits_ceiling(pod: Pod) -> ResourceList:
    limits: ResourceList = {}
    for c in pod.spec.containers:
        limits = merge(limits, c.resources.limits)
    for c in pod.spec.init_containers:
        limits = max_resources(limits, c.resources.limits)
    return limits


def requests_for_pods(*pods: Pod) -> ResourceList:
    """Total requests incl. an implicit "pods" count (resources.go:27)."""
    if len(pods) == 1:
        # hot path: single pod, single plain container (the overwhelmingly
        # common shape on the 50k-pod solve path)
        p = pods[0]
        spec = p.spec
        if len(spec.containers) == 1 and not spec.init_containers and not spec.overhead:
            c = spec.containers[0]
            if not c.resources.limits:
                merged = dict(c.resources.requests)
                merged[RESOURCE_PODS] = NANO
                return merged
    merged = merge(*(ceiling(p) for p in pods))
    merged[RESOURCE_PODS] = len(pods) * NANO
    return merged


def limits_for_pods(*pods: Pod) -> ResourceList:
    merged = merge(*(limits_ceiling(p) for p in pods))
    merged[RESOURCE_PODS] = len(pods) * NANO
    return merged


def cmp(lhs: int, rhs: int) -> int:
    return (lhs > rhs) - (lhs < rhs)


def is_zero(rl: ResourceList) -> bool:
    return all(v == 0 for v in rl.values())


def to_string(rl: ResourceList) -> str:
    from ..kube.quantity import format_quantity

    if not rl:
        return "{}"
    return "{" + ", ".join(f"{k}: {format_quantity(v)}" for k, v in sorted(rl.items())) + "}"
