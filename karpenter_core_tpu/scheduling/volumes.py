"""CSI volume counting against per-driver limits (ref
pkg/scheduling/volumeusage.go, storageclass.go).

The reference resolves a pod's PVCs → storage class → CSI driver, then
counts mounted volumes per driver against the node's reported CSI limit.
We keep the same resolution chain against our in-memory kube store.
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from ..kube.objects import Pod

DEFAULT_STORAGE_CLASS_ANNOTATION = "storageclass.kubernetes.io/is-default-class"


class Volumes(Dict[str, Set[str]]):
    """driver name → set of pvc ids (volumeusage.go:40)."""

    def add(self, provisioner: str, pvc_id: str) -> None:
        self.setdefault(provisioner, set()).add(pvc_id)

    def union(self, other: "Volumes") -> "Volumes":
        out = Volumes()
        for k, v in self.items():
            out[k] = set(v)
        for k, v in other.items():
            out.setdefault(k, set()).update(v)
        return out

    def insert(self, other: "Volumes") -> None:
        for k, v in other.items():
            self.setdefault(k, set()).update(v)


def get_volumes(kube_client, pod: Pod) -> Volumes:
    """Resolve the pod's PVC-backed volumes to CSI drivers
    (volumeusage.go:79 GetVolumes)."""
    vols = Volumes()
    default_sc = _default_storage_class(kube_client)
    for volume in pod.spec.volumes:
        if volume.persistent_volume_claim:
            pvc = kube_client.get("PersistentVolumeClaim", volume.persistent_volume_claim, namespace=pod.namespace)
            if pvc is None:
                raise KeyError(f"pvc {pod.namespace}/{volume.persistent_volume_claim} not found")
            pvc_id = f"{pod.namespace}/{volume.persistent_volume_claim}"
            sc_name = pvc.storage_class_name or default_sc
            volume_name = pvc.volume_name
        elif volume.ephemeral:
            # https://kubernetes.io/docs/concepts/storage/ephemeral-volumes/#persistentvolumeclaim-naming
            pvc_id = f"{pod.namespace}/{pod.name}-{volume.name}"
            sc_name = default_sc
            volume_name = ""
        else:
            continue
        driver = _resolve_driver(kube_client, volume_name, sc_name)
        if driver:
            vols.add(driver, pvc_id)
    return vols


def _default_storage_class(kube_client) -> Optional[str]:
    for sc in kube_client.list("StorageClass"):
        if sc.metadata.annotations.get(DEFAULT_STORAGE_CLASS_ANNOTATION) == "true":
            return sc.name
    return None


def _resolve_driver(kube_client, volume_name: str, storage_class_name: Optional[str]) -> str:
    """Bound PV's driver wins, else the storage class provisioner
    (volumeusage.go:121-160 resolveDriver)."""
    if volume_name:
        pv = kube_client.get("PersistentVolume", volume_name)
        if pv is not None and pv.driver:
            return pv.driver
    if storage_class_name:
        sc = kube_client.get("StorageClass", storage_class_name)
        if sc is not None:
            return sc.provisioner
    return ""


class VolumeUsage:
    """Per-node mounted-volume tracking vs CSI limits (volumeusage.go:170+)."""

    def __init__(self, csi_limits: Optional[Dict[str, int]] = None) -> None:
        self.volumes = Volumes()
        self.pod_volumes: Dict[tuple, Volumes] = {}
        self.csi_limits = csi_limits or {}

    def add(self, pod: Pod, volumes: Volumes) -> None:
        self.pod_volumes[(pod.namespace, pod.name)] = volumes
        self.volumes.insert(volumes)

    def exceeds_limits(self, volumes: Volumes) -> Optional[str]:
        """Error string if mounting `volumes` would pass a driver limit."""
        would_be = self.volumes.union(volumes)
        for driver, vols in would_be.items():
            limit = self.csi_limits.get(driver)
            if limit is not None and len(vols) > limit:
                return f"would exceed volume limit for CSI driver {driver}, {len(vols)} > {limit}"
        return None

    def delete_pod(self, namespace: str, name: str) -> None:
        self.pod_volumes.pop((namespace, name), None)
        rebuilt = Volumes()
        for v in self.pod_volumes.values():
            rebuilt.insert(v)
        self.volumes = rebuilt

    def copy(self) -> "VolumeUsage":
        out = VolumeUsage(dict(self.csi_limits))
        # share the per-pod Volumes values (add() assigns them whole and
        # insert/union only read them); the aggregate is rebuilt fresh
        # because insert() mutates its sets in place
        out.pod_volumes = dict(self.pod_volumes)
        for v in out.pod_volumes.values():
            out.volumes.insert(v)
        return out
