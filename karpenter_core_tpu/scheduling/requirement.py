"""Requirement: set algebra over node-selector operators.

Faithful re-expression of the reference's complement-set representation
(ref pkg/scheduling/requirement.go:33-39): a requirement is either a
concrete value set (``complement=False``; In / DoesNotExist) or the
complement of one (``complement=True``; NotIn / Exists / Gt / Lt), with
optional integer bounds. This is also the semantic contract for the TPU
mask encoding in ``solver.encode`` — each requirement lowers to a
boolean mask over a per-key value vocabulary plus an "all other values"
slot standing in for the complement's unseen values.
"""

from __future__ import annotations

import random
from typing import Iterable, List, Optional, Set

from ..apis import labels as wk
from ..kube.objects import (
    NodeSelectorRequirement,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
)

# stands in for the reference's math.MaxInt64 cardinality of complement sets
INFINITE = 1 << 62


class Requirement:
    """One per-key constraint (requirement.go:33)."""

    __slots__ = ("key", "complement", "values", "greater_than", "less_than")

    def __init__(self, key: str, operator: str, values: Iterable[str] = ()):  # noqa: C901
        self.key = wk.NORMALIZED_LABELS.get(key, key)
        values = list(values)
        self.greater_than: Optional[int] = None
        self.less_than: Optional[int] = None
        if operator == OP_IN:
            self.complement = False
            self.values: Set[str] = set(values)
            return
        self.complement = operator != OP_DOES_NOT_EXIST
        self.values = set(values) if operator == OP_NOT_IN else set()
        if operator == OP_GT:
            self.greater_than = int(values[0])
        elif operator == OP_LT:
            self.less_than = int(values[0])

    # -- constructors ------------------------------------------------------

    @classmethod
    def _raw(
        cls,
        key: str,
        complement: bool,
        values: Set[str],
        greater_than: Optional[int] = None,
        less_than: Optional[int] = None,
    ) -> "Requirement":
        r = cls.__new__(cls)
        r.key = key
        r.complement = complement
        r.values = values
        r.greater_than = greater_than
        r.less_than = less_than
        return r

    # -- algebra (requirement.go:128-161) ----------------------------------

    def intersection(self, other: "Requirement") -> "Requirement":
        complement = self.complement and other.complement
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return Requirement(self.key, OP_DOES_NOT_EXIST)

        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement and not other.complement:
            values = other.values - self.values
        elif not self.complement and other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = {v for v in values if _within(v, greater_than, less_than)}
        if not complement:
            greater_than, less_than = None, None
        return Requirement._raw(self.key, complement, values, greater_than, less_than)

    def has(self, value: str) -> bool:
        """True if the requirement allows the value (requirement.go:182)."""
        if self.complement:
            return value not in self.values and _within(value, self.greater_than, self.less_than)
        return value in self.values and _within(value, self.greater_than, self.less_than)

    def any(self) -> str:
        """A representative allowed value (requirement.go:163). Random for
        complement sets, like the reference."""
        op = self.operator()
        if op == OP_IN:
            return next(iter(self.values))
        if op in (OP_NOT_IN, OP_EXISTS):
            lo_ = 0 if self.greater_than is None else self.greater_than + 1
            hi = (1 << 63) - 1 if self.less_than is None else self.less_than
            return str(random.randrange(lo_, hi))
        return ""

    def insert(self, *items: str) -> None:
        self.values.update(items)

    def operator(self) -> str:
        if self.complement:
            return OP_NOT_IN if self.values else OP_EXISTS
        return OP_IN if self.values else OP_DOES_NOT_EXIST

    def len(self) -> int:
        """Cardinality; complement sets are 'infinite' (requirement.go:210)."""
        if self.complement:
            return INFINITE - len(self.values)
        return len(self.values)

    def min_values(self) -> List[str]:
        return sorted(self.values)

    def to_node_selector_requirement(self) -> NodeSelectorRequirement:
        """Round-trip back to the API shape (requirement.go:81)."""
        if self.greater_than is not None:
            return NodeSelectorRequirement(self.key, OP_GT, [str(self.greater_than)])
        if self.less_than is not None:
            return NodeSelectorRequirement(self.key, OP_LT, [str(self.less_than)])
        if self.complement:
            if self.values:
                return NodeSelectorRequirement(self.key, OP_NOT_IN, sorted(self.values))
            return NodeSelectorRequirement(self.key, OP_EXISTS, [])
        if self.values:
            return NodeSelectorRequirement(self.key, OP_IN, sorted(self.values))
        return NodeSelectorRequirement(self.key, OP_DOES_NOT_EXIST, [])

    def copy(self) -> "Requirement":
        return Requirement._raw(self.key, self.complement, set(self.values), self.greater_than, self.less_than)

    def __repr__(self) -> str:
        op = self.operator()
        if op in (OP_EXISTS, OP_DOES_NOT_EXIST):
            s = f"{self.key} {op}"
        else:
            vals = sorted(self.values)
            if len(vals) > 5:
                vals = vals[:5] + [f"and {len(vals) - 5} others"]
            s = f"{self.key} {op} {vals}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        return s

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Requirement)
            and self.key == other.key
            and self.complement == other.complement
            and self.values == other.values
            and self.greater_than == other.greater_than
            and self.less_than == other.less_than
        )


def _within(value: str, greater_than: Optional[int], less_than: Optional[int]) -> bool:
    """Bounds check; non-integers are invalid when bounds exist
    (requirement.go:238 withinIntPtrs)."""
    if greater_than is None and less_than is None:
        return True
    try:
        v = int(value)
    except ValueError:
        return False
    if greater_than is not None and greater_than >= v:
        return False
    if less_than is not None and less_than <= v:
        return False
    return True


def _min_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)
