"""Taints / tolerations (ref pkg/scheduling/taints.go)."""

from __future__ import annotations

from typing import Iterable, List, Optional

from ..apis import labels as wk
from ..kube.objects import EFFECT_NO_SCHEDULE, Pod, Taint

# taints the kubelet/cloud-provider applies transiently during startup
# (taints.go:28-32 KnownEphemeralTaints)
KNOWN_EPHEMERAL_TAINTS = [
    Taint(key=wk.TAINT_NODE_NOT_READY, effect=EFFECT_NO_SCHEDULE),
    Taint(key=wk.TAINT_NODE_UNREACHABLE, effect=EFFECT_NO_SCHEDULE),
    Taint(key=wk.TAINT_EXTERNAL_CLOUD_PROVIDER, value="true", effect=EFFECT_NO_SCHEDULE),
]


class Taints(List[Taint]):
    """Decorated taint list (taints.go:35)."""

    def tolerates(self, pod: Pod) -> Optional[str]:
        """None if the pod tolerates every taint, else an error string
        (taints.go:38)."""
        errs = []
        for taint in self:
            if not any(t.tolerates(taint) for t in pod.spec.tolerations):
                errs.append(f"did not tolerate {taint.key}={taint.value}:{taint.effect}")
        return "; ".join(errs) if errs else None

    def merge(self, other: Iterable[Taint]) -> "Taints":
        """Union keeping self's entries on key+effect conflicts (taints.go:53)."""
        res = Taints(self)
        for taint in other:
            if not any(taint.match(t) for t in res):
                res.append(taint)
        return res


def tolerates(taints: Iterable[Taint], pod: Pod) -> Optional[str]:
    return Taints(taints).tolerates(pod)
