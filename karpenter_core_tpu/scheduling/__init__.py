from .requirement import Requirement, INFINITE
from .requirements import Requirements, pod_requirements, strict_pod_requirements, label_requirements
from .taints import Taints, tolerates
from . import resources
from .hostports import HostPortUsage, get_host_ports, HostPort
from .volumes import VolumeUsage, Volumes
