"""Thread-safe lazy value (ref pkg/utils/atomic Lazy).

The reference caches expensive lookups (e.g. resolved kubelet configs)
behind atomic.Lazy (atomic/lazy.go). Python equivalent: double-checked
lock around a resolve callable, with explicit Set/Reset for tests.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")

_UNSET = object()


class Lazy(Generic[T]):
    def __init__(self, resolve: Optional[Callable[[], T]] = None):
        self._resolve = resolve
        self._value: object = _UNSET
        self._lock = threading.Lock()

    def get(self, resolve: Optional[Callable[[], T]] = None) -> T:
        # read into a local once: a racing reset() must not turn an
        # already-checked slot back into the sentinel mid-return
        # (double-checked locking — the lock-free fast path is the point)
        value = self._value  # analysis: allow-lock-discipline
        if value is not _UNSET:
            return value  # type: ignore[return-value]
        with self._lock:
            value = self._value
            if value is _UNSET:
                fn = resolve or self._resolve
                if fn is None:
                    raise ValueError("Lazy has no resolver")
                value = fn()
                self._value = value
        return value  # type: ignore[return-value]

    def set(self, value: T) -> None:
        with self._lock:
            self._value = value

    def reset(self) -> None:
        with self._lock:
            self._value = _UNSET
