"""Pod predicates (ref pkg/utils/pod/scheduling.go)."""

from __future__ import annotations

from ..apis import labels as wk
from ..kube.objects import EFFECT_NO_SCHEDULE, Pod, Taint
from ..scheduling.taints import Taints

TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"

DISRUPTION_NO_SCHEDULE_TAINT = Taint(
    key=wk.DISRUPTION_TAINT_KEY,
    value=wk.DISRUPTION_NO_SCHEDULE_VALUE,
    effect=EFFECT_NO_SCHEDULE,
)


def is_scheduled(pod: Pod) -> bool:
    return pod.spec.node_name != ""


def is_terminal(pod: Pod) -> bool:
    return pod.status.phase in ("Failed", "Succeeded")


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def failed_to_schedule(pod: Pod) -> bool:
    """PodScheduled=False with reason Unschedulable (scheduling.go:36)."""
    for cond in pod.status.conditions:
        if cond.type == "PodScheduled" and cond.status == "False" and cond.reason == "Unschedulable":
            return True
    return False


def is_provisionable(pod: Pod) -> bool:
    """Unscheduled + marked unschedulable + not terminal/terminating + not a
    static/node-owned pod (scheduling.go:28)."""
    return (
        not is_scheduled(pod)
        and not is_preempting(pod)
        and failed_to_schedule(pod)
        and not is_owned_by_daemonset(pod)
        and not is_owned_by_node(pod)
        and not is_terminal(pod)
        and not is_terminating(pod)
    )


def is_preempting(pod: Pod) -> bool:
    return False  # NominatedNodeName isn't modeled; preemption is out of scope


def is_owned_by_daemonset(pod: Pod) -> bool:
    return any(o.kind == "DaemonSet" for o in pod.metadata.owner_references)


def is_owned_by_node(pod: Pod) -> bool:
    return any(o.kind == "Node" for o in pod.metadata.owner_references)


def has_do_not_disrupt(pod: Pod) -> bool:
    """karpenter.sh/do-not-disrupt (+ v1alpha5 do-not-evict compat)
    (scheduling.go:85)."""
    ann = pod.metadata.annotations
    return (
        ann.get(wk.DO_NOT_DISRUPT_ANNOTATION_KEY) == "true"
        or ann.get(wk.DO_NOT_EVICT_ANNOTATION_KEY) == "true"
    )


def tolerates_unschedulable_taint(pod: Pod) -> bool:
    return (
        Taints([Taint(key=TAINT_NODE_UNSCHEDULABLE, effect=EFFECT_NO_SCHEDULE)]).tolerates(pod)
        is None
    )


def tolerates_disruption_no_schedule_taint(pod: Pod) -> bool:
    return Taints([DISRUPTION_NO_SCHEDULE_TAINT]).tolerates(pod) is None


def is_critical(pod: Pod) -> bool:
    """System-critical priority classes (utils/pod/scheduling.go)."""
    return pod.spec.priority_class_name in (
        "system-cluster-critical",
        "system-node-critical",
    )


def has_pod_anti_affinity(pod: Pod) -> bool:
    a = pod.spec.affinity
    return a is not None and a.pod_anti_affinity is not None and (
        len(a.pod_anti_affinity.required) > 0 or len(a.pod_anti_affinity.preferred) > 0
    )


def has_required_pod_anti_affinity(pod: Pod) -> bool:
    a = pod.spec.affinity
    return (
        a is not None
        and a.pod_anti_affinity is not None
        and len(a.pod_anti_affinity.required) > 0
    )


def is_active(pod: Pod) -> bool:
    return not is_terminal(pod) and not is_terminating(pod)


def disruption_screen_flags(pod: Pod) -> tuple:
    """``(active, do_not_disrupt_block)`` — the two per-pod verdicts the
    disruption candidate scan re-derives for every bound pod on every
    pass (50k+ evaluations per decision at config-9 scale). Memoized on
    the pod object behind its resource_version (the pod_eviction_cost
    rv-guard pattern): any annotation/status/deletion edit moves the rv
    and recomputes."""
    cached = getattr(pod, "_karp_dscreen", None)
    rv = pod.metadata.resource_version
    if cached is not None and cached[0] == rv:
        return cached[1]
    active = not is_terminal(pod) and not is_terminating(pod)
    flags = (active, active and has_do_not_disrupt(pod))
    pod._karp_dscreen = (rv, flags)
    return flags


def is_reschedulable(pod: Pod) -> bool:
    """Pods that must be rescheduled elsewhere when their node is disrupted:
    active and not owned by the node / daemonset."""
    return is_active(pod) and not is_owned_by_node(pod) and not is_owned_by_daemonset(pod)
