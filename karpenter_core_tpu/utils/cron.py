"""Minimal 5-field cron matcher for disruption-budget schedules
(ref designs/disruption-controls.md + apis/v1beta1/nodepool.go:104-110:
upstream cronjob syntax, plus the @hourly/@daily/... macros; timezones
unsupported, matching the reference's validation pattern).

Only matching is needed: a budget with ``schedule`` + ``duration`` is
active at time t iff some schedule hit h satisfies h <= t < h + duration
— answered by scanning the minute-aligned instants of the trailing
duration window, since cron's resolution is one minute.
"""

from __future__ import annotations

import functools
import time
from typing import List, Optional, Tuple

_MACROS = {
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
    "@monthly": "0 0 1 * *",
    "@weekly": "0 0 * * 0",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@hourly": "0 * * * *",
}

_MONTH_NAMES = {
    name: i + 1
    for i, name in enumerate(
        ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug", "sep", "oct", "nov", "dec"]
    )
}
_DOW_NAMES = {name: i for i, name in enumerate(["sun", "mon", "tue", "wed", "thu", "fri", "sat"])}

# field index → (min, max, name table)
_FIELDS: List[Tuple[int, int, dict]] = [
    (0, 59, {}),  # minute
    (0, 23, {}),  # hour
    (1, 31, {}),  # day of month
    (1, 12, _MONTH_NAMES),  # month
    (0, 7, _DOW_NAMES),  # day of week (0 and 7 are Sunday)
]


class CronError(ValueError):
    pass


def _parse_value(token: str, lo: int, hi: int, names: dict) -> int:
    token = token.strip().lower()
    if token in names:
        return names[token]
    try:
        value = int(token)
    except ValueError:
        raise CronError(f"invalid cron value {token!r}")
    if not lo <= value <= hi:
        raise CronError(f"cron value {value} out of range [{lo},{hi}]")
    return value


def _parse_field(field: str, lo: int, hi: int, names: dict) -> frozenset:
    out = set()
    for part in field.split(","):
        part = part.strip()
        step = 1
        has_step = "/" in part
        if has_step:
            part, step_s = part.split("/", 1)
            step = _parse_value(step_s, 1, hi, {})
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            start_s, end_s = part.split("-", 1)
            start = _parse_value(start_s, lo, hi, names)
            end = _parse_value(end_s, lo, hi, names)
            if end < start:
                raise CronError(f"inverted cron range {part!r}")
        else:
            start = _parse_value(part, lo, hi, names)
            # robfig/cron (CronJob) semantics: "N/step" means N-max/step
            end = hi if has_step else start
        out.update(range(start, end + 1, step))
    if not out:
        raise CronError(f"empty cron field {field!r}")
    return frozenset(out)


class Schedule:
    """A parsed cron expression answering matches(timestamp)."""

    def __init__(self, expr: str):
        expr = expr.strip()
        expr = _MACROS.get(expr.lower(), expr)
        fields = expr.split()
        if len(fields) != 5:
            raise CronError(f"cron expression needs 5 fields, got {expr!r}")
        self.minute, self.hour, self.dom, self.month, dow = (
            _parse_field(f, lo, hi, names)
            for f, (lo, hi, names) in zip(fields, _FIELDS)
        )
        # 7 is an alias for Sunday
        self.dow = frozenset(0 if v == 7 else v for v in dow)
        # cron quirk: when BOTH day-of-month and day-of-week are
        # restricted, either matching suffices (vixie cron / CronJob)
        self.dom_restricted = self.dom != frozenset(range(1, 32))
        self.dow_restricted = self.dow != frozenset(range(0, 7))

    def _day_matches(self, t) -> bool:
        if t.tm_mon not in self.month:
            return False
        cron_dow = (t.tm_wday + 1) % 7  # tm_wday: Mon=0 → cron: Sun=0
        dom_ok = t.tm_mday in self.dom
        dow_ok = cron_dow in self.dow
        if self.dom_restricted and self.dow_restricted:
            # cron quirk: when BOTH fields are restricted, either suffices
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def matches(self, ts: float) -> bool:
        t = time.gmtime(ts)
        if t.tm_min not in self.minute or t.tm_hour not in self.hour:
            return False
        return self._day_matches(t)

    def last_hit(self, now: float, earliest: float) -> Optional[float]:
        """Most recent hit h with earliest < h <= now, or None. Scans
        whole days backwards — non-matching days cost O(1), so a long
        inactive window is days, not minutes, of work per check."""
        hours_desc = sorted(self.hour, reverse=True)
        mins_desc = sorted(self.minute, reverse=True)
        day_start = int(now) // 86400 * 86400
        while day_start + 86400 > earliest:
            t = time.gmtime(day_start)
            if self._day_matches(t):
                cap = now if day_start + 86400 > now else day_start + 86399
                for h in hours_desc:
                    if day_start + h * 3600 > cap:
                        continue
                    for m in mins_desc:
                        ts = day_start + h * 3600 + m * 60
                        if ts <= cap:
                            return ts if ts > earliest else None
            day_start -= 86400
        return None

    def active_within(self, now: float, duration: float) -> bool:
        """True iff a hit h exists with h <= now < h + duration."""
        if duration <= 0:
            return False
        return self.last_hit(now, now - duration) is not None


def parse(expr: str) -> Schedule:
    return Schedule(expr)


@functools.lru_cache(maxsize=256)
def _cached_schedule(expr: str) -> Schedule:
    """Budgets re-check their schedules every reconcile pass — parse once."""
    return Schedule(expr)


def budget_is_active(schedule: Optional[str], duration: Optional[float], now: float) -> bool:
    """Budget activity per the design: no schedule+duration = always
    active; otherwise active for ``duration`` after each schedule hit.
    A malformed schedule deactivates the budget (validation rejects it
    up front; this is the runtime backstop)."""
    if schedule is None and duration is None:
        return True
    if schedule is None or duration is None:
        # validation requires both-or-neither; treat half-set as always
        # active only when neither restricts (handled above), else inactive
        return False
    try:
        return _cached_schedule(schedule).active_within(now, duration)
    except CronError:
        return False
