"""Log-dedup utilities (ref pkg/utils/pretty).

ChangeMonitor rate-limits repeated log lines: a message under a key is
worth emitting only when its value changed or the key has been quiet
for the window (pretty/changemonitor.go:28, used for the provisioner's
once-per-hour "no nodepools found" warnings, provisioner.go:182-199).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Tuple


class ChangeMonitor:
    def __init__(self, window_seconds: float = 3600.0, clock: Optional[Callable[[], float]] = None):
        self.window = window_seconds
        self.clock = clock or time.monotonic
        self._seen: Dict[str, Tuple[object, float]] = {}

    def has_changed(self, key: str, value: object) -> bool:
        """True when the value under key changed or the window expired —
        i.e., the caller should log."""
        now = self.clock()
        prev = self._seen.get(key)
        if prev is not None and prev[0] == value and now - prev[1] < self.window:
            return False
        self._seen[key] = (value, now)
        return True
