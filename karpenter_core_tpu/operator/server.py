"""Served operational surface (ref pkg/operator/operator.go:126-177):
a metrics server (`/metrics`, `/debug/traces[/last]`, plus
`/debug/pprof/*` when profiling is enabled) and a probe server
(`/healthz`, `/readyz`).

The reference gets these from controller-runtime's manager; here they
are two stdlib ThreadingHTTPServers. The pprof equivalents are
TPU-build-native: a live all-thread stack dump, and a sampling
profiler over ``sys._current_frames`` that emits collapsed stacks
(flamegraph input) — the closest Python analogue of
``/debug/pprof/profile``.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# route → (status, content_type, body) producer
Route = Callable[[Dict[str, list]], Tuple[int, str, str]]


def _stack_dump(_query) -> Tuple[int, str, str]:
    lines = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sys._current_frames().items():
        lines.append(f"goroutine-equivalent thread {ident} [{names.get(ident, '?')}]:")
        lines.extend(l.rstrip() for l in traceback.format_stack(frame))
        lines.append("")
    return 200, "text/plain; charset=utf-8", "\n".join(lines)


# single-flight gate for the sampling profiler: two overlapping captures
# would double-count samples (both walk sys._current_frames and see each
# other's handler thread) and burn two threads at 100 Hz
_PROFILE_GATE = threading.Lock()


def _collapsed_profile(query) -> Tuple[int, str, str]:
    """Sample every thread's stack for ?seconds=N (default 2, max 30) at
    ~100 Hz; emit one collapsed stack per line with its sample count.
    Concurrent captures are rejected with 429."""
    try:
        seconds = min(float(query.get("seconds", ["2"])[0]), 30.0)
    except ValueError:
        return 400, "text/plain", "bad seconds parameter\n"
    if not _PROFILE_GATE.acquire(blocking=False):
        return 429, "text/plain", "profile capture already in flight\n"
    try:
        me = threading.get_ident()
        samples: Counter = Counter()
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                stack = []
                while frame is not None:
                    code = frame.f_code
                    stack.append(f"{code.co_name} ({code.co_filename}:{frame.f_lineno})")
                    frame = frame.f_back
                if stack:
                    samples[";".join(reversed(stack))] += 1
            time.sleep(0.01)
    finally:
        _PROFILE_GATE.release()
    body = "".join(f"{stack} {count}\n" for stack, count in samples.most_common())
    return 200, "text/plain; charset=utf-8", body or "no samples\n"


def _traces(query) -> Tuple[int, str, str]:
    """Chrome trace-event JSON of the buffered solve traces
    (Perfetto / chrome://tracing loadable). ``?id=<trace_id>`` selects
    one trace; default is every trace still in the ring."""
    from ..tracing import RING, to_chrome_json

    wanted = query.get("id", [None])[0]
    if wanted is not None:
        tr = RING.get(wanted)
        if tr is None:
            return 404, "text/plain", f"no buffered trace {wanted}\n"
        traces = [tr]
    else:
        traces = RING.all()
    return 200, "application/json", to_chrome_json(traces)


def _traces_last(_query) -> Tuple[int, str, str]:
    """The most recent solve trace as Chrome trace-event JSON."""
    from ..tracing import RING, to_chrome_json

    tr = RING.last()
    if tr is None:
        return 404, "text/plain", "no solve traces captured yet\n"
    return 200, "application/json", to_chrome_json([tr])


def _device(query) -> Tuple[int, str, str]:
    """Device-plane observatory (tracing/deviceplane.py, ISSUE 16): the
    jit-signature registry, process compile/transfer totals, and the
    recent compile events carrying trace_id exemplars, plus the managed
    compile-cache status and boot jitsig-replay outcome (ISSUE 17 — a
    cacheless or replay-degraded process is visible here, never
    silent). ``?tail=N`` bounds the event list (default 32)."""
    import json

    from ..solver import backend, prewarm
    from ..tracing import deviceplane

    try:
        tail = int(query.get("tail", ["32"])[0])
    except ValueError:
        return 400, "text/plain", "bad tail parameter\n"
    state = deviceplane.debug_state(tail=tail)
    state["compile_cache"] = backend.compile_cache_status()
    state["prewarm"] = prewarm.last_result()
    return 200, "application/json", json.dumps(state, default=str)


def _decisions(query) -> Tuple[int, str, str]:
    """The flight recorder's ring (tracing/flightrec.py): per-decision
    records with SLO burn rates and timeline-reconstruction coverage.
    ``?tail=N`` bounds the decision list (default 32)."""
    import json

    from ..tracing import RECORDER

    try:
        tail = int(query.get("tail", ["32"])[0])
    except ValueError:
        return 400, "text/plain", "bad tail parameter\n"
    return 200, "application/json", json.dumps(RECORDER.debug_state(tail=tail), default=str)


def _decisions_last(_query) -> Tuple[int, str, str]:
    """The most recent decision's flight record."""
    import json

    from ..tracing import RECORDER

    rec = RECORDER.last()
    if rec is None:
        return 404, "text/plain", "no decisions recorded yet\n"
    return 200, "application/json", json.dumps(rec, default=str)


class _Handler(BaseHTTPRequestHandler):
    # routes injected per-server via the server instance
    def do_GET(self):  # noqa: N802 — http.server API
        parsed = urlparse(self.path)
        route = self.server.routes.get(parsed.path)  # type: ignore[attr-defined]
        if route is None:
            self.send_error(404)
            return
        status, content_type, body = route(parse_qs(parsed.query))
        payload = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt, *args):  # quiet: probes poll every few seconds
        pass


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, port: int, routes: Dict[str, Route]):
        super().__init__(("0.0.0.0", port), _Handler)
        self.routes = routes


class OperationalServer:
    """Binds the metrics and probe ports and serves them from daemon
    threads. ``port`` 0 binds an ephemeral port (tests); the bound ports
    are exposed as ``metrics_port`` / ``probe_port`` after start()."""

    def __init__(
        self,
        registry,
        ready_check: Callable[[], bool],
        metrics_port: int = 8000,
        probe_port: int = 8081,
        enable_profiling: bool = False,
        logger=None,
        serving_state: Optional[Callable[[], dict]] = None,
        fleet_state: Optional[Callable[[], dict]] = None,
        solve_stats: Optional[Callable[[], Optional[dict]]] = None,
    ):
        self.registry = registry
        self.ready_check = ready_check
        self._metrics_port = metrics_port
        self._probe_port = probe_port
        self.enable_profiling = enable_profiling
        self.logger = logger
        # serving-pipeline introspection hook (ServingPipeline.debug_state)
        self.serving_state = serving_state
        # fleet introspection hook (FleetEngine/FleetScheduler state:
        # registry, last batch composition, DRR deficits)
        self.fleet_state = fleet_state
        # consolidated per-solve stats hook (solver/stats.py): the one
        # stable schema over the scattered last_* stat blobs
        self.solve_stats = solve_stats
        self._metrics_server: Optional[_Server] = None
        self._probe_server: Optional[_Server] = None

    # -- route payloads -----------------------------------------------------

    def _metrics(self, _query) -> Tuple[int, str, str]:
        return 200, PROMETHEUS_CONTENT_TYPE, self.registry.expose()

    def _healthz(self, _query) -> Tuple[int, str, str]:
        return 200, "text/plain", "ok\n"

    def _readyz(self, _query) -> Tuple[int, str, str]:
        # operator.go:171-175: readiness is cache sync
        if self.ready_check():
            return 200, "text/plain", "ok\n"
        return 503, "text/plain", "caches not synced\n"

    def _serving(self, _query) -> Tuple[int, str, str]:
        """Serving-pipeline state: queue depths/backpressure, tick log,
        prewarm traffic, decision-latency percentiles."""
        import json

        if self.serving_state is None:
            return 404, "text/plain", "serving pipeline not running\n"
        try:
            payload = json.dumps(self.serving_state(), default=str)
        except Exception as err:  # noqa: BLE001 — a debug route must not 500 the server
            return 500, "text/plain", f"serving state unavailable: {err}\n"
        return 200, "application/json", payload

    def _fleet(self, _query) -> Tuple[int, str, str]:
        """Fleet state: tenant registry, last mega-solve round
        composition, dispatcher coalescing stats, DRR deficits."""
        import json

        if self.fleet_state is None:
            return 404, "text/plain", "fleet solver not running\n"
        try:
            payload = json.dumps(self.fleet_state(), default=str)
        except Exception as err:  # noqa: BLE001 — a debug route must not 500 the server
            return 500, "text/plain", f"fleet state unavailable: {err}\n"
        return 200, "application/json", payload

    def _solve_stats(self, _query) -> Tuple[int, str, str]:
        """Consolidated per-solve stats (solver/stats.py SCHEMA): one
        stable document over timings/cache/merge/pack-backend/disruption
        — the blob the bench readers and dashboards consume."""
        import json

        if self.solve_stats is None:
            return 404, "text/plain", "no solver wired\n"
        try:
            payload = self.solve_stats()
        except Exception as err:  # noqa: BLE001 — a debug route must not 500 the server
            return 500, "text/plain", f"solve stats unavailable: {err}\n"
        if payload is None:
            return 404, "text/plain", "no solve has completed yet\n"
        return 200, "application/json", json.dumps(payload, default=str)

    # -- lifecycle ----------------------------------------------------------

    @property
    def metrics_port(self) -> Optional[int]:
        return self._metrics_server.server_address[1] if self._metrics_server else None

    @property
    def probe_port(self) -> Optional[int]:
        return self._probe_server.server_address[1] if self._probe_server else None

    def _bind(self, port: int, routes: Dict[str, Route]) -> Optional[_Server]:
        try:
            server = _Server(port, routes)
        except OSError as err:
            # a busy port must not take the operator down; the rest of
            # the surface (and the controllers) keep running
            if self.logger is not None:
                self.logger.error("failed to bind port %s: %s", port, err)
            return None
        threading.Thread(target=server.serve_forever, name=f"http-{port}", daemon=True).start()
        return server

    def start(self) -> None:
        metrics_routes: Dict[str, Route] = {
            "/metrics": self._metrics,
            # solve traces are always on: the tracer's steady-state cost
            # is a few dozen span records per solve, and the routes only
            # read the ring buffer (ISSUE 1 tentpole)
            "/debug/traces": _traces,
            "/debug/traces/last": _traces_last,
            # the flight recorder rides the same always-on policy as the
            # trace ring: the routes only read the bounded ring
            "/debug/decisions": _decisions,
            "/debug/decisions/last": _decisions_last,
            # the device plane is always on for the same reason: the
            # registry is bounded module state, the route only reads it
            "/debug/device": _device,
        }
        if self.serving_state is not None:
            metrics_routes["/debug/serving"] = self._serving
        if self.fleet_state is not None:
            metrics_routes["/debug/fleet"] = self._fleet
        if self.solve_stats is not None:
            metrics_routes["/debug/solve/stats"] = self._solve_stats
        if self.enable_profiling:
            metrics_routes["/debug/pprof/"] = _stack_dump
            metrics_routes["/debug/pprof/profile"] = _collapsed_profile
        probe_routes: Dict[str, Route] = {"/healthz": self._healthz, "/readyz": self._readyz}
        self._metrics_server = self._bind(self._metrics_port, metrics_routes)
        self._probe_server = self._bind(self._probe_port, probe_routes)

    def stop(self) -> None:
        for server in (self._metrics_server, self._probe_server):
            if server is not None:
                server.shutdown()
                server.server_close()
        self._metrics_server = None
        self._probe_server = None
