"""Operator configuration: CLI flags with env fallbacks + feature gates
(ref pkg/operator/options/options.go)."""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field, fields
from typing import Dict, List, Optional


def _env(name: str, default):
    raw = os.environ.get(name)
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


@dataclass
class FeatureGates:
    """options.go:123-137: parsed from "Drift=true,..." strings."""

    drift: bool = True

    @classmethod
    def parse(cls, s: str) -> "FeatureGates":
        gates = cls()
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, value = part.partition("=")
            enabled = value.strip().lower() in ("true", "1", "")
            if key.strip().lower() == "drift":
                gates.drift = enabled
        return gates


@dataclass
class Options:
    """options.go:47-99 — same knobs, same defaults."""

    service_name: str = ""
    # the namespace the operator runs in (SYSTEM_NAMESPACE downward-API
    # convention); the only namespace whose config-logging is honored
    system_namespace: str = "default"
    metrics_port: int = 8000
    health_probe_port: int = 8081
    kube_client_qps: int = 200
    kube_client_burst: int = 300
    enable_profiling: bool = False
    enable_leader_election: bool = True
    memory_limit: int = -1
    log_level: str = "info"
    batch_max_duration: float = 10.0  # options.go:96
    batch_idle_duration: float = 1.0  # options.go:97
    feature_gates: FeatureGates = field(default_factory=FeatureGates)
    # options.go:84 DISABLE_WEBHOOK — our admission chain (defaults +
    # validation) replaces the knative webhook; enabled by default since
    # there is no CEL layer in-process to fall back on.
    disable_webhook: bool = False
    # TPU-native knobs
    use_tpu_solver: bool = True
    tpu_consolidation_screen: bool = True
    # serving pipeline (serving/pipeline.py): replace the tick-shaped
    # provisioner reconcile loop with the staged async pipeline
    # (overlapped batching/encode/dispatch/emit + /debug/serving)
    use_serving_pipeline: bool = False

    @classmethod
    def from_env(cls) -> "Options":
        opts = cls()
        opts.service_name = _env("SYSTEM_NAME", opts.service_name)
        opts.system_namespace = _env("SYSTEM_NAMESPACE", opts.system_namespace)
        opts.metrics_port = _env("METRICS_PORT", opts.metrics_port)
        opts.health_probe_port = _env("HEALTH_PROBE_PORT", opts.health_probe_port)
        opts.kube_client_qps = _env("KUBE_CLIENT_QPS", opts.kube_client_qps)
        opts.kube_client_burst = _env("KUBE_CLIENT_BURST", opts.kube_client_burst)
        opts.enable_profiling = _env("ENABLE_PROFILING", opts.enable_profiling)
        opts.enable_leader_election = _env("LEADER_ELECT", opts.enable_leader_election)
        opts.log_level = _env("LOG_LEVEL", opts.log_level)
        opts.batch_max_duration = _env("BATCH_MAX_DURATION", opts.batch_max_duration)
        opts.batch_idle_duration = _env("BATCH_IDLE_DURATION", opts.batch_idle_duration)
        opts.feature_gates = FeatureGates.parse(_env("FEATURE_GATES", ""))
        opts.disable_webhook = _env("DISABLE_WEBHOOK", opts.disable_webhook)
        opts.use_tpu_solver = _env("USE_TPU_SOLVER", opts.use_tpu_solver)
        opts.tpu_consolidation_screen = _env("TPU_CONSOLIDATION_SCREEN", opts.tpu_consolidation_screen)
        opts.use_serving_pipeline = _env("USE_SERVING_PIPELINE", opts.use_serving_pipeline)
        return opts

    @classmethod
    def from_args(cls, argv: Optional[List[str]] = None) -> "Options":
        opts = cls.from_env()
        parser = argparse.ArgumentParser("karpenter-tpu")
        parser.add_argument("--metrics-port", type=int, default=opts.metrics_port)
        parser.add_argument("--health-probe-port", type=int, default=opts.health_probe_port)
        parser.add_argument("--enable-profiling", action="store_true", default=opts.enable_profiling)
        parser.add_argument("--leader-elect", action="store_true", default=opts.enable_leader_election)
        parser.add_argument("--log-level", default=opts.log_level)
        parser.add_argument("--batch-max-duration", type=float, default=opts.batch_max_duration)
        parser.add_argument("--batch-idle-duration", type=float, default=opts.batch_idle_duration)
        parser.add_argument("--feature-gates", default="")
        parser.add_argument("--use-tpu-solver", action="store_true", default=opts.use_tpu_solver)
        args = parser.parse_args(argv)
        opts.metrics_port = args.metrics_port
        opts.health_probe_port = args.health_probe_port
        opts.enable_profiling = args.enable_profiling
        opts.enable_leader_election = args.leader_elect
        opts.log_level = args.log_level
        opts.batch_max_duration = args.batch_max_duration
        opts.batch_idle_duration = args.batch_idle_duration
        if args.feature_gates:
            opts.feature_gates = FeatureGates.parse(args.feature_gates)
        opts.use_tpu_solver = args.use_tpu_solver
        return opts
