"""Controller abstractions (ref pkg/operator/controller/controller.go,
singleton.go): singleton poll-loop controllers with reconcile metrics and
the 10 ms → 10 s backoff rate limiter."""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

BASE_DELAY = 0.01  # singleton.go:133 rate-limiter base
MAX_DELAY = 10.0  # singleton.go:141 max
GATED_POLL = 2.0  # follower re-check cadence (matches elector retry period)


class SingletonController:
    """singleton.go:39: a controller that reconciles in its own loop."""

    def __init__(
        self,
        name: str,
        reconcile: Callable[[], Optional[float]],
        metrics=None,
        logger=None,
        period: float = 10.0,
        gate: Optional[Callable[[], bool]] = None,
    ):
        self.name = name
        self._reconcile = reconcile
        self.metrics = metrics
        self.logger = logger
        self.period = period
        # leader-election gate: while it returns False (we are a
        # follower), reconciles are skipped but the loop keeps ticking
        self.gate = gate
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error_streak = 0

    def reconcile_once(self) -> Optional[float]:
        """One reconcile; returns the requeue delay. Errors back off
        exponentially (singleton.go:81-123)."""
        if self.gate is not None and not self.gate():
            # short follower poll — a newly promoted leader must start
            # reconciling promptly, not after e.g. a 600 s consistency period
            return min(self.period, GATED_POLL)
        start = time.perf_counter()
        try:
            requeue_after = self._reconcile()
            self._error_streak = 0
        except Exception as e:  # noqa: BLE001 — controller loops never die
            self._error_streak += 1
            if self.metrics is not None:
                self.metrics.reconcile_errors.inc(controller=self.name)
            if self.logger is not None:
                self.logger.with_(controller=self.name).error("reconcile error, %s", e)
            requeue_after = min(BASE_DELAY * (2 ** self._error_streak), MAX_DELAY)
        finally:
            if self.metrics is not None:
                self.metrics.reconcile_duration.observe(
                    time.perf_counter() - start, controller=self.name
                )
        return requeue_after if requeue_after is not None else self.period

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive() and not self._stop.is_set():
            return  # already running
        # fresh stop event per start: a previous loop still draining a
        # long reconcile keeps its own (set) event and exits at its next
        # check, so stop() → start() restart can never leak a second loop
        stop = self._stop = threading.Event()

        def loop():
            while not stop.is_set():
                delay = self.reconcile_once()
                stop.wait(delay)

        self._thread = threading.Thread(target=loop, name=self.name, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
