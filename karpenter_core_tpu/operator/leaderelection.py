"""Lease-based leader election (ref pkg/operator/operator.go:121-124:
LeaderElection over the Leases resource lock, id
"karpenter-leader-election", in the operator's namespace).

The algorithm is client-go's leaderelection.LeaderElector, expressed
over this build's kube store and its optimistic-concurrency update:
every ``retry_period`` each candidate runs one try_acquire_or_renew
step — create the Lease if absent, take it over if expired, renew it
if held — and a Conflict from the store means another candidate's
write landed first, so the step simply loses this round. Correctness
rides on the store's resourceVersion check, exactly as the real thing
rides on the apiserver's.
"""

from __future__ import annotations

import copy
import os
import threading
import time
import uuid
from typing import Callable, Optional

from ..kube.client import Conflict, NotFound
from ..kube.objects import Lease

LEASE_NAME = "karpenter-leader-election"


def default_holder_id() -> str:
    # client-go convention: hostname + a unique suffix, so two operators
    # on one host still get distinct identities
    return f"{os.uname().nodename}_{uuid.uuid4().hex[:8]}"


class LeaderElector:
    def __init__(
        self,
        kube_client,
        holder_id: Optional[str] = None,
        namespace: str = "default",
        lease_name: str = LEASE_NAME,
        lease_duration: float = 15.0,
        retry_period: float = 2.0,
        # analysis: allow-clock(lease renew_time crosses processes — wall clock by leader-election protocol)
        clock: Callable[[], float] = time.time,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.kube_client = kube_client
        self.holder_id = holder_id or default_holder_id()
        self.namespace = namespace
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def is_leader(self) -> bool:
        return self._leader

    # -- one election step --------------------------------------------------

    def _expired(self, lease: Lease, now: float) -> bool:
        if not lease.holder:
            return True
        duration = lease.lease_duration_seconds or self.lease_duration
        renewed = lease.renew_time if lease.renew_time is not None else 0.0
        return now > renewed + duration

    def try_acquire_or_renew(self) -> bool:
        """One leaderelection.go tryAcquireOrRenew step; returns whether
        this candidate holds the lease afterwards."""
        now = self.clock()
        lease = self.kube_client.get("Lease", self.lease_name, namespace=self.namespace)
        if lease is None:
            fresh = Lease(
                holder=self.holder_id,
                lease_duration_seconds=int(self.lease_duration),
                acquire_time=now,
                renew_time=now,
            )
            fresh.metadata.name = self.lease_name
            fresh.metadata.namespace = self.namespace
            try:
                self.kube_client.create(fresh)
            except Conflict:
                return self._observe(False)
            return self._observe(True)

        if lease.holder != self.holder_id and not self._expired(lease, now):
            return self._observe(False)

        # ours to renew, or expired and up for grabs — write through a
        # copy so losing the race leaves the stored lease untouched
        target = copy.deepcopy(lease)
        if target.holder != self.holder_id:
            target.lease_transitions += 1
            target.acquire_time = now
        target.holder = self.holder_id
        target.lease_duration_seconds = int(self.lease_duration)
        target.renew_time = now
        try:
            self.kube_client.update(target)
        except (Conflict, NotFound):
            return self._observe(False)
        return self._observe(True)

    def release(self) -> None:
        """client-go ReleaseOnCancel: clear the holder so a successor
        acquires immediately instead of waiting out the lease."""
        lease = self.kube_client.get("Lease", self.lease_name, namespace=self.namespace)
        if lease is None or lease.holder != self.holder_id:
            # someone else already took (or removed) the lease — we are
            # certainly not leading; make the local state and callbacks agree
            self._observe(False)
            return
        target = copy.deepcopy(lease)
        target.holder = ""
        target.renew_time = None
        try:
            self.kube_client.update(target)
        except (Conflict, NotFound):
            pass
        self._observe(False)

    def _observe(self, leading: bool) -> bool:
        if leading and not self._leader:
            self._leader = True
            if self.on_started_leading is not None:
                self.on_started_leading()
        elif not leading and self._leader:
            self._leader = False
            if self.on_stopped_leading is not None:
                self.on_stopped_leading()
        return leading

    # -- background loop ----------------------------------------------------

    def start(self) -> None:
        self.try_acquire_or_renew()  # synchronous first step

        def loop():
            while not self._stop.wait(self.retry_period):
                try:
                    self.try_acquire_or_renew()
                except Exception:  # noqa: BLE001 — election never kills the operator
                    self._observe(False)

        self._thread = threading.Thread(target=loop, name="leader-election", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.release()
