"""Operator: the composition root (ref pkg/operator/operator.go +
pkg/controllers/controllers.go:47-82 — the single place listing every
controller)."""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..apis.validation import install_admission
from ..cloudprovider.metrics import MetricsDecorator
from ..disruption import DisruptionController, NodeClaimDisruptionController, OrchestrationQueue
from ..events import Recorder
from ..kube.client import KubeClient
from ..lifecycle import (
    ConsistencyController,
    EvictionQueue,
    LeaseGarbageCollectionController,
    NodeClaimGarbageCollectionController,
    NodeClaimLifecycleController,
    NodeClaimTerminationController,
    NodePoolCounterController,
    NodePoolHashController,
    NodeTerminationController,
    Terminator,
)
from ..metrics import Metrics, MetricsStore, Registry
from ..provisioning import Batcher, Provisioner
from ..state.cluster import Cluster
from ..state.informers import Informers
from .controller import SingletonController
from .logging import new_logger, watch_config_logging
from .options import Options


class Operator:
    """operator.go:80 NewOperator / WithControllers / Start, collapsed into
    one object (we have no provider-binary split)."""

    def __init__(
        self,
        cloud_provider,
        kube_client: Optional[KubeClient] = None,
        options: Optional[Options] = None,
        # analysis: allow-clock(fans to lease/stamping controllers that compare persisted wall-clock stamps)
        clock: Callable[[], float] = time.time,
    ):
        self.options = options or Options.from_env()
        self.logger = new_logger(self.options.log_level)
        self.kube_client = kube_client or KubeClient(clock=clock)
        # live log-level from the config-logging ConfigMap (logging.go:47-167)
        self._log_config_unsub = watch_config_logging(
            self.kube_client, self.logger, namespace=self.options.system_namespace
        )
        if not self.options.disable_webhook:
            install_admission(self.kube_client)
        self.registry = Registry()
        self.metrics = Metrics(self.registry)
        self.cloud_provider = MetricsDecorator(cloud_provider, self.metrics)
        self.recorder = Recorder(self.kube_client, clock=clock)
        self.clock = clock

        self.cluster = Cluster(self.kube_client, self.cloud_provider, clock=clock)
        self.informers = Informers(self.kube_client, self.cluster)
        self.batcher = Batcher(
            idle_seconds=self.options.batch_idle_duration,
            max_seconds=self.options.batch_max_duration,
            clock=clock,
        )
        self.provisioner = Provisioner(
            self.kube_client,
            self.cloud_provider,
            self.cluster,
            recorder=self.recorder,
            batcher=self.batcher,
            use_tpu_solver=self.options.use_tpu_solver,
            metrics=self.metrics,
        )
        self.eviction_queue = EvictionQueue(self.kube_client, self.recorder)
        self.terminator = Terminator(self.kube_client, self.eviction_queue, clock=clock)
        self.orchestration_queue = OrchestrationQueue(
            self.kube_client, self.cluster, self.recorder, clock, self.metrics
        )
        self.nodeclaim_lifecycle = NodeClaimLifecycleController(
            self.kube_client, self.cloud_provider, self.recorder, clock, self.metrics
        )
        self.nodeclaim_termination = NodeClaimTerminationController(
            self.kube_client, self.cloud_provider, self.metrics
        )
        self.node_termination = NodeTerminationController(
            self.kube_client, self.cloud_provider, self.terminator, self.recorder, self.metrics
        )
        self.nodeclaim_gc = NodeClaimGarbageCollectionController(
            self.kube_client, self.cloud_provider, clock
        )
        self.nodeclaim_disruption = NodeClaimDisruptionController(
            self.kube_client,
            self.cloud_provider,
            self.cluster,
            clock,
            drift_enabled=self.options.feature_gates.drift,
        )
        self.disruption = DisruptionController(
            self.kube_client,
            self.cluster,
            self.provisioner,
            self.cloud_provider,
            recorder=self.recorder,
            clock=clock,
            queue=self.orchestration_queue,
            use_tpu_screen=self.options.tpu_consolidation_screen,
            metrics=self.metrics,
        )
        self.consistency = ConsistencyController(self.kube_client, self.recorder, metrics=self.metrics)
        self.nodepool_counter = NodePoolCounterController(self.kube_client, self.cluster)
        self.nodepool_hash = NodePoolHashController(self.kube_client)
        self.lease_gc = LeaseGarbageCollectionController(self.kube_client)
        self.metrics_store = MetricsStore(self.metrics)
        self.elector = None
        self.http = None
        # staged async serving pipeline (serving/pipeline.py): when
        # enabled it owns provisioning — the tick-shaped provisioner
        # controller below degrades to a no-op safety net
        self.serving = None
        if self.options.use_serving_pipeline:
            from ..serving import PipelineConfig, ServingPipeline

            self.serving = ServingPipeline(
                self.provisioner,
                metrics=self.metrics,
                config=PipelineConfig(
                    idle_seconds=self.options.batch_idle_duration,
                    max_seconds=self.options.batch_max_duration,
                ),
                # continuous disruption (KARPENTER_TPU_SERVING_DISRUPT_EVERY
                # > 0): the pass runs as a plan-thread stage; the 10 s
                # singleton below stays as the safety net either way
                disruption=self.disruption,
            )

        # the reconcile surface, mirroring controllers.go:47-82
        self.controllers: List[SingletonController] = [
            SingletonController("provisioner", self._reconcile_provisioner, self.metrics, self.logger, gate=self._leading, period=10.0),
            SingletonController("disruption", self._reconcile_disruption, self.metrics, self.logger, gate=self._leading, period=10.0),
            SingletonController("disruption.queue", self._reconcile_queue, self.metrics, self.logger, gate=self._leading, period=1.0),
            SingletonController("nodeclaim.lifecycle", self._reconcile_lifecycle, self.metrics, self.logger, gate=self._leading, period=2.0),
            SingletonController("nodeclaim.termination", self._reconcile_nc_termination, self.metrics, self.logger, gate=self._leading, period=2.0),
            SingletonController("node.termination", self._reconcile_node_termination, self.metrics, self.logger, gate=self._leading, period=2.0),
            SingletonController("nodeclaim.garbagecollection", lambda: self._none(self.nodeclaim_gc.reconcile), self.metrics, self.logger, gate=self._leading, period=120.0),
            SingletonController("nodeclaim.disruption", lambda: self._none(self.nodeclaim_disruption.reconcile_all), self.metrics, self.logger, gate=self._leading, period=10.0),
            SingletonController("nodeclaim.consistency", lambda: self._none(self.consistency.reconcile_all), self.metrics, self.logger, gate=self._leading, period=600.0),
            SingletonController("nodepool.counter", lambda: self._none(self.nodepool_counter.reconcile_all), self.metrics, self.logger, gate=self._leading, period=10.0),
            SingletonController("nodepool.hash", lambda: self._none(self.nodepool_hash.reconcile_all), self.metrics, self.logger, gate=self._leading, period=10.0),
            SingletonController("lease.garbagecollection", lambda: self._none(self.lease_gc.reconcile), self.metrics, self.logger, gate=self._leading, period=120.0),
            SingletonController("metrics.scraper", self._reconcile_metrics, self.metrics, self.logger, gate=self._leading, period=10.0),
            SingletonController("eviction.queue", lambda: self._none(self.eviction_queue.reconcile), self.metrics, self.logger, gate=self._leading, period=1.0),
        ]
        self._started = False
        self._batching = False

    def _leading(self) -> bool:
        """Leader gate for every controller: standalone (no election) or
        the current Lease holder. Followers keep their loops ticking but
        skip reconciles — the reference gets this from controller-
        runtime's manager (operator.go:121-124)."""
        return self.elector is None or self.elector.is_leader()

    # -- reconcile wrappers -------------------------------------------------

    @staticmethod
    def _none(fn: Callable) -> None:
        fn()
        return None

    def _reconcile_provisioner(self) -> None:
        if self.serving is not None:
            return None  # the serving pipeline owns provisioning ticks
        with self.metrics.scheduling_duration.time():
            _, reason = self.provisioner.reconcile(wait_for_batch=self._batching)
        if reason:
            self.logger.with_(controller="provisioner").info("%s", reason)
        return None

    def _reconcile_disruption(self) -> None:
        if self.serving is not None and self.serving.config.disrupt_every > 0:
            # the serving pipeline owns disruption passes (plan-thread
            # stage): running them here too would race its mutations
            return None
        self.disruption.reconcile()
        return None

    def _reconcile_queue(self) -> None:
        self.orchestration_queue.reconcile()
        return None

    def _reconcile_lifecycle(self) -> None:
        self.nodeclaim_lifecycle.reconcile_all()
        return None

    def _reconcile_nc_termination(self) -> None:
        self.nodeclaim_termination.reconcile_all()
        return None

    def _reconcile_node_termination(self) -> None:
        self.node_termination.reconcile_all()
        return None

    def _reconcile_metrics(self) -> None:
        self.metrics_store.scrape_nodes(self.cluster)
        self.metrics_store.scrape_nodepools(self.kube_client)
        self.metrics_store.scrape_pods(self.kube_client)
        return None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """operator.go:203 Start: informers first (cache sync), then the
        operational surface and election, then all controllers."""
        self.informers.start()
        if self.options.enable_leader_election and self.elector is None:
            from .leaderelection import LeaderElector

            self.elector = LeaderElector(
                self.kube_client,
                namespace=self.options.system_namespace,
                clock=self.clock,
                on_started_leading=lambda: self.logger.info("became leader"),
                on_stopped_leading=lambda: self.logger.info("lost leadership"),
            )
            self.elector.start()
        if self.http is None:
            from .server import OperationalServer

            from ..solver import stats as solver_stats

            def _live_solver():
                cached = getattr(self.provisioner, "_tpu_solver", None)
                return cached[1] if cached is not None else None

            self.http = OperationalServer(
                self.registry,
                ready_check=self.healthy,
                metrics_port=self.options.metrics_port,
                probe_port=self.options.health_probe_port,
                enable_profiling=self.options.enable_profiling,
                logger=self.logger,
                serving_state=(
                    self.serving.debug_state if self.serving is not None else None
                ),
                solve_stats=lambda: solver_stats.route_payload(
                    _live_solver, lambda: getattr(self, "disruption", None)
                ),
            )
            self.http.start()
        # start/stop symmetry: re-register the config-logging watch a
        # previous stop() tore down
        if self._log_config_unsub is None:
            self._log_config_unsub = watch_config_logging(
                self.kube_client, self.logger, namespace=self.options.system_namespace
            )
        # pod-watch → batcher trigger, the provisioning trigger controller
        # (provisioning/controller.go:58)
        from ..utils import pod as podutils

        if self.serving is not None:
            # the pipeline's ingest stage replaces the trigger controller
            self.serving.attach_watch()
            self.serving.start()
            self._pod_watch_unsub = None
        else:

            def on_pod(event, pod):
                if event != "DELETED" and podutils.is_provisionable(pod):
                    self.provisioner.trigger()

            self._pod_watch_unsub = self.kube_client.watch("Pod", on_pod)
        self._batching = True
        for c in self.controllers:
            c.start()
        self._started = True

    def stop(self) -> None:
        for c in self.controllers:
            c.stop()
        if self.serving is not None:
            self.serving.stop()
        unsub = getattr(self, "_pod_watch_unsub", None)
        if unsub is not None:
            unsub()
        if self._log_config_unsub is not None:
            self._log_config_unsub()
            self._log_config_unsub = None
        if self.elector is not None:
            self.elector.stop()
            self.elector = None
        if self.http is not None:
            self.http.stop()
            self.http = None
        self.informers.stop()
        self._started = False
        self._batching = False

    def reconcile_all_once(self) -> None:
        """Synchronous single pass over every controller (test/simulation
        driver)."""
        if not self._started:
            self.informers.start()
            self._started = True
        for c in self.controllers:
            c.reconcile_once()

    def healthy(self) -> bool:
        return self.cluster.synced()

    def metrics_text(self) -> str:
        return self.registry.expose()
