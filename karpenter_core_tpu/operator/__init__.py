from .options import Options
from .operator import Operator
from .controller import SingletonController
