"""Structured logging (ref pkg/operator/logging/logging.go): zap-style
leveled logger with key-value context."""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Optional

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO, "warn": logging.WARNING, "error": logging.ERROR}


class StructuredFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "level": record.levelname.lower(),
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "logger": record.name,
            "message": record.getMessage(),
        }
        extra = getattr(record, "kv", None)
        if extra:
            payload.update(extra)
        return json.dumps(payload)


class Logger:
    """knative-style sugar: .with_(k=v) returns a child carrying context."""

    def __init__(self, name: str = "controller", level: str = "info", kv: Optional[dict] = None):
        self._logger = logging.getLogger(name)
        if not self._logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(StructuredFormatter())
            self._logger.addHandler(handler)
            self._logger.propagate = False
        self._logger.setLevel(_LEVELS.get(level, logging.INFO))
        self.kv = kv or {}

    def set_level(self, level: str) -> None:
        self._logger.setLevel(_LEVELS.get(level, logging.INFO))

    def with_(self, **kv) -> "Logger":
        child = Logger.__new__(Logger)
        child._logger = self._logger
        child.kv = {**self.kv, **kv}
        return child

    def _log(self, level: int, msg: str, *args) -> None:
        self._logger.log(level, msg % args if args else msg, extra={"kv": self.kv})

    def debug(self, msg: str, *args) -> None:
        self._log(logging.DEBUG, msg, *args)

    def info(self, msg: str, *args) -> None:
        self._log(logging.INFO, msg, *args)

    def warn(self, msg: str, *args) -> None:
        self._log(logging.WARNING, msg, *args)

    def error(self, msg: str, *args) -> None:
        self._log(logging.ERROR, msg, *args)


def new_logger(level: str = "info") -> Logger:
    return Logger(level=level)


CONFIG_NAME = "config-logging"


def watch_config_logging(
    kube_client, logger: Logger, component: str = "controller", namespace: str = "default"
):
    """Drive the log level from the system namespace's ``config-logging``
    ConfigMap, live. The reference loads the same keys once at startup
    from mounted files (pkg/operator/logging/logging.go:47-167:
    ``loglevel.<component>`` wins, else the zap config JSON's "level")
    and fails hard on bad config; this build extends that to a live
    knative-observer-style watch, so bad config is rejected loudly but
    non-fatally instead. Only the operator's own namespace is honored —
    any other namespace's config-logging is ignored. Returns the
    watch's unsubscribe fn."""

    # the level to fall back to when the ConfigMap stops selecting one
    # (key removed, config deleted) — live config must be revertible
    base_level = logger._logger.level

    def _reject(value) -> None:
        # error level so the rejection survives whatever level the
        # (possibly broken) config itself selected
        logger.error("ignoring invalid log level %r from %s ConfigMap", value, CONFIG_NAME)

    def _apply(cm) -> None:
        # user-authored config: malformed JSON / non-dict / unknown
        # levels must never take down the watch (or Operator.__init__,
        # which receives a synchronous ADDED replay)
        try:
            level = cm.data.get(f"loglevel.{component}")
            if level is not None and level not in _LEVELS:
                _reject(level)  # bad override: reject, then fall back
                level = None
            if not level:
                raw = cm.data.get("zap-logger-config")
                if raw:
                    parsed = json.loads(raw)
                    if not isinstance(parsed, dict):
                        _reject(parsed)
                    else:
                        level = parsed.get("level")
                        if level is not None and not (isinstance(level, str) and level in _LEVELS):
                            _reject(level)
                            level = None
            if level:
                logger.set_level(level)
            else:
                logger._logger.setLevel(base_level)
        except Exception:
            logger.error("ignoring malformed %s ConfigMap", CONFIG_NAME)

    def _on_event(event: str, obj) -> None:
        if obj.name != CONFIG_NAME or obj.namespace != namespace:
            return
        if event in ("ADDED", "MODIFIED"):
            _apply(obj)
        elif event == "DELETED":
            logger._logger.setLevel(base_level)

    return kube_client.watch("ConfigMap", _on_event)
