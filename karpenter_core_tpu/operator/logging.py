"""Structured logging (ref pkg/operator/logging/logging.go): zap-style
leveled logger with key-value context."""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Optional

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO, "warn": logging.WARNING, "error": logging.ERROR}


class StructuredFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "level": record.levelname.lower(),
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "logger": record.name,
            "message": record.getMessage(),
        }
        extra = getattr(record, "kv", None)
        if extra:
            payload.update(extra)
        return json.dumps(payload)


class Logger:
    """knative-style sugar: .with_(k=v) returns a child carrying context."""

    def __init__(self, name: str = "controller", level: str = "info", kv: Optional[dict] = None):
        self._logger = logging.getLogger(name)
        if not self._logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(StructuredFormatter())
            self._logger.addHandler(handler)
            self._logger.propagate = False
        self._logger.setLevel(_LEVELS.get(level, logging.INFO))
        self.kv = kv or {}

    def with_(self, **kv) -> "Logger":
        child = Logger.__new__(Logger)
        child._logger = self._logger
        child.kv = {**self.kv, **kv}
        return child

    def _log(self, level: int, msg: str, *args) -> None:
        self._logger.log(level, msg % args if args else msg, extra={"kv": self.kv})

    def debug(self, msg: str, *args) -> None:
        self._log(logging.DEBUG, msg, *args)

    def info(self, msg: str, *args) -> None:
        self._log(logging.INFO, msg, *args)

    def warn(self, msg: str, *args) -> None:
        self._log(logging.WARNING, msg, *args)

    def error(self, msg: str, *args) -> None:
        self._log(logging.ERROR, msg, *args)


def new_logger(level: str = "info") -> Logger:
    return Logger(level=level)
