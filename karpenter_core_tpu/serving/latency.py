"""Decision-latency accounting: pod-pending → plan-emitted, the serving
pipeline's headline SLO (ROADMAP item 3 — pods/sec says how fast the
solver chews batches; decision latency says how long a *pod* waited for
its capacity decision, which is what a user-facing deployment feels).

The tracker is shared by the pipeline and the sequential baseline so the
two measure the identical interval: arrival is stamped in the pod-watch
callback (the moment the control plane could first have known about the
pod), decision when the authoritative step has emitted the pod's plan
(NodeClaim created / existing-node nomination / terminal error).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def percentiles_ms(samples_ms: Sequence[float], qs: Sequence[int] = (50, 95, 99)) -> dict:
    """{p50: .., p95: .., p99: ..} over latency samples, in ms (linear
    interpolation, numpy-free so bench helpers can share it)."""
    if not samples_ms:
        return {f"p{q}": 0.0 for q in qs}
    s = sorted(samples_ms)
    out = {}
    for q in qs:
        k = (len(s) - 1) * (q / 100.0)
        lo, hi = int(k), min(int(k) + 1, len(s) - 1)
        out[f"p{q}"] = round(s[lo] + (s[hi] - s[lo]) * (k - lo), 3)
    return out


class DecisionLatencyTracker:
    def __init__(self, clock=time.perf_counter, histogram=None):
        self._mu = threading.Lock()
        self.clock = clock
        self._histogram = histogram  # optional seconds Histogram
        # uid -> (arrival time, arrival step) for undecided pods
        self._pending: Dict[str, Tuple[float, Optional[int]]] = {}
        # (uid, latency_s, arrival_step, decided_tick, error?)
        self._samples: List[Tuple[str, float, Optional[int], int, bool]] = []
        # emit-order decision log: (tick, uid) — the monotonicity witness
        self._decision_log: List[Tuple[int, str]] = []

    # -- producers ----------------------------------------------------------

    def pod_pending(self, uid: str, step: Optional[int] = None) -> None:
        """First-seen-pending wins: re-listing an already-pending pod
        must not move its arrival time."""
        t = self.clock()
        with self._mu:
            self._pending.setdefault(uid, (t, step))

    def forget(self, uid: str) -> None:
        """Pod deleted before any decision (churn) — not a sample."""
        with self._mu:
            self._pending.pop(uid, None)

    def pods_decided(
        self,
        uids: Iterable[str],
        tick: int,
        error: bool = False,
        trace_id: Optional[str] = None,
    ) -> List[float]:
        """First decision wins (a later re-plan of a still-pending pod
        does not extend its measured latency). ``trace_id`` (the
        deciding solve's trace) rides the latency histogram as an
        exemplar, so a slow bucket names a loadable trace. Returns the
        latencies (seconds) settled by THIS call — the flight
        recorder's per-decision timeline input."""
        t = self.clock()
        hist = self._histogram
        settled: List[float] = []
        with self._mu:
            for uid in uids:
                arrived = self._pending.pop(uid, None)
                if arrived is None:
                    continue
                lat = t - arrived[0]
                self._samples.append((uid, lat, arrived[1], tick, error))
                self._decision_log.append((tick, uid))
                settled.append(lat)
                if hist is not None:
                    hist.observe(lat, exemplar=trace_id)
        return settled

    # -- consumers ----------------------------------------------------------

    def samples_ms(self, include_errors: bool = True) -> List[float]:
        with self._mu:
            return [
                s[1] * 1000.0 for s in self._samples if include_errors or not s[4]
            ]

    def percentiles(self, qs: Sequence[int] = (50, 95, 99)) -> dict:
        return percentiles_ms(self.samples_ms(), qs)

    def decisions(self) -> List[Tuple[str, float, Optional[int], int, bool]]:
        with self._mu:
            return list(self._samples)

    def decision_log(self) -> List[Tuple[int, str]]:
        with self._mu:
            return list(self._decision_log)

    def pending_count(self) -> int:
        with self._mu:
            return len(self._pending)

    def decided_count(self) -> int:
        with self._mu:
            return len(self._samples)

    def reset(self) -> None:
        with self._mu:
            self._pending.clear()
            self._samples.clear()
            self._decision_log.clear()
