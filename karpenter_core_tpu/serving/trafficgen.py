"""Production traffic simulator (ISSUE 6): replays production-shaped
pod streams against the serving pipeline through the fake kube client,
with a kubelet binder completing the loop (claim launch → node join →
pod binding) so decided pods leave the pending set exactly as they
would in a live cluster.

Scenarios (each deterministic given its seed):
  rollout     — deployment rollouts: team-by-team waves replace pods
                with a new revision whose requests differ (new
                signatures → real encode work per wave)
  spot_storm  — a spot-interruption storm: a large slice of BOUND pods
                evicted at once and re-created pending
  cascade     — cascading evictions: waves of growing size (5→10→20%)
  diurnal     — arrival-rate ramp up and back down
  churn10x    — the config-7 churn shape at 10× the rate: half the
                fleet swapped per step, concentrated on a few teams,
                with periodic catalog price mutation

Two drive modes:
  lockstep — scenario steps are the batch boundaries (inject, release,
             quiesce). Runs through the pipeline AND the sequential
             loop; the canonical plan streams must be byte-identical
             (the overlap-safety gate).
  free     — events paced on the wall clock, batches form by window:
             the decision-latency SLO measurement.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..apis import labels as wk
from ..apis.nodeclaim import (
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
)
from ..apis.nodepool import NodePool
from ..cloudprovider.fake import FakeCloudProvider, new_instance_type
from ..events import Recorder
from ..kube.client import KubeClient
from ..kube.objects import (
    Condition,
    Container,
    Node,
    NodeSelectorRequirement,
    Pod,
    PodCondition,
    PodSpec,
    ResourceRequirements,
)
from ..kube.quantity import parse_quantity
from ..metrics import Metrics
from ..provisioning import Provisioner
from ..state.cluster import Cluster
from ..state.informers import Informers
from .pipeline import PipelineConfig, SequentialLoop, ServingPipeline

_CPUS = ["100m", "250m", "500m", "1", "2", "4"]
_MEMS = ["128Mi", "512Mi", "1Gi", "2Gi", "4Gi"]


# ---------------------------------------------------------------------------
# scenario model: pure data, materialized to Pod objects at injection time
# so two runs of the same scenario inject identical streams


@dataclass(frozen=True)
class PodSpecLite:
    name: str
    cpu: str
    mem: str
    gpu: Optional[str]
    team: int


@dataclass
class Step:
    creates: List[PodSpecLite] = field(default_factory=list)
    # names of live pods to evict: delete (bound or not) and re-create
    # pending under a fresh name with the same shape
    evicts: List[str] = field(default_factory=list)
    # names of live pods to delete outright (scale-down)
    deletes: List[str] = field(default_factory=list)
    mutate_catalog: bool = False


@dataclass
class Scenario:
    name: str
    seed: int
    teams: int
    steps: List[Step]

    @property
    def total_creates(self) -> int:
        return sum(len(s.creates) for s in self.steps)


class _NameGen:
    def __init__(self, scenario: str):
        self.scenario = scenario
        self.n = 0

    def next(self) -> str:
        self.n += 1
        return f"{self.scenario}-p{self.n:06d}"


def _mk_spec(names: _NameGen, rng, team: int, rev: int = 0) -> PodSpecLite:
    """One pod shape. ``rev`` models a deployment revision: real
    rollouts ship new resource requests, so each revision's sizes are a
    fresh request quantum — fresh signatures the encoder has never seen
    (what keeps 10×-churn from degenerating into an all-cached replay)."""
    cpu_m = [100, 250, 500, 1000, 2000, 4000][rng.randint(6)] + (rev % 97)
    mem_mi = [128, 512, 1024, 2048, 4096][rng.randint(5)] + (rev % 97)
    return PodSpecLite(
        name=names.next(),
        cpu=f"{cpu_m}m",
        mem=f"{mem_mi}Mi",
        gpu="1" if rng.rand() < 0.1 else None,
        team=team,
    )


class _LivePods:
    """Scenario-construction-time mirror of which pods are alive, so
    evict/delete selections are deterministic data, not runtime
    choices."""

    def __init__(self):
        self.by_name: Dict[str, PodSpecLite] = {}

    def add(self, specs: List[PodSpecLite]) -> None:
        for s in specs:
            self.by_name[s.name] = s

    def remove(self, names: List[str]) -> None:
        for n in names:
            self.by_name.pop(n, None)

    def pick(self, rng, frac: float, teams: Optional[List[int]] = None) -> List[PodSpecLite]:
        pool = sorted(self.by_name)
        if teams is not None:
            tset = set(teams)
            pool = [n for n in pool if self.by_name[n].team in tset]
        k = max(1, int(len(pool) * frac)) if pool else 0
        if not k:
            return []
        idx = rng.choice(len(pool), size=min(k, len(pool)), replace=False)
        return [self.by_name[pool[i]] for i in sorted(idx)]

    def pick_concentrated(self, rng, count: int, teams: List[int]) -> List[PodSpecLite]:
        """``count`` pods, drawn from ``teams`` first and spilling
        uniformly once those are exhausted (a deployment-rollout shape
        at rates the hit teams alone can't supply)."""
        tset = set(teams)
        pool = sorted(self.by_name)
        hit = [n for n in pool if self.by_name[n].team in tset]
        rest = [n for n in pool if self.by_name[n].team not in tset]
        chosen = hit[:count]
        short = count - len(chosen)
        if short > 0 and rest:
            idx = rng.choice(len(rest), size=min(short, len(rest)), replace=False)
            chosen += [rest[i] for i in sorted(idx)]
        return [self.by_name[n] for n in chosen]


def _base_steps(names: _NameGen, live: _LivePods, rng, n_pods: int, teams: int) -> Step:
    specs = [_mk_spec(names, rng, t % teams) for t in range(n_pods)]
    live.add(specs)
    return Step(creates=specs)


def scenario_rollout(scale: int = 1000, teams: int = 10, seed: int = 101, waves: int = 8) -> Scenario:
    rng = np.random.RandomState(seed)
    names = _NameGen("rollout")
    live = _LivePods()
    steps = [_base_steps(names, live, rng, scale, teams)]
    for w in range(waves):
        team = int(w % teams)
        old = live.pick(rng, 1.0, teams=[team])
        # the new revision: same team, revision-bumped sizes (a fresh
        # request shape per wave is what a real image+resources bump
        # looks like)
        new = [_mk_spec(names, rng, team, rev=w + 1) for _ in old]
        live.remove([s.name for s in old])
        live.add(new)
        steps.append(Step(creates=new, evicts=[s.name for s in old]))
    return Scenario("rollout", seed, teams, steps)


def scenario_spot_storm(scale: int = 1000, teams: int = 10, seed: int = 102) -> Scenario:
    rng = np.random.RandomState(seed)
    names = _NameGen("spotstorm")
    live = _LivePods()
    steps = [_base_steps(names, live, rng, scale, teams)]
    # steady trickle, then the storm: 30% of the fleet interrupted at once
    for _ in range(2):
        trickle = [_mk_spec(names, rng, int(rng.randint(teams))) for _ in range(max(1, scale // 50))]
        live.add(trickle)
        steps.append(Step(creates=trickle))
    storm = live.pick(rng, 0.30)
    replacements = [_mk_spec(names, rng, s.team, rev=1) for s in storm]
    live.remove([s.name for s in storm])
    live.add(replacements)
    steps.append(Step(creates=replacements, evicts=[s.name for s in storm]))
    # recovery trickle
    trickle = [_mk_spec(names, rng, int(rng.randint(teams))) for _ in range(max(1, scale // 50))]
    live.add(trickle)
    steps.append(Step(creates=trickle))
    return Scenario("spot_storm", seed, teams, steps)


def scenario_cascade(scale: int = 1000, teams: int = 10, seed: int = 103) -> Scenario:
    rng = np.random.RandomState(seed)
    names = _NameGen("cascade")
    live = _LivePods()
    steps = [_base_steps(names, live, rng, scale, teams)]
    for i, frac in enumerate((0.05, 0.10, 0.20)):
        wave = live.pick(rng, frac)
        repl = [_mk_spec(names, rng, s.team, rev=i + 1) for s in wave]
        live.remove([s.name for s in wave])
        live.add(repl)
        steps.append(Step(creates=repl, evicts=[s.name for s in wave]))
    return Scenario("cascade", seed, teams, steps)


def scenario_diurnal(scale: int = 1000, teams: int = 10, seed: int = 104) -> Scenario:
    rng = np.random.RandomState(seed)
    names = _NameGen("diurnal")
    live = _LivePods()
    steps = []
    profile = [0.125, 0.25, 0.5, 1.0, 0.5, 0.25, 0.125]
    for load in profile:
        n = max(1, int(scale * load / 4))
        specs = [_mk_spec(names, rng, int(rng.randint(teams))) for _ in range(n)]
        live.add(specs)
        step = Step(creates=specs)
        # down-ramp: scale the oldest pods away
        if len(live.by_name) > scale and load < 1.0:
            victims = sorted(live.by_name)[: n // 2]
            live.remove(victims)
            step.deletes = victims
        steps.append(step)
    return Scenario("diurnal", seed, teams, steps)


def scenario_churn10x(
    scale: int = 1000, teams: int = 20, seed: int = 105, ticks: int = 10, churn: float = 0.5
) -> Scenario:
    """Config 7's churn shape at 10× its 5% rate: per step, ``churn`` of
    the WHOLE fleet swapped — concentrated on teams//10 teams, spilling
    uniformly beyond them (10× is more than two teams hold) — with
    catalog price mutation every 4th step."""
    rng = np.random.RandomState(seed)
    names = _NameGen("churn10x")
    live = _LivePods()
    steps = [_base_steps(names, live, rng, scale, teams)]
    for tick in range(ticks):
        if tick > 0 and tick % 4 == 0:
            # a spot-price storm: catalog mutation arrives as its own
            # event between churn waves (price feeds are asynchronous
            # to pod traffic — they never ride along with a rollout)
            steps.append(Step(mutate_catalog=True))
        hit = rng.choice(teams, max(1, teams // 10), replace=False)
        swap = live.pick_concentrated(
            rng, max(1, int(len(live.by_name) * churn)), [int(t) for t in hit]
        )
        repl = [_mk_spec(names, rng, s.team, rev=tick + 1) for s in swap]
        live.remove([s.name for s in swap])
        live.add(repl)
        steps.append(Step(creates=repl, evicts=[s.name for s in swap]))
    return Scenario("churn10x", seed, teams, steps)


def scenario_restart_wave(
    scale: int = 800, teams: int = 10, seed: int = 106, waves: int = 12
) -> Scenario:
    """Config-7-shaped steady redeploy churn for the restart scenario
    (ISSUE 13): each wave rolls one team — evict its live pods, re-create
    the SAME shape multiset under fresh names (steady replicas of a
    stable deployment, the common production case; rollout's per-wave
    revision bumps model the rarer size-changing deploy). Shapes are
    drawn per (seed, team), so a wave's request matrices are
    content-identical to that team's earlier waves — exactly the content
    a restarted process's restored job memos can serve. One catalog
    price mutation early in the run keeps the snapshotted world honest
    (the snapshot must reflect post-mutation prices)."""
    rng = np.random.RandomState(seed)
    names = _NameGen("restart")
    live = _LivePods()

    def team_shapes(team: int, count: int) -> List[tuple]:
        trng = np.random.RandomState(seed * 1009 + team)
        return [
            (
                f"{[100, 250, 500, 1000, 2000, 4000][trng.randint(6)]}m",
                f"{[128, 512, 1024, 2048, 4096][trng.randint(5)]}Mi",
                "1" if trng.rand() < 0.1 else None,
            )
            for _ in range(count)
        ]

    per_team = max(1, scale // teams)
    base: List[PodSpecLite] = []
    for t in range(teams):
        base.extend(
            PodSpecLite(names.next(), cpu, mem, gpu, t)
            for cpu, mem, gpu in team_shapes(t, per_team)
        )
    live.add(base)
    steps = [Step(creates=base)]
    for w in range(waves):
        if w == 1:
            steps.append(Step(mutate_catalog=True))
        team = int(w % teams)
        old = live.pick(rng, 1.0, teams=[team])
        new = [
            PodSpecLite(names.next(), cpu, mem, gpu, team)
            for cpu, mem, gpu in team_shapes(team, len(old))
        ]
        live.remove([s.name for s in old])
        live.add(new)
        steps.append(Step(creates=new, evicts=[s.name for s in old]))
    return Scenario("restart_wave", seed, teams, steps)


SCENARIOS: Dict[str, Callable[..., Scenario]] = {
    "rollout": scenario_rollout,
    "spot_storm": scenario_spot_storm,
    "cascade": scenario_cascade,
    "diurnal": scenario_diurnal,
    "churn10x": scenario_churn10x,
    "restart_wave": scenario_restart_wave,
}


def build_scenario(name: str, scale: int = 1000, seed: Optional[int] = None) -> Scenario:
    fn = SCENARIOS[name]
    return fn(scale=scale) if seed is None else fn(scale=scale, seed=seed)


# ---------------------------------------------------------------------------
# harness: kube + cluster + provider + provisioner + kubelet binder


def _catalog(n_types: int) -> List:
    cat = [
        new_instance_type(
            f"st-{i}",
            {"cpu": str((i % 64) + 1), "memory": f"{2 * ((i % 64) + 1)}Gi", "pods": "110"},
        )
        for i in range(max(1, n_types - 8))
    ]
    for g in range(min(8, n_types)):
        cat.append(
            new_instance_type(
                f"st-gpu-{g}",
                {
                    "cpu": str(8 * (g + 1)),
                    "memory": f"{16 * (g + 1)}Gi",
                    "pods": "110",
                    "nvidia.com/gpu": str(min(8, g + 1)),
                },
            )
        )
    return cat


def materialize_spec(spec: PodSpecLite) -> Pod:
    """A pending Pod from one scenario spec (shared by the serving
    harness and the fleet driver)."""
    pod = Pod()
    pod.metadata.name = spec.name
    pod.metadata.labels = {"team": f"t{spec.team}"}
    requests = {"cpu": parse_quantity(spec.cpu), "memory": parse_quantity(spec.mem)}
    if spec.gpu:
        requests["nvidia.com/gpu"] = parse_quantity(spec.gpu)
    pod.spec = PodSpec(
        node_selector={"team": f"t{spec.team}"},
        containers=[
            Container(name="main", resources=ResourceRequirements(requests=requests))
        ],
    )
    pod.status.conditions = [
        PodCondition(type="PodScheduled", status="False", reason="Unschedulable")
    ]
    return pod


class TrafficHarness:
    """One self-contained serving world. Create one per run — plan
    identity is compared across runs, so runs must not share mutable
    state (each gets its own provider, and with it its own warm-state
    entry).

    ``restore`` (a ``dump_state()`` payload from a killed process)
    rebuilds the apiserver world instead of starting empty: objects
    re-create in store order so informers rebuild identical cluster
    state, name/claim sequences fast-forward, and pods re-enter WITHOUT
    their ``_karp_memo`` — a restarted process reads pods from the
    apiserver, and the old process's interned ids must never leak into
    the new interner's id space."""

    def __init__(
        self,
        teams: int = 20,
        n_types: int = 96,
        metrics: Optional[Metrics] = None,
        restore: Optional[dict] = None,
    ):
        self.kube = KubeClient()
        self.provider = FakeCloudProvider()
        self.provider.instance_types = (
            list(restore["catalog"]) if restore is not None else _catalog(n_types)
        )
        self.provider.bump_catalog_generation()  # harness owns invalidation
        self.cluster = Cluster(self.kube, self.provider)
        self.informers = Informers(self.kube, self.cluster)
        self.informers.start()
        self.recorder = Recorder(self.kube)
        self.metrics = metrics or Metrics()
        if restore is None:
            self.nodepool = NodePool()
            self.nodepool.metadata.name = "default"
            self.nodepool.spec.template.requirements = [
                NodeSelectorRequirement("team", "In", [f"t{t}" for t in range(teams)])
            ]
            self.kube.create(self.nodepool)
        else:
            from ..kube.objects import resume_name_sequence
            from ..solver import podcache

            for kind, obj in restore["objects"]:
                if kind == "Pod":
                    obj.__dict__.pop("_karp_memo", None)
                self.kube.create(obj)
            self.nodepool = self.kube.get("NodePool", "default")
            resume_name_sequence(restore["name_mark"])
            # the memo maps must be empty (fresh-interpreter contract):
            # any surviving memo would carry the dead process's ids
            podcache.reset()
        self.provisioner = Provisioner(
            self.kube,
            self.provider,
            self.cluster,
            recorder=self.recorder,
            use_tpu_solver=True,
            metrics=self.metrics,
        )
        self._node_seq = restore["node_seq"] if restore is not None else 0
        # catalog-event fanout: the serving pipeline's catalog ingest
        # (observe_catalog_event), wired per run mode
        self.on_catalog_event: Optional[Callable[[], None]] = None
        # arrival bookkeeping for the parity test: pod uid -> (name, step)
        self.arrivals: Dict[str, Tuple[str, int]] = {}
        self.uid_to_name: Dict[str, str] = {}
        self._live: Dict[str, Pod] = {}  # name -> live Pod object
        if restore is not None:
            self.arrivals = {u: tuple(v) for u, v in restore["arrivals"].items()}
            self.uid_to_name = dict(restore["uid_to_name"])
            for name in restore["live_names"]:
                pod = self.kube.get("Pod", name)
                if pod is not None:
                    self._live[name] = pod

    def dump_state(self) -> dict:
        """Serialize the apiserver world + harness bookkeeping for a
        process handoff (the kill-the-process-mid-stream scenario): the
        durable state a real restart would re-read from the apiserver
        and the cloud provider, nothing from the solver's memory."""
        from ..kube.objects import name_sequence_mark

        objects = []
        # claims before their nodes, nodes before the pods bound to them
        # — re-creation replays the live flow's event order
        for kind in ("NodePool", "DaemonSet", "NodeClaim", "Node", "Pod"):
            for obj in self.kube.list(kind):
                objects.append((kind, obj))
        return {
            "version": 1,
            "objects": objects,
            "catalog": list(self.provider.instance_types),
            "node_seq": self._node_seq,
            "name_mark": name_sequence_mark(),
            "arrivals": {u: list(v) for u, v in self.arrivals.items()},
            "uid_to_name": dict(self.uid_to_name),
            "live_names": sorted(self._live),
        }

    # -- injection ----------------------------------------------------------

    def _materialize(self, spec: PodSpecLite) -> Pod:
        return materialize_spec(spec)

    def inject_step(self, step: Step, step_index: int) -> None:
        """Apply one scenario step to the kube store (deletes/evictions
        first — the replacements in ``creates`` arrive after the
        interruption, like real controllers re-creating pods)."""
        for name in step.deletes:
            pod = self._live.pop(name, None)
            if pod is not None:
                self.kube.delete(pod)
        for name in step.evicts:
            pod = self._live.pop(name, None)
            if pod is not None:
                self.kube.delete(pod)
        if step.mutate_catalog:
            its = self.provider.get_instance_types(self.nodepool)
            for it in its[:: max(1, len(its) // 16)]:
                for o in it.offerings:
                    o.price *= 1.01
            self.provider.bump_catalog_generation()
            if self.on_catalog_event is not None:
                self.on_catalog_event()
        for spec in step.creates:
            pod = self._materialize(spec)
            self.kube.create(pod)
            self._live[spec.name] = pod
            self.uid_to_name[pod.uid] = spec.name
            self.arrivals[pod.uid] = (spec.name, step_index)

    # -- kubelet binder (the on_decision hook) -------------------------------

    def bind(self, tick: int, results) -> None:
        """Complete each emitted plan's lifecycle synchronously on the
        authoritative thread: launch the claim, join its node, bind the
        pods — so the next tick's pending listing is exactly 'everything
        not yet decided', in both pipeline and sequential modes."""
        for plan in getattr(results, "tpu_plans", []) or []:
            name = getattr(plan, "created_claim_name", None)
            if not name:
                continue
            self._launch_and_bind(name, plan.instance_type, plan.zone, plan.capacity_type, plan.pods)
        for claim in results.new_node_claims:
            name = getattr(claim, "created_claim_name", None)
            if not name or not claim.instance_type_options:
                continue
            it = claim.instance_type_options[0]
            off = it.offerings.available()
            zone = off[0].zone if off else "test-zone-1"
            ct = off[0].capacity_type if off else wk.CAPACITY_TYPE_ON_DEMAND
            self._launch_and_bind(name, it, zone, ct, claim.pods)
        for plan in getattr(results, "existing_plans", []) or []:
            self._bind_pods(plan.state_node.name(), getattr(plan, "pods", []) or [])
        for ex in results.existing_nodes:
            self._bind_pods(ex.state_node.name(), ex.pods)

    def _launch_and_bind(self, claim_name: str, it, zone: str, ct: str, pods) -> None:
        nc = self.kube.get("NodeClaim", claim_name)
        if nc is None:
            return
        self._node_seq += 1
        provider_id = f"fake:///serve-{self._node_seq:06d}"
        nc.status.provider_id = provider_id
        nc.status.capacity = dict(it.capacity)
        nc.status.allocatable = it.allocatable()
        for cond in (COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED):
            nc.set_condition(cond, "True")
        self.kube.update(nc)
        node = Node()
        node.metadata.name = f"node-{claim_name}"
        node.metadata.labels = {
            **nc.metadata.labels,
            wk.LABEL_INSTANCE_TYPE: it.name,
            wk.LABEL_TOPOLOGY_ZONE: zone,
            wk.CAPACITY_TYPE_LABEL_KEY: ct,
            wk.LABEL_HOSTNAME: f"node-{claim_name}",
            wk.NODE_REGISTERED_LABEL_KEY: "true",
            wk.NODE_INITIALIZED_LABEL_KEY: "true",
        }
        node.spec.provider_id = provider_id
        node.status.capacity = dict(it.capacity)
        node.status.allocatable = it.allocatable()
        node.status.conditions = [Condition(type="Ready", status="True")]
        self.kube.create(node)
        self._bind_pods(node.metadata.name, pods)

    def _bind_pods(self, node_name: str, pods) -> None:
        for pod in pods:
            pod.spec.node_name = node_name
            pod.status.phase = "Running"
            pod.status.conditions = []
            self.kube.apply(pod)

    def warmup(self) -> None:
        """Pay one-time costs (jit compile, catalog encode) outside the
        measured window, then clear their traces/latency effects."""
        from ..solver import TPUScheduler

        warm_pod = self._materialize(PodSpecLite("warmup-0", "250m", "256Mi", None, 0))
        TPUScheduler([self.nodepool], self.provider).solve([warm_pod])

    def warmup_compile_only(
        self, n_pods: int = 64, pay_compiles: bool = True
    ) -> Optional[dict]:
        """Backend/jit warmup that does NOT touch this harness's
        catalog entry: the restart phases (ISSUE 13) measure the first
        post-restart solve, and the catalog re-encode is exactly the
        cold cost the warm-state snapshot exists to skip — warming it
        here would flatter the cold baseline. A content-DISJOINT
        throwaway catalog of the same size (fresh names → fresh
        fingerprint → its own cache entry) pays backend init and the
        shape-keyed XLA kernel compiles.

        ``pay_compiles=False`` (the ISSUE-17 cold-resume lane) pays
        backend init ONLY and leaves the kernel compiles to the first
        measured solve. Under PR 13 both restart modes paid the
        compiles identically outside the window, so pre-paying them
        was neutral; the managed executable cache breaks that symmetry
        — a warm resume genuinely never compiles again, so a cold
        baseline that quietly pre-compiles would understate the
        restore win and flatter itself. A real unsnapshot restart pays
        trace+lower+compile inside its first solve; the cold lane must
        too.

        ISSUE 17: after the synthetic solve, any jitsig inventory rows
        already restored into this process replay through
        ``solver.prewarm.warmup_compile_only`` — the SAME code the
        serving pipeline's boot replay and fleet admission run, so
        bench lanes and the production boot path cannot drift. On cold
        baselines the registry holds no restored rows and the replay is
        an empty no-op. Returns the replay outcome (None only if the
        solve path failed before the replay)."""
        from ..apis.nodepool import NodePool as _NodePool
        from ..solver import TPUScheduler, backend, prewarm

        if not pay_compiles:
            backend.default_backend()  # transport/client init only
            return prewarm.warmup_compile_only(None)
        provider = FakeCloudProvider()
        warm_cat = _catalog(len(self.provider.instance_types))
        for it in warm_cat:
            it.name = f"warm-{it.name}"
        provider.instance_types = warm_cat
        provider.bump_catalog_generation()
        np_ = _NodePool()
        np_.metadata.name = "warmup"
        pods = []
        for i in range(max(1, n_pods)):
            pod = self._materialize(
                PodSpecLite(f"warmup-{i}", _CPUS[i % len(_CPUS)], _MEMS[i % len(_MEMS)], None, 0)
            )
            pod.spec.node_selector = {}
            pods.append(pod)
        sched = TPUScheduler([np_], provider)
        sched.solve(pods)
        return prewarm.warmup_compile_only(sched)

    def close(self) -> None:
        self.informers.stop()


# ---------------------------------------------------------------------------
# runners


@dataclass
class RunResult:
    mode: str
    scenario: str
    plan_stream: List[tuple] = field(default_factory=list)  # per non-empty tick
    decisions: List[Tuple[int, str]] = field(default_factory=list)  # (tick, pod name)
    arrivals: Dict[str, int] = field(default_factory=dict)  # pod name -> step
    latency_ms: dict = field(default_factory=dict)
    samples_ms: List[float] = field(default_factory=list)
    # steady-phase slice: pods that arrived AFTER the initial base-load
    # step — the cold ramp is a restart artifact, the SLO is steady state
    steady_samples_ms: List[float] = field(default_factory=list)
    wall_s: float = 0.0
    ticks: int = 0
    pods_decided: int = 0
    errors: int = 0
    stage_stats: dict = field(default_factory=dict)

    def plan_bytes(self) -> bytes:
        """The byte-identity witness: the canonical plan stream,
        serialized."""
        return repr(self.plan_stream).encode()


def _canon_results(harness: TrafficHarness, results) -> Optional[tuple]:
    """Canonical, run-comparable identity of one tick's emitted plans
    (pods keyed by name — uids differ across runs)."""
    plans = []
    for plan in getattr(results, "tpu_plans", []) or []:
        if not getattr(plan, "created_claim_name", None):
            continue
        plans.append(
            (
                plan.nodepool_name,
                plan.instance_type.name,
                plan.zone,
                plan.capacity_type,
                round(plan.price, 9),
                tuple(sorted(p.metadata.name for p in plan.pods)),
            )
        )
    for claim in results.new_node_claims:
        if not getattr(claim, "created_claim_name", None):
            continue
        plans.append(
            (
                claim.nodepool_name,
                "oracle",
                tuple(sorted(p.metadata.name for p in claim.pods)),
            )
        )
    for plan in getattr(results, "existing_plans", []) or []:
        pods = getattr(plan, "pods", []) or []
        plans.append(("existing", plan.state_node.name(), tuple(sorted(p.metadata.name for p in pods))))
    errors = tuple(
        sorted(harness.uid_to_name.get(uid, uid) for uid in results.pod_errors)
    )
    if not plans and not errors:
        return None
    return (tuple(sorted(plans)), errors)


class _StreamRecorder:
    """Wraps the harness binder to also record the canonical plan
    stream in emit order (it runs on the authoritative thread, so the
    stream order IS the observable emit order)."""

    def __init__(self, harness: TrafficHarness):
        self.harness = harness
        self.stream: List[tuple] = []
        self.decision_ticks: List[Tuple[int, str]] = []

    def __call__(self, tick: int, results) -> None:
        canon = _canon_results(self.harness, results)
        if canon is not None:
            self.stream.append(canon)
            for plan_key in canon[0]:
                for pod_name in plan_key[-1]:
                    self.decision_ticks.append((tick, pod_name))
        self.harness.bind(tick, results)


def _finalize_result(
    rr: RunResult, harness: TrafficHarness, rec: _StreamRecorder, latency, wall_s: float
) -> RunResult:
    rr.plan_stream = rec.stream
    rr.decisions = rec.decision_ticks
    rr.arrivals = {name: step for (name, step) in harness.arrivals.values()}
    rr.latency_ms = latency.percentiles()
    rr.samples_ms = latency.samples_ms()
    rr.steady_samples_ms = [
        lat * 1000.0
        for (uid, lat, _step, _tick, _err) in latency.decisions()
        if harness.arrivals.get(uid, ("", 0))[1] >= 1
    ]
    rr.wall_s = round(wall_s, 3)
    rr.pods_decided = latency.decided_count()
    rr.errors = sum(1 for d in latency.decisions() if d[4])
    return rr


def run_lockstep(
    scenario: Scenario,
    mode: str = "pipeline",
    teams: Optional[int] = None,
    config: Optional[PipelineConfig] = None,
    quiesce_timeout: float = 60.0,
) -> RunResult:
    """Drive the scenario with steps as batch boundaries; plans recorded
    per tick. Pipeline mode runs with full stage concurrency (prewarm
    racing the authoritative solve) — only the batch boundary is
    pinned."""
    harness = TrafficHarness(teams=teams or scenario.teams)
    rec = _StreamRecorder(harness)
    config = config or PipelineConfig(
        idle_seconds=0.02, max_seconds=1.0, solve_queue_cap=1, telemetry_queue_cap=1024
    )
    rr = RunResult(mode=mode, scenario=scenario.name)
    harness.warmup()
    t0 = time.perf_counter()
    if mode == "pipeline":
        pipe = ServingPipeline(
            harness.provisioner, metrics=harness.metrics, config=config, on_decision=rec
        )
        harness.on_catalog_event = pipe.observe_catalog_event
        pipe.attach_watch()
        pipe.hold()
        pipe.start()
        try:
            for i, step in enumerate(scenario.steps):
                harness.inject_step(step, i)
                pipe.release()
                if not pipe.quiesce(timeout=quiesce_timeout):
                    raise TimeoutError(
                        f"pipeline failed to quiesce at step {i} of {scenario.name}"
                    )
                pipe.hold()
            latency = pipe.latency
            rr.ticks = pipe.ticks()
            rr.stage_stats = pipe.debug_state()
        finally:
            pipe.stop()
    elif mode == "sequential":
        loop = SequentialLoop(
            harness.provisioner, metrics=harness.metrics, config=config, on_decision=rec
        )
        loop.attach_watch()
        try:
            for i, step in enumerate(scenario.steps):
                harness.inject_step(step, i)
                loop.step_once()
            latency = loop.latency
            rr.ticks = loop.ticks()
        finally:
            loop.stop()
    else:
        raise ValueError(f"unknown mode {mode!r}")
    out = _finalize_result(rr, harness, rec, latency, time.perf_counter() - t0)
    harness.close()
    return out


def monotonic_decision_order(rr: RunResult) -> bool:
    """The ordering witness: emitted decisions carry non-decreasing tick
    ordinals (the authoritative thread never reorders observable
    state), and no pod is decided twice."""
    last = 0
    seen = set()
    for tick, name in rr.decisions:
        if tick < last or name in seen:
            return False
        last = tick
        seen.add(name)
    return True


def run_free(
    scenario: Scenario,
    mode: str = "pipeline",
    pace_s: float = 0.05,
    teams: Optional[int] = None,
    config: Optional[PipelineConfig] = None,
    drain_timeout: float = 120.0,
) -> RunResult:
    """Free-running mode: steps injected on a wall-clock pace while the
    serving loop forms its own batches — the decision-latency SLO
    measurement. Identical config for both modes keeps the comparison
    honest (the pipeline's edge is overlap, not a smaller window)."""
    harness = TrafficHarness(teams=teams or scenario.teams)
    rec = _StreamRecorder(harness)
    config = config or PipelineConfig(
        idle_seconds=0.02, max_seconds=0.5, solve_queue_cap=1, telemetry_queue_cap=1024
    )
    rr = RunResult(mode=mode, scenario=scenario.name)
    harness.warmup()
    if mode == "pipeline":
        serve = ServingPipeline(
            harness.provisioner, metrics=harness.metrics, config=config, on_decision=rec
        )
        harness.on_catalog_event = serve.observe_catalog_event
    elif mode == "sequential":
        serve = SequentialLoop(
            harness.provisioner, metrics=harness.metrics, config=config, on_decision=rec
        )
    else:
        raise ValueError(f"unknown mode {mode!r}")
    serve.attach_watch()
    serve.start()
    t0 = time.perf_counter()
    try:
        for i, step in enumerate(scenario.steps):
            harness.inject_step(step, i)
            if pace_s:
                time.sleep(pace_s)
        # drain: every injected pod decided (or the timeout names the jam)
        deadline = time.monotonic() + drain_timeout
        while time.monotonic() < deadline:
            if serve.latency.pending_count() == 0:
                break
            time.sleep(0.005)
        rr.ticks = serve.ticks()
        if hasattr(serve, "debug_state"):
            rr.stage_stats = serve.debug_state()
        latency = serve.latency
    finally:
        serve.stop()
    out = _finalize_result(rr, harness, rec, latency, time.perf_counter() - t0)
    harness.close()
    return out


# ---------------------------------------------------------------------------
# kill-the-process-mid-stream (ISSUE 13): snapshot on quiesce, restart
# subprocess, restore, resume the stream. The kill phase and each resume
# phase run in their OWN processes (the config-8 pyperf discipline —
# a resumed process must inherit nothing but the handoff + snapshot
# files); plan streams concatenate across the kill point and must hash
# identical to an unkilled reference run.


def _restart_config() -> PipelineConfig:
    # prewarm off: the measurement is the FIRST authoritative solve
    # after restart — a racing speculative encode would warm the caches
    # between release and solve and blur the cold/warm contrast (plan
    # identity is unaffected either way). The ISSUE-17 boot jitsig
    # replay is NOT this knob: it runs only on restored inventory rows
    # (part of the warm path under measurement) and is a no-op on the
    # cold lane, whose measured first solve pays the real XLA compiles
    # (warmup_compile_only(pay_compiles=False) — backend init only).
    return PipelineConfig(
        idle_seconds=0.02, max_seconds=1.0, solve_queue_cap=1,
        telemetry_queue_cap=1024, prewarm=False,
        warmstore_dir=None, warmstore_restore=None,
    )


def _drive_steps(pipe, harness, steps, first_index, quiesce_timeout):
    """Lockstep-drive ``steps`` through a held pipeline; returns the
    per-solve tick records (step_ms/solve_host_ms of ticks that decided
    pods) and the last quiesce() return (the snapshot path when the
    pipeline's warmstore_dir is set for the final step)."""
    solve_ticks: List[dict] = []
    seen = set()
    out = True
    for i, step in enumerate(steps):
        harness.inject_step(step, first_index + i)
        pipe.release()
        out = pipe.quiesce(timeout=quiesce_timeout)
        if not out:
            raise TimeoutError(f"pipeline failed to quiesce at resumed step {first_index + i}")
        pipe.hold()
        for tick_rec in pipe.debug_state()["last_ticks"]:
            if tick_rec.get("tick") in seen:
                continue
            seen.add(tick_rec.get("tick"))
            if tick_rec.get("decided", 0) > 0:
                solve_ticks.append(
                    {
                        "tick": tick_rec.get("tick"),
                        "step_ms": tick_rec.get("step_ms", 0.0),
                        "solve_host_ms": tick_rec.get("solve_host_ms", 0.0),
                        "solve_compiles": tick_rec.get("solve_compiles"),
                    }
                )
    return solve_ticks, out


def run_restart_kill(
    scenario_name: str,
    kill_step: int,
    workdir: str,
    scale: int = 800,
    seed: Optional[int] = None,
    teams: Optional[int] = None,
    n_types: int = 480,
    quiesce_timeout: float = 120.0,
) -> dict:
    """Phase A of the kill scenario: drive steps [0, kill_step) through
    a serving pipeline, quiesce (which snapshots the warm planes and
    returns the snapshot path), dump the apiserver world + partial plan
    stream to ``workdir/handoff.pkl``, and return a summary. The caller
    then EXITS — everything the resumed process may use is on disk."""
    sc = build_scenario(scenario_name, scale=scale, seed=seed)
    if not 0 < kill_step < len(sc.steps):
        raise ValueError(f"kill_step must be in (0, {len(sc.steps)}), got {kill_step}")
    harness = TrafficHarness(teams=teams or sc.teams, n_types=n_types)
    rec = _StreamRecorder(harness)
    pipe = ServingPipeline(
        harness.provisioner, metrics=harness.metrics, config=_restart_config(),
        on_decision=rec,
    )
    harness.on_catalog_event = pipe.observe_catalog_event
    harness.warmup_compile_only()
    pipe.attach_watch()
    pipe.hold()
    pipe.start()
    try:
        solve_ticks, _ = _drive_steps(
            pipe, harness, sc.steps[: kill_step - 1], 0, quiesce_timeout
        )
        # final pre-kill step: arm the snapshot — quiesce() returns the
        # snapshot path (the satellite contract: no side channel needed
        # to hand the restarted process its warm state)
        pipe.config.warmstore_dir = workdir
        last_ticks, path = _drive_steps(
            pipe, harness, [sc.steps[kill_step - 1]], kill_step - 1, quiesce_timeout
        )
        solve_ticks.extend(last_ticks)
        snapshot_path = path if isinstance(path, str) else None
    finally:
        pipe.stop()
    steady = [t["step_ms"] for t in solve_ticks[1:]] or [t["step_ms"] for t in solve_ticks]
    handoff = harness.dump_state()
    handoff.update(
        scenario=scenario_name, scale=scale, seed=sc.seed, teams=teams or sc.teams,
        n_types=n_types, kill_step=kill_step,
        plan_stream=rec.stream, decision_ticks=rec.decision_ticks,
        snapshot_path=snapshot_path,
        steady_step_ms_p50=float(np.median(steady)) if steady else 0.0,
    )
    handoff_path = os.path.join(workdir, "handoff.pkl")
    import pickle

    with open(handoff_path, "wb") as f:
        pickle.dump(handoff, f, protocol=4)
    harness.close()
    return {
        "phase": "kill",
        "scenario": scenario_name,
        "kill_step": kill_step,
        "steps_driven": kill_step,
        "snapshot_path": snapshot_path,
        "handoff_path": handoff_path,
        "plans_emitted": len(rec.stream),
        "steady_step_ms_p50": handoff["steady_step_ms_p50"],
    }


def run_restart_resume(
    handoff_path: str,
    restore: bool = True,
    quiesce_timeout: float = 120.0,
) -> dict:
    """Phase B: rebuild the world from the handoff (the durable state a
    restarted operator re-reads), restore the warm-state snapshot
    (``restore=False`` = the unsnapshot cold-restart baseline), resume
    the stream from the kill step, and report the full-stream plan hash
    plus the post-restart warm-up trajectory."""
    import hashlib
    import pickle

    with open(handoff_path, "rb") as f:
        handoff = pickle.load(f)
    sc = build_scenario(handoff["scenario"], scale=handoff["scale"], seed=handoff["seed"])
    kill_step = handoff["kill_step"]
    harness = TrafficHarness(
        teams=handoff["teams"] or sc.teams, n_types=handoff["n_types"], restore=handoff
    )
    rec = _StreamRecorder(harness)
    pipe = ServingPipeline(
        harness.provisioner, metrics=harness.metrics, config=_restart_config(),
        on_decision=rec,
    )
    harness.on_catalog_event = pipe.observe_catalog_event
    snapshot_path = handoff.get("snapshot_path")
    # cold lane: backend init only — the measured first solve pays the
    # real trace+compile a restored process provably skips (ISSUE 17)
    harness.warmup_compile_only(pay_compiles=bool(restore and snapshot_path))
    restore_ms = 0.0
    warmstore_outcome = None
    if restore and snapshot_path:
        # restore BEFORE the first tick (the pipeline hook); timed
        # separately so bench can report restore_ms on its own
        t0 = time.perf_counter()
        warmstore_outcome = pipe.restore_warm_state(snapshot_path)
        restore_ms = (time.perf_counter() - t0) * 1000.0
    pipe.attach_watch()
    pipe.hold()
    pipe.start()
    try:
        solve_ticks, _ = _drive_steps(
            pipe, harness, sc.steps[kill_step:], kill_step, quiesce_timeout
        )
        # boot jitsig-replay outcome (ISSUE 17): settled by now — the
        # plan thread's first tick waited on the replay gate
        boot_replay = pipe.debug_state()["prewarm"].get("boot_replay")
    finally:
        pipe.stop()
    harness.close()
    full_stream = list(handoff["plan_stream"]) + list(rec.stream)
    steady_p50 = handoff.get("steady_step_ms_p50") or 0.0
    # warm-up trajectory: 1-indexed post-restart solve tick at which the
    # pipeline is back to the killed process's steady p50 (x1.5 + 2 ms
    # of jitter headroom); 0 = never within the driven window
    ticks_to_warm = 0
    for i, t in enumerate(solve_ticks):
        if steady_p50 and t["step_ms"] <= steady_p50 * 1.5 + 2.0:
            ticks_to_warm = i + 1
            break
    return {
        "phase": "resume",
        "mode": "warm" if (restore and snapshot_path) else "cold",
        "scenario": handoff["scenario"],
        "kill_step": kill_step,
        "restored": warmstore_outcome is not None,
        "restore_ms": round(restore_ms, 3),
        "warmstore": warmstore_outcome,
        "first_solve_ms": solve_ticks[0]["step_ms"] if solve_ticks else 0.0,
        "first_solve_host_ms": solve_ticks[0]["solve_host_ms"] if solve_ticks else 0.0,
        # ISSUE 17: deviceplane compile events raised by the first
        # authoritative solve (the restored path must gate this at 0)
        # and the boot jitsig-replay outcome that made it so
        "first_solve_compiles": (
            solve_ticks[0].get("solve_compiles") if solve_ticks else None
        ),
        "prewarm_ms": (boot_replay or {}).get("prewarm_ms", 0.0),
        "prewarm_replay": boot_replay,
        "post_restart_step_ms": [round(t["step_ms"], 3) for t in solve_ticks],
        "steady_step_ms_p50": steady_p50,
        "ticks_to_warm": ticks_to_warm,
        "plans_emitted": len(full_stream),
        "plan_sha256": hashlib.sha256(repr(full_stream).encode()).hexdigest(),
    }


def run_restart_reference(
    scenario_name: str,
    scale: int = 800,
    seed: Optional[int] = None,
    teams: Optional[int] = None,
    n_types: int = 480,
    quiesce_timeout: float = 120.0,
) -> dict:
    """The unkilled oracle: the same scenario driven end to end in one
    process, same pipeline config and harness shape as the kill/resume
    phases — its full-stream plan hash is what the concatenated
    killed-run stream must equal (byte identity across the kill point)."""
    import hashlib

    sc = build_scenario(scenario_name, scale=scale, seed=seed)
    harness = TrafficHarness(teams=teams or sc.teams, n_types=n_types)
    rec = _StreamRecorder(harness)
    pipe = ServingPipeline(
        harness.provisioner, metrics=harness.metrics, config=_restart_config(),
        on_decision=rec,
    )
    harness.on_catalog_event = pipe.observe_catalog_event
    harness.warmup_compile_only()
    pipe.attach_watch()
    pipe.hold()
    pipe.start()
    try:
        solve_ticks, _ = _drive_steps(pipe, harness, sc.steps, 0, quiesce_timeout)
    finally:
        pipe.stop()
    harness.close()
    return {
        "phase": "reference",
        "scenario": scenario_name,
        "steps": len(sc.steps),
        "plans_emitted": len(rec.stream),
        "plan_sha256": hashlib.sha256(repr(list(rec.stream)).encode()).hexdigest(),
        "solve_ticks": len(solve_ticks),
    }


# ---------------------------------------------------------------------------
# chaos pack (ISSUE 15): the same lockstep drive, with a deterministic
# seeded fault schedule (kube/faults.py) applied at step boundaries over
# the in-memory apiserver. Every fault has a clean twin (fault="none",
# same scenario/seed) and the gate is plan identity between the two: a
# faulted run may DELAY decisions (held ticks) and INFLATE latency, but
# must emit the byte-identical plan stream — degradation is hold +
# counter, never a stale or divergent plan.


CHAOS_FAULTS = ("watch_flap", "watch_hang", "latency_spike", "failover", "clock_skew")

# kind-specific magnitudes for the harness runs: latency in ms per
# NodeClaim admission, skew in seconds (one hour — an egregious NTP step)
_CHAOS_MAGNITUDES = {"latency_spike": 25.0, "clock_skew": 3600.0}
# the fault kinds whose degradation is a HELD tick, and which hold
# counter proves it
_HOLDING_FAULTS = {"watch_flap": "stale", "watch_hang": "stale", "failover": "leader"}


def _chaos_config(fault: str) -> PipelineConfig:
    cfg = PipelineConfig(
        idle_seconds=0.02, max_seconds=1.0, solve_queue_cap=1, telemetry_queue_cap=1024
    )
    if fault == "watch_hang":
        # the hang fault is detected by AGE, not by an explicit flag: no
        # watch delivery for > max_staleness_s ⇒ the world is stale
        cfg.max_staleness_s = 0.25
    return cfg


def run_chaos(
    scenario_name: str,
    fault: str = "none",
    scale: int = 600,
    seed: Optional[int] = None,
    teams: Optional[int] = None,
    quiesce_timeout: float = 120.0,
    hold_timeout: float = 10.0,
) -> dict:
    """One chaos measurement: drive ``scenario_name`` in lockstep
    through the serving pipeline with ``fault`` windows injected from a
    seeded FaultSchedule (``fault="none"`` = the clean twin). Returns
    the plan hash plus the degradation evidence:

    - ``held_ticks`` — ticks held by the stale-world guard / leader
      gate (the bounded degradation);
    - ``stale_plans_emitted`` — plans that appeared WHILE the guard
      held (must be 0: the no-stale-plan invariant, observed, not
      assumed);
    - ``single_writer_ok`` — no NodeClaim landed while deposed
      (failover windows);
    - p99 decision latency and flight-recorder SLO burn, with the
      fault window annotated on every record taken inside it.
    """
    import hashlib

    from ..kube.faults import FaultSchedule
    from ..tracing import flightrec
    from .latency import percentiles_ms

    if fault != "none" and fault not in CHAOS_FAULTS:
        raise ValueError(f"unknown chaos fault {fault!r} (choices: {CHAOS_FAULTS})")
    sc = build_scenario(scenario_name, scale=scale, seed=seed)
    schedule = (
        FaultSchedule.build(
            f"chaos-{fault}", sc.seed, (fault,), len(sc.steps),
            magnitudes=_CHAOS_MAGNITUDES,
        )
        if fault != "none"
        else None
    )
    harness = TrafficHarness(teams=teams or sc.teams)
    rec = _StreamRecorder(harness)
    config = _chaos_config(fault)
    pipe = ServingPipeline(
        harness.provisioner, metrics=harness.metrics, config=config, on_decision=rec
    )
    harness.on_catalog_event = pipe.observe_catalog_event
    led = {"leading": True}
    pipe.attach_leader_gate(lambda: led["leading"])
    harness.warmup()
    pipe.attach_watch()
    pipe.hold()
    pipe.start()
    held_seen = pipe.held_ticks()
    stale_plans_emitted = 0
    writes_while_deposed = 0
    fault_steps: List[int] = []
    spike_guard = None
    skewed_clock = None
    rr = RunResult(mode="pipeline", scenario=sc.name)
    t0 = time.perf_counter()
    try:
        for i, step in enumerate(sc.steps):
            ev = schedule.active(i)[0] if schedule and schedule.active(i) else None
            if ev is not None:
                fault_steps.append(i)
                flightrec.set_fault_window(f"chaos_{fault}", fault, "active")
                if fault == "clock_skew" and skewed_clock is None:
                    # skew BEFORE injection so this window's object
                    # stamps carry the jumped wall clock — the plans
                    # must not care
                    base = harness.kube.clock
                    skewed_clock = base
                    harness.kube.clock = lambda _b=base, _m=ev.magnitude: _b() + _m
            elif skewed_clock is not None:
                # window over: the NTP step back (stamps jump backwards)
                harness.kube.clock = skewed_clock
                skewed_clock = None
            harness.inject_step(step, i)
            plans_before = len(rec.stream)
            claims_before = len(harness.kube.list("NodeClaim"))
            if ev is not None:
                if fault == "watch_flap":
                    pipe.set_world_stale(True)
                elif fault == "failover":
                    led["leading"] = False
                elif fault == "latency_spike" and spike_guard is None:
                    delay_s = max(0.0, ev.magnitude) / 1000.0

                    def _slow(obj, _d=delay_s):
                        if obj.kind == "NodeClaim":
                            time.sleep(_d)

                    spike_guard = _slow
                    harness.kube.admission.append(spike_guard)
                elif fault == "watch_hang":
                    # no watch delivery past the freshness bound: the
                    # age check, not an explicit flag, must trip
                    time.sleep(config.max_staleness_s * 1.6)
            elif spike_guard is not None:
                harness.kube.admission.remove(spike_guard)
                spike_guard = None
            pipe.release()
            if ev is not None and fault in _HOLDING_FAULTS:
                counter = _HOLDING_FAULTS[fault]
                deadline = time.monotonic() + hold_timeout
                while (
                    time.monotonic() < deadline
                    and pipe.held_ticks()[counter] <= held_seen[counter]
                ):
                    time.sleep(0.002)
                held_now = pipe.held_ticks()
                if held_now[counter] <= held_seen[counter]:
                    raise TimeoutError(
                        f"tick did not hold under {fault} at step {i} of {sc.name}"
                    )
                held_seen = held_now
                # the no-stale-plan invariant, observed: nothing may
                # have been emitted while the guard held
                stale_plans_emitted += len(rec.stream) - plans_before
                writes_while_deposed += (
                    len(harness.kube.list("NodeClaim")) - claims_before
                    if fault == "failover"
                    else 0
                )
                flightrec.set_fault_window(f"chaos_{fault}", fault, "recovery")
                if fault == "watch_flap":
                    pipe.set_world_stale(False)
                elif fault == "watch_hang":
                    pipe.note_world_event()  # the liveness probe returns
                elif fault == "failover":
                    led["leading"] = True  # re-elected
            if not pipe.quiesce(timeout=quiesce_timeout):
                raise TimeoutError(f"failed to quiesce at step {i} of {sc.name}")
            pipe.hold()
            if ev is None:
                flightrec.clear_fault_window()
        latency = pipe.latency
        rr.ticks = pipe.ticks()
        rr.stage_stats = pipe.debug_state()
    finally:
        flightrec.clear_fault_window()
        pipe.stop()
    rr = _finalize_result(rr, harness, rec, latency, time.perf_counter() - t0)
    harness.close()
    dbg = rr.stage_stats
    return {
        "scenario": scenario_name,
        "fault": fault,
        "schedule": schedule.to_dict() if schedule is not None else None,
        "fault_steps": fault_steps,
        "steps": len(sc.steps),
        "pods_injected": sc.total_creates,
        "ticks": rr.ticks,
        "pods_decided": rr.pods_decided,
        "pod_errors": rr.errors,
        "plans_emitted": len(rr.plan_stream),
        "plan_sha256": hashlib.sha256(rr.plan_bytes()).hexdigest(),
        "monotonic_decision_order": monotonic_decision_order(rr),
        "held_ticks": dbg.get("chaos", {}).get("held_ticks", {}),
        "stale_plans_emitted": stale_plans_emitted,
        "single_writer_ok": writes_while_deposed == 0,
        "decision_latency_ms": percentiles_ms(rr.samples_ms),
        "steady_decision_latency_ms": percentiles_ms(rr.steady_samples_ms),
        "slo_burn": dbg.get("flightrec", {}).get("burn_rate", {}),
        "wall_s": rr.wall_s,
    }


# ---------------------------------------------------------------------------
# fleet driver: N independent scenario streams against one device
# (fleet/ — ISSUE 9). Each tenant gets its own provider/catalog archetype
# and its own seeded scenario; steps are injected fleet-wide and decided
# through the FleetScheduler's DRR rounds.


def _fleet_plan_key(plan) -> tuple:
    """Content identity of one NodePlan (the engine-parity projection:
    object identities differ across engines by design — the batched
    engine emits from canonical catalog snapshots)."""
    return (
        plan.nodepool_name,
        plan.instance_type.name,
        plan.zone,
        plan.capacity_type,
        round(plan.price, 9),
        tuple(plan.pod_indices),
        plan.max_pods_per_node,
    )


def run_fleet_measurement(
    n_tenants: int = 8,
    scenario: str = "rollout",
    scale: int = 200,
    engine: str = "batched",
    seed: int = 7,
    catalog_sizes: Tuple[int, ...] = (16, 48, 96),
    quantum: Optional[int] = None,
) -> dict:
    """One fleet drive: ``n_tenants`` independent ``scenario`` streams
    (per-tenant seeds and catalog archetypes) through one FleetScheduler
    on the chosen engine → plain-JSON summary with the aggregate
    throughput, per-tenant decision-latency SLO, the mega-dispatch
    coalescing stats, and a content hash of every tenant's plan stream
    (equal across engines ⇔ plan identity)."""
    import hashlib

    from ..fleet import FleetEngine, FleetRegistry, FleetScheduler
    from .latency import percentiles_ms

    os.environ["KARPENTER_TPU_FLEET_ENGINE"] = engine
    # the catalog entry cache must hold the whole fleet's archetypes
    # (both engines get the same headroom)
    os.environ.setdefault("KARPENTER_TPU_CATALOG_CACHE_MAX", str(2 * n_tenants + 16))
    registry = FleetRegistry()
    fleet = FleetEngine(registry)
    sched = FleetScheduler(fleet, quantum=quantum)

    scenarios = []
    for t in range(n_tenants):
        tid = f"tenant-{t:03d}"
        sc = build_scenario(scenario, scale=scale, seed=seed + 17 * t)
        provider = FakeCloudProvider()
        provider.instance_types = _catalog(catalog_sizes[t % len(catalog_sizes)])
        provider.bump_catalog_generation()
        nodepool = NodePool()
        nodepool.metadata.name = "default"
        nodepool.spec.template.requirements = [
            NodeSelectorRequirement("team", "In", [f"t{k}" for k in range(sc.teams)])
        ]
        registry.add_tenant(tid, [nodepool], provider)
        scenarios.append((tid, sc, provider, nodepool))

    plan_log: List[tuple] = []
    round_dispatch = {"flushes": 0, "pack_calls": 0, "jobs": 0, "max_occupancy": 0}

    injected = 0
    rounds = 0
    t0 = time.perf_counter()
    n_steps = max(len(sc.steps) for _, sc, _, _ in scenarios)
    for si in range(n_steps):
        for tid, sc, provider, nodepool in scenarios:
            if si >= len(sc.steps):
                continue
            step = sc.steps[si]
            if step.mutate_catalog:
                its = provider.get_instance_types(nodepool)
                for it in its[:: max(1, len(its) // 16)]:
                    for o in it.offerings:
                        o.price *= 1.01
                provider.bump_catalog_generation()
            if step.creates:
                pods = [materialize_spec(s) for s in step.creates]
                injected += len(pods)
                sched.submit(tid, pods)
        while sched.queued():
            outcomes = sched.run_round()
            rounds += 1
            d = fleet.last_round.get("dispatch") or {}
            for k in ("flushes", "pack_calls", "jobs"):
                round_dispatch[k] += d.get(k, 0)
            round_dispatch["max_occupancy"] = max(
                round_dispatch["max_occupancy"], d.get("max_occupancy", 0)
            )
            for tid in sorted(outcomes):
                o = outcomes[tid]
                if o.error is None:
                    plan_log.append(
                        (rounds, tid, tuple(sorted(_fleet_plan_key(p) for p in o.result.node_plans)))
                    )
                else:
                    plan_log.append((rounds, tid, ("error", o.error)))
    wall = time.perf_counter() - t0

    samples: List[float] = []
    decided = errors = 0
    per_tenant = {}
    for tid, _sc, _p, _np in scenarios:
        tracker = registry.get(tid).latency
        ms = tracker.samples_ms()
        samples.extend(ms)
        decided += tracker.decided_count()
        errors += sum(1 for s in tracker.decisions() if s[4])
        if len(per_tenant) < 4:
            per_tenant[tid] = percentiles_ms(ms)
    return {
        "engine": engine,
        "scenario": scenario,
        "tenants": n_tenants,
        "scale": scale,
        "rounds": rounds,
        "pods_injected": injected,
        "pods_decided": decided,
        "decision_errors": errors,
        "wall_s": round(wall, 4),
        "pods_per_sec": round(decided / wall, 1) if wall else 0.0,
        "decision_latency_ms": percentiles_ms(samples),
        "per_tenant_latency_ms": per_tenant,
        "dispatch": round_dispatch,
        "plan_sha256": hashlib.sha256(repr(plan_log).encode()).hexdigest(),
        "scheduler": sched.debug_state(),
    }


# ---------------------------------------------------------------------------
# CLI: one measurement per process. Bench config 8 shells out here so
# every (scenario, mode) pair runs with a fresh process-wide state —
# XLA compile cache included — the pyperf discipline: whichever mode
# runs second must not inherit the first one's warmed jits.


def run_measurement(
    scenario: str,
    mode: str,
    drive: str,
    scale: int,
    pace: float,
    seed: Optional[int] = None,
    idle_s: float = 0.02,
    max_s: float = 0.5,
) -> dict:
    """One scenario × mode × drive measurement → plain-JSON summary
    (the subprocess payload; also what --stream profiling drives)."""
    import hashlib

    from .latency import percentiles_ms

    sc = build_scenario(scenario, scale=scale, seed=seed)
    config = PipelineConfig(idle_seconds=idle_s, max_seconds=max_s)
    if drive == "lockstep":
        rr = run_lockstep(sc, mode=mode, config=config)
    elif drive == "free":
        rr = run_free(sc, mode=mode, pace_s=pace, config=config)
    else:
        raise ValueError(f"unknown drive {drive!r}")
    out = {
        "scenario": scenario,
        "mode": mode,
        "drive": drive,
        "steps": len(sc.steps),
        "pods_injected": sc.total_creates,
        "ticks": rr.ticks,
        "pods_decided": rr.pods_decided,
        "pod_errors": rr.errors,
        "wall_s": rr.wall_s,
        "plans_emitted": len(rr.plan_stream),
        "plan_sha256": hashlib.sha256(rr.plan_bytes()).hexdigest(),
        "monotonic_decision_order": monotonic_decision_order(rr),
        "decision_latency_ms": percentiles_ms(rr.samples_ms),
        "steady_decision_latency_ms": percentiles_ms(rr.steady_samples_ms),
        "steady_samples": len(rr.steady_samples_ms),
        "pods_per_sec": round(rr.pods_decided / rr.wall_s, 1) if rr.wall_s else 0.0,
    }
    if rr.stage_stats:
        out["queues"] = rr.stage_stats.get("queues", {})
        out["prewarm"] = rr.stage_stats.get("prewarm", {})
        # flight-recorder health (ISSUE 10 acceptance: timeline
        # reconstruction coverage + orphan spans over the whole run)
        out["flightrec"] = rr.stage_stats.get("flightrec", {})
        from ..tracing import tracer as _tracer

        out["orphan_spans"] = _tracer.orphan_spans()
        agg: dict = {}
        for tick_rec in rr.stage_stats.get("last_ticks", []):
            agg["batch_wait"] = agg.get("batch_wait", 0.0) + tick_rec.get(
                "queue_wait_ms", 0.0
            )
            for k, v in tick_rec.get("phase_breakdown_ms", {}).items():
                agg[k] = agg.get(k, 0.0) + v
        out["stage_attribution_ms"] = {
            k: round(v, 2)
            for k, v in sorted(agg.items(), key=lambda kv: -kv[1])[:6]
        }
    return out


def _cli(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        "python -m karpenter_core_tpu.serving.trafficgen",
        description="Replay a production-shaped traffic scenario against the serving pipeline.",
    )
    ap.add_argument("--scenario", required=True, choices=sorted(SCENARIOS))
    ap.add_argument("--mode", default="pipeline", choices=("pipeline", "sequential"))
    ap.add_argument("--drive", default="free", choices=("free", "lockstep"))
    ap.add_argument("--scale", type=int, default=800)
    ap.add_argument("--pace", type=float, default=0.25)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--idle", type=float, default=0.02, help="batch window idle seconds")
    ap.add_argument("--max", dest="max_s", type=float, default=0.5, help="batch window max seconds")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet mode: drive N independent tenant streams of "
                         "--scenario through the fleet scheduler")
    ap.add_argument("--engine", default="batched", choices=("batched", "solo"),
                    help="fleet engine (with --fleet)")
    # kill-the-process-mid-stream (ISSUE 13): each phase is one process
    ap.add_argument("--restart-kill-at", type=int, default=0, metavar="K",
                    help="drive steps [0, K), snapshot on quiesce, dump the "
                         "handoff to --workdir, print the summary, exit "
                         "(the kill IS the process exit)")
    ap.add_argument("--restart-resume", metavar="HANDOFF", default=None,
                    help="rebuild from a kill phase's handoff, restore the "
                         "warm-state snapshot, resume the stream from the "
                         "kill step")
    ap.add_argument("--restart-reference", action="store_true",
                    help="drive the whole scenario unkilled (the identity "
                         "oracle for a kill/resume pair)")
    ap.add_argument("--cold", action="store_true",
                    help="with --restart-resume: skip the warm-state restore "
                         "(the unsnapshot cold-restart baseline)")
    ap.add_argument("--workdir", default=None,
                    help="snapshot/handoff directory (with --restart-kill-at)")
    ap.add_argument("--n-types", type=int, default=480,
                    help="catalog size for the restart phases")
    # chaos pack (ISSUE 15): one fault kind per run, "none" = clean twin
    ap.add_argument("--chaos", default=None, choices=("none",) + CHAOS_FAULTS,
                    help="chaos mode: lockstep-drive --scenario with this "
                         "fault injected from a seeded schedule ('none' = "
                         "the clean twin the faulted run's plan hash is "
                         "gated against)")
    args = ap.parse_args(argv)
    if args.chaos is not None:
        out = run_chaos(
            args.scenario, fault=args.chaos, scale=args.scale, seed=args.seed
        )
        print(json.dumps(out), flush=True)
        return 0
    if args.restart_kill_at or args.restart_resume or args.restart_reference:
        if args.restart_resume:
            out = run_restart_resume(args.restart_resume, restore=not args.cold)
        elif args.restart_reference:
            out = run_restart_reference(
                args.scenario, scale=args.scale, seed=args.seed, n_types=args.n_types
            )
        else:
            if not args.workdir:
                ap.error("--restart-kill-at requires --workdir")
            out = run_restart_kill(
                args.scenario, args.restart_kill_at, args.workdir,
                scale=args.scale, seed=args.seed, n_types=args.n_types,
            )
        print(json.dumps(out), flush=True)
        return 0
    if args.fleet:
        out = run_fleet_measurement(
            n_tenants=args.fleet,
            scenario=args.scenario,
            scale=args.scale,
            engine=args.engine,
            seed=args.seed if args.seed is not None else 7,
        )
        print(json.dumps(out), flush=True)
        return 0
    out = run_measurement(
        args.scenario,
        args.mode,
        args.drive,
        args.scale,
        args.pace,
        seed=args.seed,
        idle_s=args.idle,
        max_s=args.max_s,
    )
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(_cli())
