"""Staged async serving pipeline (ISSUE 6 tentpole).

The provisioner was tick-shaped: batch pending pods, solve, emit —
serially, with a polling batcher in front. Production traffic is a
stream. This module overlaps the stages:

    watch events ──► ingest (observe_pod_event: stamp arrival, trigger window)
                          │
                 batch former thread: condition-variable window
                 (idle/max), runs WHILE the current solve is in flight
                          │  bounded solve queue (backpressure)
                 plan thread: the single AUTHORITATIVE stage —
                 pending-pod listing → solve (encode → device dispatch →
                 finalize) → NodeClaim emit, strictly in tick order
                          │  bounded telemetry queue
                 telemetry thread: latency histograms, queue gauges,
                 per-stage attribution off the solve trace
    prewarm thread: double buffer — while tick N's pack is in flight on
    device, tick N+1's accumulating batch runs `encode_prewarm` on the
    host (pod memos, signature grouping, compat kernel rows), so the
    authoritative solve is warm by construction.

Overlap-safety invariant: **overlap is scheduling, never reordering of
observable state.** Only the plan thread mutates observable state
(claims, nominations, events), in tick order — concurrent stages form
batches, warm content-addressed caches (sound by the cache-key analysis
family), and drain telemetry. Hence pipeline plans are byte-identical
to the equivalent sequential reconcile; `SequentialLoop` below IS that
reconcile (same decision step, no overlap), and bench config 8 + the
seeded-interleaving test assert the identity on every traffic scenario.

Every stage boundary is a `StageQueue` (lock-free sharing is banned in
this package by the pipeline-safety analysis rule); knobs are
env-tunable (`KARPENTER_TPU_SERVING_*`, see `PipelineConfig`).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..provisioning.batcher import Batcher
from ..tracing import flightrec, tracer
from ..utils import pod as podutils
from .latency import DecisionLatencyTracker
from .queues import Closed, StageQueue, queue_cap

log = logging.getLogger("karpenter.serving")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class PipelineConfig:
    """Serving knobs. Queue caps bound each stage's buffering — a full
    queue blocks the producer (backpressure), it never drops work."""

    idle_seconds: float = field(
        default_factory=lambda: _env_float("KARPENTER_TPU_SERVING_IDLE_S", 1.0)
    )
    max_seconds: float = field(
        default_factory=lambda: _env_float("KARPENTER_TPU_SERVING_MAX_S", 10.0)
    )
    # batch tokens in flight: 1 = the window former may run exactly one
    # window ahead of the solve (the double buffer); raising it deepens
    # lookahead without changing plan identity (emits stay serialized)
    solve_queue_cap: int = field(default_factory=lambda: queue_cap("SOLVE", 1))
    telemetry_queue_cap: int = field(default_factory=lambda: queue_cap("TELEMETRY", 1024))
    prewarm: bool = field(
        default_factory=lambda: os.environ.get("KARPENTER_TPU_SERVING_PREWARM", "1") != "0"
    )
    # run the disruption pass as a pipeline stage every N plan ticks
    # (0 = off). It executes ON the plan thread, after the provisioning
    # step — disruption mutates claims/taints/cluster marks, and the
    # overlap-safety invariant says only the plan thread mutates
    # observable state. The batched engine's cross-pass memos
    # (disruption/engine.py) make the steady-state pass cheap, which is
    # what lets it ride the serving cadence instead of a 10 s timer.
    disrupt_every: int = field(
        default_factory=lambda: int(
            _env_float("KARPENTER_TPU_SERVING_DISRUPT_EVERY", 0)
        )
    )
    # warm-state persistence (ISSUE 13, solver/warmstore.py): with a
    # directory configured, `quiesce()` snapshots the cache planes and
    # returns the snapshot path; `warmstore_restore` (a snapshot path)
    # is restored before the first tick so a restarted pipeline's first
    # solve is a warm solve
    warmstore_dir: Optional[str] = field(
        default_factory=lambda: os.environ.get("KARPENTER_TPU_WARMSTORE_DIR", "").strip() or None
    )
    warmstore_restore: Optional[str] = field(
        default_factory=lambda: os.environ.get("KARPENTER_TPU_WARMSTORE_RESTORE", "").strip() or None
    )
    # stale-world guard (ISSUE 15): with a positive bound, the plan
    # thread refuses to run the authoritative step against an observed
    # world older than this many seconds (no watch event / explicit
    # staleness mark) — the tick HOLDS (counted, visible in /debug)
    # until freshness recovers. 0 disables the age check; the explicit
    # `set_world_stale` seam works regardless.
    max_staleness_s: float = field(
        default_factory=lambda: _env_float("KARPENTER_TPU_SERVING_MAX_STALENESS_S", 0.0)
    )

    def to_dict(self) -> dict:
        return {
            "idle_seconds": self.idle_seconds,
            "max_seconds": self.max_seconds,
            "solve_queue_cap": self.solve_queue_cap,
            "telemetry_queue_cap": self.telemetry_queue_cap,
            "prewarm": self.prewarm,
            "disrupt_every": self.disrupt_every,
            "warmstore_dir": self.warmstore_dir,
            "warmstore_restore": self.warmstore_restore,
            "max_staleness_s": self.max_staleness_s,
        }


class LostLeadership(RuntimeError):
    """Raised by the leader admission guard when a NodeClaim write is
    attempted by a process that no longer holds the leader lease — the
    deposed leader's in-flight tick must not emit (ISSUE 15)."""


class _DecisionStep:
    """The shared authoritative decision step: one sequential reconcile
    (pending listing → solve → emit), plus decision-latency marking and
    the optional on_decision hook (the traffic simulator's kubelet
    binder). Both the pipeline's plan thread and `SequentialLoop` run
    EXACTLY this code, which is what makes 'byte-identical to the
    sequential reconcile' hold by construction.

    Telemetry plane (ISSUE 10): the whole step runs under one
    ``decision`` trace root — the provisioner's reconcile root and the
    solver's solve root JOIN it, so every span of the decision's
    lifetime (including spans worker threads adopt via the captured
    context) lands under one trace. At plan-emit time the step
    assembles the decision's flight record. ``on_root`` (pipeline only)
    receives the decision's TraceContext the moment the root opens —
    the prewarm thread adopts it so the double buffer's speculative
    work is attributed to the decision it overlaps."""

    def __init__(
        self,
        provisioner,
        latency: DecisionLatencyTracker,
        on_decision=None,
        kind: str = "sequential",
        recorder=None,
        on_root=None,
    ):
        self.provisioner = provisioner
        self.latency = latency
        self.on_decision = on_decision
        self.kind = kind
        self.recorder = recorder if recorder is not None else flightrec.RECORDER
        self.on_root = on_root
        # the decision context of the step in flight / just finished —
        # read only by the thread that called run() (the plan thread or
        # the sequential loop), for enqueueing downstream work under
        # this decision's trace
        self.last_ctx = None

    def run(self, tick: int, queue_wait_ms: Optional[float] = None) -> dict:
        t0 = time.perf_counter()
        with tracer.trace_root("decision", buffer_if="solve", tick=tick) as tr:
            self.last_ctx = tracer.capture()
            if self.on_root is not None:
                self.on_root(self.last_ctx)
            names, reason, results = self.provisioner.reconcile_with_results()
            decided: List[str] = []
            errored: List[str] = []
            plan_cost = 0.0
            if results is not None:
                for plan in getattr(results, "tpu_plans", []) or []:
                    if getattr(plan, "created_claim_name", None):
                        decided.extend(p.uid for p in plan.pods)
                        plan_cost += float(getattr(plan, "price", 0.0) or 0.0)
                for claim in results.new_node_claims:
                    if getattr(claim, "created_claim_name", None):
                        decided.extend(p.uid for p in claim.pods)
                for plan in getattr(results, "existing_plans", []) or []:
                    decided.extend(p.uid for p in getattr(plan, "pods", []) or [])
                for ex in results.existing_nodes:
                    decided.extend(p.uid for p in ex.pods)
                errored.extend(results.pod_errors.keys())
            trace_id = tr.trace_id if tr is not None else None
            # decision point: the plan (or terminal error) is emitted —
            # the settled latencies feed the flight record, the trace_id
            # rides the latency histogram as an exemplar
            settled = self.latency.pods_decided(decided, tick, trace_id=trace_id)
            settled += self.latency.pods_decided(
                errored, tick, error=True, trace_id=trace_id
            )
            if self.on_decision is not None and results is not None:
                # simulator hook (kubelet binder) — runs ON the authoritative
                # thread, before the next tick's listing, in both modes
                self.on_decision(tick, results)
        solver = None
        cached = getattr(self.provisioner, "_tpu_solver", None)
        if cached is not None:
            solver = cached[1]
        timings = getattr(solver, "last_timings", None) if solver is not None else None
        step_ms = round((time.perf_counter() - t0) * 1000.0, 3)
        self._flight_record(
            tick, tr, solver, settled, decided, errored, queue_wait_ms, plan_cost
        )
        return {
            "tick": tick,
            "step_ms": step_ms,
            "created": len(names),
            "decided": len(decided),
            "errors": len(errored),
            "reason": reason,
            "trace_id": (timings or {}).get("trace_id"),
            "decision_trace_id": tr.trace_id if tr is not None else None,
            "solve_host_ms": round((timings or {}).get("host_ms", 0.0), 3),
            "solve_device_ms": round((timings or {}).get("device_ms", 0.0), 3),
            # deviceplane compile events raised by this tick's solve
            # (ISSUE 17: the restart lanes gate the restored first solve
            # at zero); None when the device plane is off or no solver
            "solve_compiles": (
                (getattr(solver, "last_device_stats", None) or {}).get("compiles")
                if solver is not None
                else None
            ),
        }

    def _flight_record(
        self, tick, tr, solver, settled, decided, errored, queue_wait_ms, plan_cost
    ) -> None:
        """Assemble the decision's flight record once the root closed
        (so the root span's duration and every same-thread span are
        final). Must never fail the decision."""
        try:
            from ..solver import stats as solver_stats

            solve = solver_stats.solve_stats(solver) if solver is not None else {}
            cost: dict = {}
            if decided and plan_cost:
                from ..solver import plancost

                bound = (solve.get("pack_backend") or {}).get("lp_bound_sum")
                gap = plancost.optimality_gap(plan_cost, bound) if bound else None
                cost = {
                    "plan_cost_per_hr": round(plan_cost, 4),
                    "lp_bound_per_hr": round(bound, 4) if bound else None,
                    "opt_gap_pct": round(gap * 100.0, 2) if gap is not None else None,
                }
            if tr is not None and queue_wait_ms:
                # queue wait on the synthetic lane: visible in the trace
                # viewer just before the root, excluded from breakdowns
                tr.add_synthetic(
                    "queue_wait",
                    tr.start_ns - int(queue_wait_ms * 1e6),
                    int(queue_wait_ms * 1e6),
                )
            self.recorder.record(
                self.kind,
                tick,
                trace=tr,
                solve=solve,
                queue_wait_ms=queue_wait_ms,
                latency_ms=[s * 1000.0 for s in settled],
                pods_decided=len(decided),
                errors=len(errored),
                cost=cost,
            )
        except Exception:  # noqa: BLE001 — telemetry must never fail a decision
            log.debug("flight-record assembly failed", exc_info=True)


class ServingPipeline:
    """The staged pipeline. Wire `observe_pod_event` into the kube pod
    watch, then `start()`. `hold()`/`release()` gate batch formation
    (used by the lockstep identity harness and operational pause);
    `quiesce()` waits for the decision stream to drain."""

    def __init__(
        self,
        provisioner,
        metrics=None,
        config: Optional[PipelineConfig] = None,
        latency: Optional[DecisionLatencyTracker] = None,
        on_decision: Optional[Callable] = None,
        disruption=None,
    ):
        self.provisioner = provisioner
        self.kube_client = provisioner.kube_client
        self.cluster = provisioner.cluster
        self.metrics = metrics
        self.config = config or PipelineConfig()
        self.latency = latency or DecisionLatencyTracker(
            histogram=getattr(metrics, "serving_decision_latency", None)
        )
        self.batcher = Batcher(
            idle_seconds=self.config.idle_seconds, max_seconds=self.config.max_seconds
        )
        depth_gauge = getattr(metrics, "serving_queue_depth", None)
        self.solve_q = StageQueue("solve", self.config.solve_queue_cap, depth_gauge)
        self.telemetry_q = StageQueue(
            "telemetry", self.config.telemetry_queue_cap, depth_gauge
        )
        burn_gauge = getattr(metrics, "decision_slo_burn", None)
        if burn_gauge is not None:
            flightrec.RECORDER.attach_burn_gauge(burn_gauge)
        self._step = _DecisionStep(
            provisioner,
            self.latency,
            on_decision,
            kind="pipeline",
            on_root=self._set_plan_ctx,
        )
        # optional continuous-disruption stage (DisruptionController):
        # reconciled on the plan thread every `disrupt_every` ticks, so
        # the single-writer invariant holds for disruption's mutations
        # (taints, claims, deletion marks) exactly as for provisioning's
        self.disruption = disruption
        self._disrupt_log: deque = deque(maxlen=32)
        self._stop_evt = threading.Event()
        self._new_pods_evt = threading.Event()
        # the double-buffer handshake: set by the live solver the moment
        # its encode phase hands off to device pack (the host is idle
        # while the pack is in flight — exactly the prewarm slot);
        # cleared by the plan thread before each authoritative step
        self._encode_done_evt = threading.Event()
        self._encode_done_evt.set()
        provisioner.encode_done_listener = self._encode_done_evt.set
        self._gate_cv = threading.Condition()
        self._gate_held = False
        self._mu = threading.Lock()
        self._ticks = 0
        self._step_inflight = False
        # ticks whose telemetry record has landed in the tick log — the
        # quiesce barrier compares this against _ticks so a "quiesced"
        # pipeline's /debug payload is settled (no undrained tick)
        self._telemetry_drained = 0
        self._ingested = 0
        # the in-flight decision's TraceContext (the prewarm handshake's
        # trace half): stamped by the decision root's on_root hook on
        # the plan thread, adopted by the prewarm thread so the double
        # buffer's speculative encode lands on the decision it overlaps
        self._plan_ctx = None
        # ingest → prewarm handoff: pods seen pending since the last
        # prewarm pass. Only NEW pods can have cold memos/signature
        # rows, so the speculative encode walks the delta, never the
        # whole pending set — at steady state the buffer is empty and
        # prewarm costs nothing (GIL included). Dropping entries would
        # only skip speculation, but the cap is far above any burst.
        self._prewarm_buf: deque = deque(maxlen=100_000)
        # bounded memory of recently-pending pods: after a catalog
        # event the fresh catalog entry starts with empty compat rows
        # and a fresh vocab, so prewarm replays these to rebuild rows,
        # masks, and the kernels' compiled shapes off the hot path
        self._recent_pods: "OrderedDict[str, object]" = OrderedDict()
        self._catalog_dirty = False
        self._tick_log: deque = deque(maxlen=64)
        self._prewarm_stats: dict = {}
        self._prewarm_runs = 0
        self._catalog_prewarms = 0
        self._prewarm_solver = None  # (nodepool key, TPUScheduler)
        # warm-state restore outcome (ISSUE 13): per-plane restored/
        # dropped counts of the pre-first-tick restore, for /debug
        self._warmstore_outcome: Optional[dict] = None
        # boot-order contract (ISSUE 17): restore → prewarm → tick 0.
        # Cleared by start() when a restore landed (a jitsig replay is
        # pending on the prewarm thread); the plan thread's first tick
        # waits on it, bounded, so a restored process's first solve
        # dispatches against warm executables and raises zero compile
        # events. Set everywhere else — tick 0 must never deadlock on a
        # replay that will not run.
        self._boot_prewarm_done = threading.Event()
        self._boot_prewarm_done.set()
        self._boot_prewarm_result: Optional[dict] = None
        # chaos-plane degradation state (ISSUE 15): the stale-world
        # guard's freshness stamp (monotonic; any watch delivery is
        # evidence of liveness) + explicit staleness seam, the leader
        # emit gate, and the held-tick counters the bench gates on
        # (held ticks are degradation, never silent)
        self._world_stamp = time.monotonic()
        self._world_stale_flag = False
        self._stale_holds = 0
        self._leader_holds = 0
        self._is_leader: Optional[Callable[[], bool]] = None
        self._leader_guard = None
        self._threads: List[threading.Thread] = []
        self._watch_unsub = None

    # -- ingest stage (watch-callback context) ------------------------------

    def attach_watch(self) -> None:
        """Subscribe the ingest stage to the kube pod watch."""
        self._watch_unsub = self.kube_client.watch("Pod", self.observe_pod_event)

    def observe_pod_event(self, event: str, pod) -> None:
        """Ingest: stamp first-pending arrival (the SLO clock starts
        here) and nudge the batch window. Runs on whatever thread wrote
        the pod — the cheap, nonblocking edge of the pipeline."""
        self.note_world_event()
        if event == "DELETED":
            self.latency.forget(pod.uid)
            return
        if podutils.is_provisionable(pod):
            self.latency.pod_pending(pod.uid)
            with self._mu:
                self._ingested += 1
                self._prewarm_buf.append(pod)
            self.batcher.trigger()
            self._new_pods_evt.set()

    def observe_catalog_event(self) -> None:
        """Ingest for provider-side catalog/price changes (spot price
        storms, offering updates). These arrive asynchronously to pod
        traffic, and re-tensorizing the catalog is the most expensive
        single encode step — the prewarm stage absorbs it into idle
        time, where the tick-shaped loop pays it on its first
        post-event solve."""
        with self._mu:
            self._catalog_dirty = True
        self.note_world_event()
        self._new_pods_evt.set()

    # -- chaos-plane degradation (ISSUE 15) ----------------------------------

    def note_world_event(self) -> None:
        """Any watch/catalog delivery is evidence the observed world is
        live — refresh the stale-world guard's freshness stamp. Called
        from the ingest edge; watch-liveness probes may call it too."""
        with self._mu:
            self._world_stamp = time.monotonic()

    def set_world_stale(self, stale: bool) -> None:
        """Explicit staleness seam: a watch-health monitor (or the chaos
        harness) marks the observed world unsafe to plan against —
        e.g. the watch channel is flapping/hung, or node heartbeats
        stopped. Independent of the age-bound check."""
        with self._mu:
            self._world_stale_flag = bool(stale)

    def world_is_stale(self) -> bool:
        bound = self.config.max_staleness_s
        with self._mu:
            if self._world_stale_flag:
                return True
            if bound > 0.0:
                return (time.monotonic() - self._world_stamp) > bound
        return False

    def attach_leader_gate(self, is_leader: Callable[[], bool]) -> None:
        """Single-writer enforcement under leader election: (a) the plan
        thread holds each tick while not leading, and (b) an admission
        guard on the kube client rejects NodeClaim writes the moment
        leadership is lost — so a failover MID-tick (leadership lost
        after the step started) still cannot emit: the deposed leader's
        in-flight emit raises LostLeadership at the write, the tick
        lands as an error, and the new leader is the sole writer.

        Attach/detach happen while the pipeline is held (or before
        start/after stop) — the admission-guard list itself is only
        ever mutated with no tick in flight."""

        def _guard(obj) -> None:
            if obj.kind == "NodeClaim" and not is_leader():
                raise LostLeadership("NodeClaim write without leadership")

        kc = self.kube_client
        with self._mu:
            self._is_leader = is_leader
            self._leader_guard = _guard
        kc.admission.append(_guard)

    def detach_leader_gate(self) -> None:
        with self._mu:
            guard, self._leader_guard = self._leader_guard, None
            self._is_leader = None
        if guard is not None:
            kc = self.kube_client
            try:
                kc.admission.remove(guard)
            except ValueError:
                pass

    def held_ticks(self) -> dict:
        with self._mu:
            return {"stale": self._stale_holds, "leader": self._leader_holds}

    def _await_emit_preconditions(self) -> bool:
        """The degradation point: before the authoritative step runs,
        prove (a) the observed world is within the freshness bound and
        (b) this process holds leadership. Failing either HOLDS the
        tick — counted once per hold, never emitted — and waits for
        recovery. A held tick keeps its batch token, so the pending work
        is decided the moment the world recovers (degrade to hold +
        counter, never a stale plan). Returns False when stopping."""
        counted_stale = counted_leader = False
        while not self._stop_evt.is_set():
            stale = self.world_is_stale()
            with self._mu:
                is_leader = self._is_leader
            deposed = is_leader is not None and not is_leader()
            if not stale and not deposed:
                return True
            with self._mu:
                if stale and not counted_stale:
                    self._stale_holds += 1
                    counted_stale = True
                if deposed and not counted_leader:
                    self._leader_holds += 1
                    counted_leader = True
            time.sleep(0.005)
        return False

    # -- batch former stage --------------------------------------------------

    def _batch_loop(self) -> None:
        while not self._stop_evt.is_set():
            if not self.batcher.wait():
                continue  # max window elapsed with no trigger — re-check stop
            token = {"formed_at": time.perf_counter()}
            try:
                # blocks while a solve is in flight and one batch is
                # already queued: backpressure, the next window keeps
                # absorbing triggers meanwhile
                self.solve_q.put(token)
            except Closed:
                return

    # -- plan stage (the authoritative thread) -------------------------------

    def _plan_loop(self) -> None:
        # tick-0 gate (ISSUE 17): wait for the boot jitsig replay so the
        # first authoritative solve dispatches against warm executables.
        # Bounded — a wedged replay costs a cold first solve, not a dead
        # pipeline.
        self._boot_prewarm_done.wait(timeout=60.0)
        while True:
            try:
                token = self.solve_q.get(timeout=0.2)
            except Closed:
                return
            if token is None:
                if self._stop_evt.is_set():
                    return
                continue
            # the hold gate sits HERE, not in the window former: a batch's
            # content is determined by the pending listing at solve time,
            # so gating the authoritative step is what makes a lockstep
            # driver's injections atomic w.r.t. decisions (tokens formed
            # early just wait; an extra token solves an empty batch)
            with self._gate_cv:
                while self._gate_held and not self._stop_evt.is_set():
                    self._gate_cv.wait(timeout=0.2)
            if self._stop_evt.is_set():
                return
            # stale-world guard + leader gate (ISSUE 15): the tick holds
            # here — token kept, nothing emitted — until the world is
            # fresh and this process leads. Sits AFTER the hold gate so
            # lockstep drivers stay atomic, BEFORE tick accounting so a
            # held tick never appears as an undrained tick to quiesce().
            if not self._await_emit_preconditions():
                return
            queue_wait_ms = round(
                (time.perf_counter() - token["formed_at"]) * 1000.0, 3
            )
            with self._mu:
                self._ticks += 1
                tick = self._ticks
                self._step_inflight = True
            self._encode_done_evt.clear()
            try:
                rec = self._step.run(tick, queue_wait_ms=queue_wait_ms)
            except Exception:  # noqa: BLE001 — one failed tick must not kill serving
                log.exception("serving tick %d failed", tick)
                rec = {"tick": tick, "error": True}
            finally:
                self._set_plan_ctx(None)
                with self._mu:
                    self._step_inflight = False
                self._encode_done_evt.set()
            self._maybe_disrupt(tick, rec)
            rec["queue_wait_ms"] = queue_wait_ms
            try:
                # the decision's context rides the entry: the telemetry
                # stage adopts it, so its drain work lands on the
                # decision's trace (its own lane, after the root)
                self.telemetry_q.put(rec, timeout=1.0, ctx=self._step.last_ctx)
            except Closed:
                return

    def _set_plan_ctx(self, ctx) -> None:
        with self._mu:
            self._plan_ctx = ctx

    def _maybe_disrupt(self, tick: int, rec: dict) -> None:
        """Continuous-disruption stage: one DisruptionController pass on
        the plan thread every `disrupt_every` ticks (0 = off). Runs
        after the provisioning step so the pass sees this tick's
        nominations; the engine's cross-pass memos make a no-change pass
        nearly free, which is what makes per-tick cadence viable."""
        if self.disruption is None or self.config.disrupt_every <= 0:
            return
        if tick % self.config.disrupt_every != 0:
            return
        t0 = time.perf_counter()
        try:
            executed = self.disruption.reconcile()
        except Exception:  # noqa: BLE001 — a failed pass must not kill serving
            log.exception("serving disruption pass at tick %d failed", tick)
            return
        rec["disrupt_ms"] = round((time.perf_counter() - t0) * 1000.0, 3)
        if executed:
            rec["disrupt_method"] = executed
        stats = getattr(self.disruption, "last_decision_stats", None)
        try:
            self._step.recorder.record(
                "disrupt",
                tick,
                trace=getattr(self.disruption, "last_trace", None),
                solve={"disruption": dict(stats) if stats else None},
                pods_decided=0,
                executed=executed,
            )
        except Exception:  # noqa: BLE001 — telemetry must never fail the pass
            log.debug("disrupt flight-record failed", exc_info=True)
        with self._mu:
            self._disrupt_log.append(
                {
                    "tick": tick,
                    "ms": rec["disrupt_ms"],
                    "executed": executed,
                    "stats": stats,
                }
            )
        if self.metrics is not None:
            self.metrics.serving_stage_duration.observe(
                rec["disrupt_ms"] / 1000.0, stage="disrupt"
            )

    # -- telemetry stage -----------------------------------------------------

    def _telemetry_loop(self) -> None:
        while True:
            try:
                entry = self.telemetry_q.get_entry(timeout=0.2)
            except Closed:
                return
            if entry is None:
                if self._stop_evt.is_set() and self.telemetry_q.depth() == 0:
                    return
                continue
            rec, ctx = entry
            with tracer.adopt(ctx, "telemetry.drain", tick=rec.get("tick")):
                self._record_telemetry(rec)

    def _record_telemetry(self, rec: dict) -> None:
        trace_id = rec.get("trace_id")
        if trace_id:
            trace = tracer.RING.get(trace_id)
            if trace is not None:
                rec["phase_breakdown_ms"] = {
                    k: round(v, 2) for k, v in sorted(trace.phase_breakdown_ms().items())
                }
        if self.metrics is not None and "step_ms" in rec:
            self.metrics.serving_stage_duration.observe(
                rec["step_ms"] / 1000.0, stage="plan"
            )
            self.metrics.serving_stage_duration.observe(
                rec.get("queue_wait_ms", 0.0) / 1000.0, stage="batch_wait"
            )
        with self._mu:
            self._tick_log.append(rec)
            self._telemetry_drained += 1

    # -- prewarm stage (the double buffer) -----------------------------------

    def _prewarm_loop(self) -> None:
        self._boot_prewarm_replay()
        while not self._stop_evt.is_set():
            if not self._new_pods_evt.wait(timeout=0.25):
                continue
            # debounce: let a create burst accumulate (and give the
            # ingesting thread the GIL back) before walking the delta
            time.sleep(0.01)
            self._new_pods_evt.clear()
            if not self.config.prewarm or self._stop_evt.is_set():
                continue
            # the speculative encode shares the catalog lock (and the
            # GIL) with the authoritative encode — running during THAT
            # phase would make the step wait on speculation. The prewarm
            # slot is everything else: the gap between ticks, and — the
            # double buffer — the in-flight step's pack/finalize, which
            # the solver signals via encode_done_listener the moment its
            # encode hands off to device (tick N's pack runs on device
            # while tick N+1's delta encodes on the host).
            if not self._encode_done_evt.wait(timeout=0.05):
                self._new_pods_evt.set()
                continue
            try:
                # adopt the overlapped decision's context (None → the
                # prewarm's own never-buffered roots, as before): the
                # speculative encode shows up on its own lane of the
                # decision it double-buffers
                with self._mu:
                    ctx = self._plan_ctx
                with tracer.adopt(ctx, "prewarm"):
                    self._prewarm_once()
            except Exception:  # noqa: BLE001 — speculation must never break serving
                log.debug("serving prewarm failed", exc_info=True)

    def _boot_prewarm_replay(self) -> None:
        """The prewarm half of the boot-order contract (ISSUE 17,
        restore → prewarm → tick 0): replay the restored jitsig
        inventory through the live registered functions
        (``solver/prewarm.py``) so every predicted compile is paid — a
        persistent-cache hit when the compile-cache plane restored
        clean — before the plan thread's first authoritative tick.
        Runs once, on this thread, gated by the event ``start()`` armed;
        a failed replay degrades to a cold first solve, never a dead
        pipeline."""
        if self._boot_prewarm_done.is_set():
            return
        try:
            from ..solver import prewarm as prewarm_replay

            solver = self._warmstore_solver()
            result = prewarm_replay.warmup_compile_only(solver)
            with self._mu:
                self._boot_prewarm_result = result
        except Exception:  # noqa: BLE001 — replay must never break serving boot
            log.exception("boot jitsig replay failed; first solve runs cold")
        finally:
            self._boot_prewarm_done.set()

    def _prewarm_once(self) -> None:
        """Speculatively encode the newly arrived pods on a dedicated
        solver instance. Warms only content-addressed caches shared by
        construction (see TPUScheduler.encode_prewarm) — safe to race
        the authoritative solve, even on a stale batch guess. Walks the
        ingest delta only: pods already prewarmed (or already decided)
        have warm memos and signature rows, and re-walking the whole
        pending set would steal the GIL from the authoritative stages
        for no cache effect."""
        with self._mu:
            if not self._prewarm_buf and not self._catalog_dirty:
                return
            delta = list(self._prewarm_buf)
            self._prewarm_buf.clear()
            catalog_dirty = self._catalog_dirty
            self._catalog_dirty = False
        if catalog_dirty:
            solver = self._prewarm_scheduler()
            if solver is not None:
                stats = solver.prewarm_catalog()
                with self._mu:
                    self._catalog_prewarms += 1
                    self._prewarm_stats = stats
                # the fresh entry has no compat rows and a fresh vocab:
                # replay the recent workload through the encode so row
                # rebuilds and kernel recompiles happen HERE, not on the
                # first post-event authoritative solve
                with self._mu:
                    recent = list(self._recent_pods.values())
                if recent:
                    stats = solver.encode_prewarm(
                        recent, daemonset_pods=self.cluster.get_daemonset_pods()
                    )
                    with self._mu:
                        self._prewarm_stats = stats
        seen = set()
        pods = []
        for pod in delta:
            if pod.uid not in seen and podutils.is_provisionable(pod):
                seen.add(pod.uid)
                pods.append(pod)
        with self._mu:
            for pod in pods:
                self._recent_pods[pod.uid] = pod
                self._recent_pods.move_to_end(pod.uid)
            while len(self._recent_pods) > 4096:
                self._recent_pods.popitem(last=False)
        if not pods:
            return
        solver = self._prewarm_scheduler()
        if solver is None:
            return
        stats = solver.encode_prewarm(
            pods, daemonset_pods=self.cluster.get_daemonset_pods()
        )
        with self._mu:
            self._prewarm_runs += 1
            self._prewarm_stats = stats

    def _prewarm_scheduler(self):
        """A prewarm-only TPUScheduler (no kube/cluster: it must read no
        authoritative state), rebuilt when the nodepool set changes —
        same reuse discipline as the provisioner's live solver."""
        nodepools = [
            np_
            for np_ in self.kube_client.list("NodePool")
            if np_.metadata.deletion_timestamp is None
        ]
        if not nodepools:
            return None
        key = tuple((id(np_), np_.metadata.resource_version) for np_ in nodepools)
        with self._mu:
            cached = self._prewarm_solver
        if cached is not None and cached[0] == key:
            return cached[1]
        from ..solver import TPUScheduler

        solver = TPUScheduler(nodepools, self.provisioner.cloud_provider)
        with self._mu:
            self._prewarm_solver = (key, solver, list(nodepools))
        return solver

    # -- warm-state persistence (ISSUE 13, solver/warmstore.py) --------------

    def _warmstore_solver(self):
        """The solver whose warm planes snapshot/restore operate on:
        the provisioner's live solver when it exists, else a fresh one
        over the SAME provider object — the warm state and the catalog
        cache are provider-keyed module state, so a restore through it
        warms exactly what the provisioner's next solver will read."""
        cached = self.provisioner._tpu_solver
        if cached is not None:
            return cached[1]
        nodepools = [
            np_
            for np_ in self.kube_client.list("NodePool")
            if np_.metadata.deletion_timestamp is None
        ]
        if not nodepools:
            return None
        from ..solver import TPUScheduler

        return TPUScheduler(
            nodepools,
            self.provisioner.cloud_provider,
            kube_client=self.kube_client,
            cluster=self.cluster,
        )

    def restore_warm_state(self, path: str) -> Optional[dict]:
        """Restore a warm-state snapshot into this pipeline's solver
        world (call before ``start()``; ``start()`` invokes it itself
        when ``config.warmstore_restore`` is set). → outcome dict with
        per-plane restored/dropped counts, or None when no solver can
        be built yet."""
        solver = self._warmstore_solver()
        if solver is None:
            return None
        outcome = solver.restore(path)
        with self._mu:
            self._warmstore_outcome = outcome
        return outcome

    def snapshot_warm_state(self, directory: Optional[str] = None) -> Optional[str]:
        """Snapshot the live solver's warm planes → path (or None when
        persistence is disabled or nothing can be snapshotted)."""
        solver = self._warmstore_solver()
        if solver is None:
            return None
        return solver.snapshot(directory=directory or self.config.warmstore_dir)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        # restore BEFORE the first tick: the plan thread's first solve
        # must already see the restored planes (zero-cold-start restart)
        if self.config.warmstore_restore:
            try:
                self.restore_warm_state(self.config.warmstore_restore)
            except Exception:  # noqa: BLE001 — a bad snapshot degrades to a cold start
                log.exception("warm-state restore failed; starting cold")
        # arm the tick-0 prewarm gate only when a restore actually
        # landed and the jitsig replay is enabled (ISSUE 17): the
        # prewarm thread will replay and release it
        from ..solver import prewarm as prewarm_replay

        with self._mu:
            restored = self._warmstore_outcome is not None
        if restored and prewarm_replay.enabled():
            self._boot_prewarm_done.clear()
        else:
            self._boot_prewarm_done.set()
        self._stop_evt.clear()
        self.solve_q.reopen()
        self.telemetry_q.reopen()
        self._threads = [
            threading.Thread(target=self._batch_loop, name="serve-batch", daemon=True),
            threading.Thread(target=self._plan_loop, name="serve-plan", daemon=True),
            threading.Thread(
                target=self._telemetry_loop, name="serve-telemetry", daemon=True
            ),
            threading.Thread(target=self._prewarm_loop, name="serve-prewarm", daemon=True),
        ]
        for t in self._threads:
            t.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        with self._gate_cv:
            self._gate_cv.notify_all()
        self.batcher.trigger()  # wake a waiting window former
        # closing the queues unblocks any stage parked on put/get; an
        # in-flight authoritative tick still completes first (the plan
        # thread only sees Closed at its next queue operation)
        self.solve_q.close()
        self.telemetry_q.close()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        self._threads = []
        if self._watch_unsub is not None:
            self._watch_unsub()
            self._watch_unsub = None
        self.detach_leader_gate()

    # -- gating / quiescence (lockstep harness + operational pause) ----------

    def hold(self) -> None:
        """Pause batch formation (in-flight ticks finish; triggers keep
        accumulating in the window)."""
        with self._gate_cv:
            self._gate_held = True

    def release(self) -> None:
        with self._gate_cv:
            self._gate_held = False
            self._gate_cv.notify_all()

    def ticks(self) -> int:
        with self._mu:
            return self._ticks

    def quiesce(self, timeout: float = 30.0, require_empty: bool = True):
        """Wait until the decision stream drains: no queued batches, no
        in-flight step, no undrained telemetry (a quiesced pipeline's
        /debug payload is settled — the tick log must already hold every
        completed tick), and (require_empty) no undecided pending pods.

        Returns False on timeout. On success, with a warmstore directory
        configured (``config.warmstore_dir``), the quiesced cache planes
        are snapshotted and the SNAPSHOT PATH is returned (truthy) so
        operators/trafficgen can hand it to a restarted process without
        a side channel; otherwise True."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mu:
                busy = self._step_inflight
                drained = self._telemetry_drained >= self._ticks
            if (
                not busy
                and drained
                and self.solve_q.depth() == 0
                and (not require_empty or self.latency.pending_count() == 0)
            ):
                if self.config.warmstore_dir:
                    path = self.snapshot_warm_state()
                    if path is not None:
                        return path
                return True
            time.sleep(0.002)
        return False

    # -- observability -------------------------------------------------------

    def debug_state(self) -> dict:
        """The /debug/serving payload: config, queue stats, tick log
        tail, prewarm traffic, SLO percentiles."""
        with self._mu:
            ticks = self._ticks
            ingested = self._ingested
            tick_log = list(self._tick_log)[-8:]
            prewarm = {
                "runs": self._prewarm_runs,
                "catalog_prewarms": self._catalog_prewarms,
                "boot_replay": self._boot_prewarm_result,
                **self._prewarm_stats,
            }
            disrupt_log = list(self._disrupt_log)[-4:]
            warmstore_outcome = self._warmstore_outcome
            stale_holds = self._stale_holds
            leader_holds = self._leader_holds
            leader_gate = self._is_leader is not None
        return {
            "config": self.config.to_dict(),
            "ticks": ticks,
            "pods_ingested": ingested,
            "pods_decided": self.latency.decided_count(),
            "pods_pending": self.latency.pending_count(),
            "decision_latency_ms": self.latency.percentiles(),
            "queues": {
                "solve": self.solve_q.stats(),
                "telemetry": self.telemetry_q.stats(),
            },
            "prewarm": prewarm,
            "last_ticks": tick_log,
            "disrupt": {
                "every": self.config.disrupt_every,
                "attached": self.disruption is not None,
                "last_passes": disrupt_log,
            },
            "flightrec": {
                "coverage": self._step.recorder.coverage(kind="pipeline"),
                "burn_rate": self._step.recorder.burn_rates(),
                "retained": len(self._step.recorder),
            },
            "warmstore": warmstore_outcome,
            "chaos": {
                "max_staleness_s": self.config.max_staleness_s,
                "world_stale": self.world_is_stale(),
                "held_ticks": {"stale": stale_holds, "leader": leader_holds},
                "leader_gate": leader_gate,
                "fault_window": flightrec.active_fault_window(),
            },
        }


class SequentialLoop:
    """The tick-shaped baseline: the same authoritative decision step,
    no overlap — window, then solve, then emit, serially on one thread.
    This is the 'equivalent sequential reconcile' the pipeline's plans
    must be byte-identical to, and the latency baseline config 8's SLO
    gate compares against."""

    def __init__(
        self,
        provisioner,
        metrics=None,
        config: Optional[PipelineConfig] = None,
        latency: Optional[DecisionLatencyTracker] = None,
        on_decision: Optional[Callable] = None,
    ):
        self.provisioner = provisioner
        self.kube_client = provisioner.kube_client
        self.metrics = metrics
        self.config = config or PipelineConfig()
        self.latency = latency or DecisionLatencyTracker(
            histogram=getattr(metrics, "serving_decision_latency", None)
        )
        self.batcher = Batcher(
            idle_seconds=self.config.idle_seconds, max_seconds=self.config.max_seconds
        )
        self._step = _DecisionStep(provisioner, self.latency, on_decision)
        self._stop_evt = threading.Event()
        self._mu = threading.Lock()
        self._ticks = 0
        self._thread: Optional[threading.Thread] = None
        self._watch_unsub = None

    def attach_watch(self) -> None:
        self._watch_unsub = self.kube_client.watch("Pod", self.observe_pod_event)

    def observe_pod_event(self, event: str, pod) -> None:
        if event == "DELETED":
            self.latency.forget(pod.uid)
            return
        if podutils.is_provisionable(pod):
            self.latency.pod_pending(pod.uid)
            self.batcher.trigger()

    def step_once(self) -> dict:
        """One synchronous decision tick (the lockstep driver's entry)."""
        with self._mu:
            self._ticks += 1
            tick = self._ticks
        return self._step.run(tick)

    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            if not self.batcher.wait():
                continue
            if self._stop_evt.is_set():
                return
            self.step_once()

    def start(self) -> None:
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._loop, name="seq-loop", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        self.batcher.trigger()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self._watch_unsub is not None:
            self._watch_unsub()
            self._watch_unsub = None

    def ticks(self) -> int:
        with self._mu:
            return self._ticks
