"""Async serving pipeline (ISSUE 6): staged overlap of watch-event
ingestion → batching/encode → device dispatch → finalize/emit, plus the
production traffic simulator that measures it.

Overlap-safety invariant (the PR-4 rule extended): **overlap is
scheduling, never reordering of observable state.** Every observable
state transition — NodeClaim creation, nominations, events — happens on
the single authoritative plan thread in tick order; concurrent stages
only form batches, warm content-addressed caches (whose soundness the
cache-key analysis family proves), and drain telemetry. The pipeline's
plans are therefore byte-identical to the equivalent sequential
reconcile by construction, which `tests/test_serving.py` and bench
config 8 verify against the sequential loop on every scenario.
"""

from .latency import DecisionLatencyTracker, percentiles_ms
from .pipeline import LostLeadership, PipelineConfig, SequentialLoop, ServingPipeline
from .queues import Closed, StageQueue

__all__ = [
    "Closed",
    "DecisionLatencyTracker",
    "LostLeadership",
    "PipelineConfig",
    "SequentialLoop",
    "ServingPipeline",
    "StageQueue",
    "percentiles_ms",
]
