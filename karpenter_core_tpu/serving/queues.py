"""Bounded stage queues — the only legal way mutable work items cross a
pipeline stage boundary (enforced by the pipeline-safety analysis rule:
shared state is either lock-guarded or handed off through one of these).

``put`` blocks when the queue is full: backpressure propagates upstream
instead of buffering unboundedly (a slow solve stage slows batch
formation, which slows ingest, which blocks the watch callback — the
producer feels the pipeline's true capacity). Caps are env-tunable via
``KARPENTER_TPU_SERVING_<NAME>_CAP``.

Trace propagation (ISSUE 10): every ``put`` captures the producer's
``TraceContext`` (or takes an explicit one) into the queue entry, so a
consumer that calls ``get_entry`` can re-adopt the producing decision's
trace on its own thread — the queue is the stage boundary, so it is
also where the trace crosses. Plain ``get`` unwraps the item and drops
the context (existing consumers unchanged).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional, Tuple

from ..tracing import tracer


class Closed(Exception):
    """Raised by put()/get() once the queue is closed (and drained, for
    get)."""


def queue_cap(name: str, default: int) -> int:
    """Env-tunable stage-queue capacity:
    ``KARPENTER_TPU_SERVING_<NAME>_CAP`` (min 1)."""
    try:
        return max(1, int(os.environ.get(f"KARPENTER_TPU_SERVING_{name.upper()}_CAP", default)))
    except ValueError:
        return default


class StageQueue:
    """Bounded FIFO handoff between two pipeline stages.

    Ownership discipline: an item belongs to the producer until ``put``
    returns, to the consumer after ``get`` returns — neither side
    touches it in between, so items need no locks of their own.
    """

    def __init__(self, name: str, maxsize: int, depth_gauge=None):
        self.name = name
        self.maxsize = max(1, int(maxsize))
        self._cv = threading.Condition()
        self._items: deque = deque()
        self._closed = False
        self._high_water = 0
        self._blocked_puts = 0  # backpressure events (puts that had to wait)
        self._total_puts = 0
        # optional metrics Gauge, labeled by stage name
        self._depth_gauge = depth_gauge

    def _set_gauge(self, depth: int) -> None:
        # callers hold self._cv
        if self._depth_gauge is not None:
            self._depth_gauge.set(float(depth), stage=self.name)

    def put(self, item, timeout: Optional[float] = None, ctx=None) -> bool:
        """Enqueue, blocking while full (backpressure). Returns False on
        timeout, True otherwise. Raises Closed after close().

        The producer's active ``TraceContext`` is captured into the
        entry (``ctx`` overrides it — e.g. a context snapshotted before
        the producer's trace root closed); ``get_entry`` hands it to the
        consumer for re-adoption."""
        if ctx is None:
            ctx = tracer.capture()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            blocked = False
            while len(self._items) >= self.maxsize and not self._closed:
                if not blocked:
                    blocked = True
                    self._blocked_puts += 1
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(timeout=remaining)
            if self._closed:
                raise Closed(self.name)
            self._items.append((item, ctx))
            self._total_puts += 1
            depth = len(self._items)
            if depth > self._high_water:
                self._high_water = depth
            self._set_gauge(depth)
            self._cv.notify_all()
            return True

    def get_entry(self, timeout: Optional[float] = None) -> Optional[Tuple[object, object]]:
        """Dequeue one (item, trace context) entry, blocking while
        empty. Returns None on timeout; raises Closed once the queue is
        closed AND drained. The context is the producer's capture (None
        when the producer was untraced) — adopt it to land this stage's
        spans under the producing decision's root."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._items:
                if self._closed:
                    raise Closed(self.name)
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(timeout=remaining)
            entry = self._items.popleft()
            self._set_gauge(len(self._items))
            self._cv.notify_all()
            return entry

    def get(self, timeout: Optional[float] = None):
        """Dequeue, blocking while empty. Returns the item, or None on
        timeout (stages enqueue only non-None work items). Raises
        Closed once the queue is closed AND drained."""
        entry = self.get_entry(timeout=timeout)
        return entry[0] if entry is not None else None

    def close(self) -> None:
        """Wake every waiter; subsequent puts raise, gets drain then
        raise."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def reopen(self) -> None:
        """Reset after close() (pipeline restart); drops undrained
        items."""
        with self._cv:
            self._closed = False
            self._items.clear()
            self._set_gauge(0)

    def depth(self) -> int:
        with self._cv:
            return len(self._items)

    def stats(self) -> dict:
        with self._cv:
            return {
                "depth": len(self._items),
                "cap": self.maxsize,
                "high_water": self._high_water,
                "blocked_puts": self._blocked_puts,
                "total_puts": self._total_puts,
            }
