"""NodePool counter + hash controllers (ref
pkg/controllers/nodepool/counter/controller.go,
pkg/controllers/nodepool/hash/controller.go)."""

from __future__ import annotations

from ..apis import labels as wk
from ..scheduling import resources


class NodePoolCounterController:
    """counter:61-97 — status.resources = Σ capacity of the pool's state
    nodes."""

    def __init__(self, kube_client, cluster):
        self.kube_client = kube_client
        self.cluster = cluster

    def reconcile(self, nodepool) -> None:
        totals = {}

        def visit(state_node) -> bool:
            nonlocal totals
            if state_node.nodepool_name() == nodepool.name:
                totals = resources.merge(totals, state_node.capacity())
            return True

        self.cluster.for_each_node(visit)
        nodepool.status.resources = totals
        self.kube_client.apply(nodepool)

    def reconcile_all(self) -> None:
        for np in self.kube_client.list("NodePool"):
            self.reconcile(np)


class NodePoolHashController:
    """hash:51-62 — stamp karpenter.sh/nodepool-hash for drift detection."""

    def __init__(self, kube_client):
        self.kube_client = kube_client

    def reconcile(self, nodepool) -> None:
        nodepool.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] = nodepool.static_hash()
        self.kube_client.apply(nodepool)

    def reconcile_all(self) -> None:
        for np in self.kube_client.list("NodePool"):
            self.reconcile(np)
