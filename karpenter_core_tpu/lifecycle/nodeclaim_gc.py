"""NodeClaim garbage collection (ref
pkg/controllers/nodeclaim/garbagecollection/controller.go:57-99): every
2 min, diff the cloud provider's machines against cluster NodeClaims and
delete claims whose instance vanished (launched >10 s ago)."""

from __future__ import annotations

import time
from typing import Callable

from ..apis.nodeclaim import COND_LAUNCHED
from ..cloudprovider.types import CloudProvider

LAUNCH_GRACE = 10.0  # seconds a claim must have been launched before GC


class NodeClaimGarbageCollectionController:
    # analysis: allow-clock(GC grace vs persisted creation_timestamp wall-clock stamps)
    def __init__(self, kube_client, cloud_provider: CloudProvider, clock: Callable[[], float] = time.time):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.clock = clock

    def reconcile(self) -> int:
        """Returns the number of claims garbage-collected."""
        cloud_ids = {nc.status.provider_id for nc in self.cloud_provider.list()}
        removed = 0
        now = self.clock()
        for nc in self.kube_client.list("NodeClaim"):
            if nc.metadata.deletion_timestamp is not None:
                continue
            cond = nc.get_condition(COND_LAUNCHED)
            if cond is None or cond.status != "True":
                continue
            if now - cond.last_transition_time < LAUNCH_GRACE:
                continue
            if nc.status.provider_id and nc.status.provider_id not in cloud_ids:
                self.kube_client.delete(nc)
                removed += 1
        # also GC managed nodes whose backing instance is gone and that have
        # no claim left to cascade their deletion
        claim_ids = {
            nc.status.provider_id for nc in self.kube_client.list("NodeClaim")
        }
        from ..apis import labels as wk

        for node in self.kube_client.list("Node"):
            pid = node.spec.provider_id
            managed = wk.NODEPOOL_LABEL_KEY in node.metadata.labels
            if pid and managed and pid not in cloud_ids and pid not in claim_ids:
                self.kube_client.delete(node)
        return removed
