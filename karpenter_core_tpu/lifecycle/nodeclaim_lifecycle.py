"""NodeClaim lifecycle: Launch → Registration → Initialization, with a
Liveness TTL (ref pkg/controllers/nodeclaim/lifecycle/)."""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from ..apis import labels as wk
from ..apis.nodeclaim import (
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
    NodeClaim,
)
from ..cloudprovider.types import (
    CloudProvider,
    InsufficientCapacityError,
    NodeClassNotReadyError,
)
from ..kube.objects import Node, OwnerReference
from ..scheduling.requirements import node_selector_requirements
from ..scheduling.taints import KNOWN_EPHEMERAL_TAINTS, Taints

REGISTRATION_TTL = 15 * 60  # liveness.go:39 registrationTTL


class NodeClaimLifecycleController:
    """lifecycle/controller.go:59-124: adds the termination finalizer then
    runs the four sub-reconcilers."""

    def __init__(
        self,
        kube_client,
        cloud_provider: CloudProvider,
        recorder=None,
        # analysis: allow-clock(registration TTL vs persisted claim creation wall-clock stamps)
        clock: Callable[[], float] = time.time,
        metrics=None,
    ):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.recorder = recorder
        self.clock = clock
        self.metrics = metrics
        # launch result cache: survives status-patch races (launch.go:40)
        self._launch_cache: Dict[str, NodeClaim] = {}

    def reconcile(self, node_claim: NodeClaim) -> Optional[str]:
        """Returns a requeue reason or None."""
        if node_claim.metadata.deletion_timestamp is not None:
            return None
        if wk.TERMINATION_FINALIZER not in node_claim.metadata.finalizers:
            node_claim.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        for step in (self._launch, self._registration, self._initialization, self._liveness):
            result = step(node_claim)
            if result == "deleted":
                return None
            if result is not None:
                return result
        self.kube_client.apply(node_claim)
        return None

    def reconcile_all(self) -> None:
        for nc in self.kube_client.list("NodeClaim"):
            self.reconcile(nc)

    # -- launch (launch.go:44) ---------------------------------------------

    def _launch(self, nc: NodeClaim) -> Optional[str]:
        if nc.status_condition_is_true(COND_LAUNCHED):
            # launch is durable in status now; the race-guard cache entry
            # can go (prevents unbounded growth across node churn)
            self._launch_cache.pop(nc.uid, None)
            return None
        created = self._launch_cache.get(nc.uid)
        if created is None:
            try:
                created = self.cloud_provider.create(nc)
            except InsufficientCapacityError as e:
                if self.recorder is not None:
                    from ..events import events as ev

                    self.recorder.publish(ev.insufficient_capacity(nc, e))
                self.kube_client.delete(nc)
                if self.metrics is not None:
                    self.metrics.nodeclaims_terminated.inc(
                        reason="insufficient_capacity",
                        nodepool=nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, ""),
                    )
                return "deleted"
            except NodeClassNotReadyError:
                nc.set_condition(COND_LAUNCHED, "False", "LaunchFailed", "node class not ready")
                return "requeue"
            except Exception as e:  # noqa: BLE001 — recorded as failed launch
                nc.set_condition(COND_LAUNCHED, "False", "LaunchFailed", str(e)[:300])
                return f"launching nodeclaim, {e}"
        self._launch_cache[nc.uid] = created
        self._populate_details(nc, created)
        nc.set_condition(COND_LAUNCHED, "True")
        if self.metrics is not None:
            self.metrics.nodeclaims_launched.inc(
                nodepool=nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
            )
        return None

    @staticmethod
    def _populate_details(nc: NodeClaim, created: NodeClaim) -> None:
        """launch.go:107 PopulateNodeClaimDetails: provider labels, then
        single-value requirement labels, then user labels (priority asc)."""
        req_labels = node_selector_requirements(nc.spec.requirements).labels()
        nc.metadata.labels = {
            **created.metadata.labels,
            **req_labels,
            **nc.metadata.labels,
        }
        nc.metadata.annotations = {**nc.metadata.annotations, **created.metadata.annotations}
        nc.status.provider_id = created.status.provider_id
        nc.status.image_id = created.status.image_id
        nc.status.allocatable = dict(created.status.allocatable)
        nc.status.capacity = dict(created.status.capacity)

    # -- registration (registration.go:42) ---------------------------------

    def _registration(self, nc: NodeClaim) -> Optional[str]:
        if nc.status_condition_is_true(COND_REGISTERED):
            return None
        if not nc.status_condition_is_true(COND_LAUNCHED):
            nc.set_condition(COND_REGISTERED, "False", "NotLaunched", "Node not launched")
            return None
        nodes = [
            n
            for n in self.kube_client.list("Node")
            if n.spec.provider_id == nc.status.provider_id
        ]
        if not nodes:
            nc.set_condition(COND_REGISTERED, "False", "NodeNotFound", "Node not registered with cluster")
            return None
        if len(nodes) > 1:
            nc.set_condition(
                COND_REGISTERED, "False", "MultipleNodesFound", "Invariant violated, matched multiple nodes"
            )
            return None
        node = nodes[0]
        self._sync_node(nc, node)
        nc.set_condition(COND_REGISTERED, "True")
        nc.status.node_name = node.name
        if self.metrics is not None:
            self.metrics.nodeclaims_registered.inc(
                nodepool=nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
            )
            self.metrics.nodes_created.inc(
                nodepool=nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
            )
        return None

    def _sync_node(self, nc: NodeClaim, node: Node) -> None:
        """registration.go:80 syncNode: finalizer, owner ref, labels,
        annotations, taint merge, registered label."""
        if wk.TERMINATION_FINALIZER not in node.metadata.finalizers:
            node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        node.metadata.owner_references = [
            OwnerReference(
                api_version="karpenter.sh/v1beta1",
                kind="NodeClaim",
                name=nc.name,
                uid=nc.uid,
                controller=True,
                block_owner_deletion=True,
            )
        ]
        node.metadata.labels.update(nc.metadata.labels)
        node.metadata.annotations.update(nc.metadata.annotations)
        node.spec.taints = Taints(node.spec.taints).merge(nc.spec.taints)
        node.spec.taints = Taints(node.spec.taints).merge(nc.spec.startup_taints)
        node.metadata.labels[wk.NODE_REGISTERED_LABEL_KEY] = "true"
        self.kube_client.apply(node)

    # -- initialization (initialization.go:46) -----------------------------

    def _initialization(self, nc: NodeClaim) -> Optional[str]:
        if nc.status_condition_is_true(COND_INITIALIZED):
            return None
        if not nc.status_condition_is_true(COND_LAUNCHED):
            nc.set_condition(COND_INITIALIZED, "False", "NotLaunched", "Node not launched")
            return None
        node = self._node_for(nc)
        if node is None:
            nc.set_condition(COND_INITIALIZED, "False", "NodeNotFound", "Node not registered with cluster")
            return None
        if not _node_ready(node):
            nc.set_condition(COND_INITIALIZED, "False", "NodeNotReady", "Node status is NotReady")
            return None
        for startup in nc.spec.startup_taints:
            if any(startup.match(t) for t in node.spec.taints):
                nc.set_condition(
                    COND_INITIALIZED, "False", "StartupTaintsExist", f"StartupTaint {startup.key} still exists"
                )
                return None
        for known in KNOWN_EPHEMERAL_TAINTS:
            if any(known.match(t) for t in node.spec.taints):
                nc.set_condition(
                    COND_INITIALIZED, "False", "KnownEphemeralTaintsExist", f"Taint {known.key} still exists"
                )
                return None
        for resource_name, qty in nc.spec.resources.requests.items():
            if qty == 0:
                continue
            # extended resources must be registered by device plugins before
            # the node counts as initialized (initialization.go:120-135)
            if node.status.allocatable.get(resource_name, 0) == 0 and resource_name not in (
                "cpu",
                "memory",
                "pods",
                "ephemeral-storage",
            ):
                nc.set_condition(
                    COND_INITIALIZED, "False", "ResourceNotRegistered", f"Resource {resource_name} not registered"
                )
                return None
        node.metadata.labels[wk.NODE_INITIALIZED_LABEL_KEY] = "true"
        self.kube_client.apply(node)
        nc.set_condition(COND_INITIALIZED, "True")
        if self.metrics is not None:
            self.metrics.nodeclaims_initialized.inc(
                nodepool=nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
            )
        return None

    # -- liveness (liveness.go:32) -----------------------------------------

    def _liveness(self, nc: NodeClaim) -> Optional[str]:
        if nc.status_condition_is_true(COND_REGISTERED):
            return None
        ttl_start = nc.metadata.creation_timestamp
        if self.clock() - ttl_start < REGISTRATION_TTL:
            return None
        # failed to register within the TTL: delete and let provisioning retry
        self._launch_cache.pop(nc.uid, None)
        self.kube_client.delete(nc)
        if self.metrics is not None:
            self.metrics.nodeclaims_terminated.inc(
                reason="liveness",
                nodepool=nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, ""),
            )
        return "deleted"

    def _node_for(self, nc: NodeClaim) -> Optional[Node]:
        for n in self.kube_client.list("Node"):
            if n.spec.provider_id == nc.status.provider_id:
                return n
        return None


def _node_ready(node: Node) -> bool:
    for c in node.status.conditions:
        if c.type == "Ready":
            return c.status == "True"
    return False
