"""Consistency checks (ref pkg/controllers/nodeclaim/consistency/):
10-minute scans that alarm on impossible states. Extended here with the
TPU build's parity oracle alarm (SURVEY §5: oracle vs solver divergence
⇒ event + fallback)."""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from ..apis import labels as wk
from ..apis.nodeclaim import COND_INITIALIZED, NodeClaim
from ..scheduling import resources


class Check:
    """controller.go:55 Check interface."""

    def check(self, node_claim: NodeClaim, node) -> List[str]:
        raise NotImplementedError


class TerminationCheck(Check):
    """termination.go:41-59: report WHY a deleting claim is stuck — a
    missing termination finalizer (instance may leak), or a PDB blocking
    the node's drain."""

    def __init__(self, kube_client=None):
        self.kube_client = kube_client
        self._pass: Optional[tuple] = None

    def begin_pass(self) -> None:
        """Snapshot PDBs + reschedulable pods once for a reconcile_all
        scan — per-claim construction re-lists the whole cluster per
        deleting claim, a redundant LIST burst during consolidation
        waves."""
        if self.kube_client is None:
            return
        self._pass = self._snapshot()

    def end_pass(self) -> None:
        self._pass = None

    def _snapshot(self) -> tuple:
        # deferred import: disruption.helpers imports from lifecycle
        from ..disruption.helpers import PDBLimits
        from ..utils import pod as podutils

        pods_by_node: dict = {}
        for p in self.kube_client.list("Pod"):
            if p.spec.node_name and podutils.is_reschedulable(p):
                pods_by_node.setdefault(p.spec.node_name, []).append(p)
        return PDBLimits(self.kube_client), pods_by_node

    def check(self, node_claim: NodeClaim, node) -> List[str]:
        if node_claim.metadata.deletion_timestamp is None:
            return []
        issues: List[str] = []
        if wk.TERMINATION_FINALIZER not in node_claim.metadata.finalizers:
            issues.append("nodeClaim is terminating without the termination finalizer")
        if self.kube_client is not None and node is not None:
            pdbs, pods_by_node = self._pass if self._pass is not None else self._snapshot()
            pdb_name, ok = pdbs.can_evict_pods(pods_by_node.get(node.name, []))
            if not ok:
                issues.append(f"can't drain node, PDB {pdb_name} is blocking evictions")
        return issues


class NodeShapeCheck(Check):
    """nodeshape.go:40: real node capacity must be within expectation
    (±10%) of what the claim promised."""

    TOLERANCE = 0.10

    def check(self, node_claim: NodeClaim, node) -> List[str]:
        if node is None or not node_claim.status_condition_is_true(COND_INITIALIZED):
            return []
        issues = []
        for name, expected in node_claim.status.capacity.items():
            actual = node.status.capacity.get(name, 0)
            if expected > 0 and actual < expected * (1 - self.TOLERANCE):
                issues.append(
                    f"expected {resources.to_string({name: expected})} of resource {name}, "
                    f"but found {resources.to_string({name: actual})}"
                )
        return issues


class ConsistencyController:
    """controller.go:62-113."""

    def __init__(self, kube_client, recorder=None, checks: Optional[List[Check]] = None, metrics=None):
        self.kube_client = kube_client
        self.recorder = recorder
        self.checks = checks or [TerminationCheck(kube_client), NodeShapeCheck()]
        self.metrics = metrics

    def reconcile(self, node_claim: NodeClaim) -> List[str]:
        node = None
        for n in self.kube_client.list("Node"):
            if node_claim.status.provider_id and n.spec.provider_id == node_claim.status.provider_id:
                node = n
                break
        issues: List[str] = []
        for check in self.checks:
            issues.extend(check.check(node_claim, node))
        for issue in issues:
            if self.recorder is not None:
                from ..events import events as ev

                self.recorder.publish(ev.consistency_check_failed(node_claim, issue))
            if self.metrics is not None:
                self.metrics.consistency_errors.inc()
        return issues

    def reconcile_all(self) -> List[str]:
        for check in self.checks:
            begin = getattr(check, "begin_pass", None)
            if begin is not None:
                begin()
        try:
            out = []
            for nc in self.kube_client.list("NodeClaim"):
                out.extend(self.reconcile(nc))
            return out
        finally:
            for check in self.checks:
                end = getattr(check, "end_pass", None)
                if end is not None:
                    end()
