"""kube-node-lease garbage collection (ref
pkg/controllers/leasegarbagecollection/controller.go:53-65): delete
orphaned node leases without owner references."""

from __future__ import annotations


class LeaseGarbageCollectionController:
    def __init__(self, kube_client):
        self.kube_client = kube_client

    def reconcile(self) -> int:
        removed = 0
        for lease in self.kube_client.list("Lease", namespace="kube-node-lease"):
            if not lease.metadata.owner_references:
                self.kube_client.delete(lease)
                removed += 1
        return removed
