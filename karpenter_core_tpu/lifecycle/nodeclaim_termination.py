"""NodeClaim termination finalizer (ref
pkg/controllers/nodeclaim/termination/controller.go:66-100): delete Node
objects, then the cloud instance, then drop the finalizer."""

from __future__ import annotations

from typing import Optional

from ..apis import labels as wk
from ..apis.nodeclaim import NodeClaim
from ..cloudprovider.types import CloudProvider, NodeClaimNotFoundError


class NodeClaimTerminationController:
    def __init__(self, kube_client, cloud_provider: CloudProvider, metrics=None):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.metrics = metrics

    def reconcile(self, node_claim: NodeClaim) -> Optional[str]:
        if node_claim.metadata.deletion_timestamp is None:
            return None
        if wk.TERMINATION_FINALIZER not in node_claim.metadata.finalizers:
            return None
        # delete any nodes linked by provider id; wait for them to go
        nodes = [
            n
            for n in self.kube_client.list("Node")
            if node_claim.status.provider_id
            and n.spec.provider_id == node_claim.status.provider_id
        ]
        if nodes:
            for n in nodes:
                self.kube_client.delete(n)
            return "waiting on node termination"
        if node_claim.status.provider_id:
            try:
                self.cloud_provider.delete(node_claim)
            except NodeClaimNotFoundError:
                pass
        self.kube_client.remove_finalizer(node_claim, wk.TERMINATION_FINALIZER)
        if self.metrics is not None:
            self.metrics.nodeclaims_terminated.inc(
                reason="deleted",
                nodepool=node_claim.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, ""),
            )
        return None

    def reconcile_all(self) -> None:
        for nc in self.kube_client.list("NodeClaim"):
            self.reconcile(nc)
