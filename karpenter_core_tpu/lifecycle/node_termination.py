"""Node termination: taint → drain → evict → provider delete → drop
finalizer (ref pkg/controllers/node/termination/, terminator/)."""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from ..apis import labels as wk
from ..cloudprovider.types import CloudProvider, NodeClaimNotFoundError
from ..kube.objects import EFFECT_NO_SCHEDULE, Node, Pod, Taint
from ..utils import pod as podutils

LB_EXCLUDE_LABEL = "node.kubernetes.io/exclude-from-external-load-balancers"


class NodeDrainError(Exception):
    pass


def pdb_disruptions_allowed(kube_client, pdb) -> int:
    """Dynamic budget, like the real PDB controller: recomputed from the
    current health of matching pods so evictions consume it and healthy
    replacements replenish it. Falls back to the static field when neither
    minAvailable nor maxUnavailable is set."""
    matching = [
        p
        for p in kube_client.list("Pod", namespace=pdb.namespace)
        if pdb.selector.matches(p.metadata.labels)
    ]
    healthy = sum(
        1
        for p in matching
        if p.status.phase == "Running" and p.metadata.deletion_timestamp is None
    )
    if pdb.min_available is not None:
        return healthy - pdb.min_available
    if pdb.max_unavailable is not None:
        unavailable = len(matching) - healthy
        return pdb.max_unavailable - unavailable
    return pdb.disruptions_allowed


class EvictionQueue:
    """Rate-limited eviction queue honoring PDBs (ref
    terminator/eviction.go:65-150). Our in-memory PDB model exposes
    ``disruptions_allowed``; a blocked eviction stays queued (the 429
    path)."""

    def __init__(self, kube_client, recorder=None):
        self.kube_client = kube_client
        self.recorder = recorder
        self._queued: List[tuple] = []

    def add(self, *pods: Pod) -> None:
        for p in pods:
            key = (p.namespace, p.name)
            if key not in self._queued:
                self._queued.append(key)

    def evict(self, pod: Pod) -> bool:
        """True if the eviction was admitted (eviction.go:101 Evict).

        do-not-disrupt is NOT honored here: it gates voluntary disruption
        candidacy (disruption engine), not the termination drain — refusing
        would deadlock node finalization (ref terminator/eviction.go)."""
        for pdb in self.kube_client.list("PodDisruptionBudget", namespace=pod.namespace):
            if pdb.selector.matches(pod.metadata.labels):
                if pdb_disruptions_allowed(self.kube_client, pdb) <= 0:
                    return False  # the PDB 429 path
        self.kube_client.delete(pod)
        if self.recorder is not None:
            from ..events import events as ev

            self.recorder.publish(ev.evict(pod))
        return True

    def reconcile(self) -> None:
        remaining = []
        for ns, name in self._queued:
            pod = self.kube_client.get("Pod", name, namespace=ns)
            if pod is None:
                continue
            if not self.evict(pod):
                remaining.append((ns, name))
        self._queued = remaining


class Terminator:
    """terminator/terminator.go: Taint (:50), Drain (:81)."""

    # analysis: allow-clock(stuck-pod age vs persisted deletionTimestamp wall-clock stamps)
    def __init__(self, kube_client, eviction_queue: EvictionQueue, clock: Callable[[], float] = time.time):
        self.kube_client = kube_client
        self.eviction_queue = eviction_queue
        self.clock = clock

    def taint(self, node: Node) -> None:
        """Cordon with the disruption taint + LB exclusion (terminator.go:50-77)."""
        taint = podutils.DISRUPTION_NO_SCHEDULE_TAINT
        if not any(taint.match(t) for t in node.spec.taints):
            node.spec.taints.append(
                Taint(key=taint.key, value=taint.value, effect=taint.effect)
            )
        node.metadata.labels[LB_EXCLUDE_LABEL] = "true"
        self.kube_client.apply(node)

    STUCK_TERMINATING = 60.0  # pods terminating longer than this are stuck

    def drain(self, node: Node, grace_period: Optional[float] = None) -> None:
        """Evict all evictable pods; raises NodeDrainError while pods remain
        (terminator.go:81-110). Terminating pods still block the drain —
        deleting the instance under a gracefully-shutting-down pod would
        hard-kill it — unless they've been stuck past the threshold."""
        pods = [
            p for p in self.kube_client.list("Pod") if p.spec.node_name == node.name
        ]
        draining = []
        # graceful-node-shutdown eviction waves (terminator.go:113-146):
        # (critical?, daemonset?) → pods, evicted one group per pass
        waves = {
            (False, False): [],
            (False, True): [],
            (True, False): [],
            (True, True): [],
        }
        for p in pods:
            if podutils.is_owned_by_node(p):
                continue  # static pods
            if podutils.is_terminal(p):
                continue
            if podutils.tolerates_disruption_no_schedule_taint(p):
                # tolerating the disruption taint means "stay until node
                # deletion" — never evicted, never blocks (terminator.go:91)
                continue
            if podutils.is_terminating(p):
                if self.clock() - p.metadata.deletion_timestamp > self.STUCK_TERMINATING:
                    continue  # stuck terminating; don't block forever
                # still blocks the drain but does NOT occupy its wave:
                # the next wave starts while this pod shuts down
                # (terminator.go:115-117 skips terminating pods when
                # grouping, deliberately — do not "fix" this)
                draining.append(p)
                continue
            draining.append(p)
            waves[(podutils.is_critical(p), podutils.is_owned_by_daemonset(p))].append(p)
        for key in ((False, False), (False, True), (True, False), (True, True)):
            if waves[key]:
                self.eviction_queue.add(*waves[key])
                break
        if draining:
            self.eviction_queue.reconcile()
            raise NodeDrainError(f"{len(draining)} pods are waiting to be evicted")


class NodeTerminationController:
    """node/termination/controller.go:76-108 finalizer flow."""

    def __init__(self, kube_client, cloud_provider: CloudProvider, terminator: Terminator, recorder=None, metrics=None):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.terminator = terminator
        self.recorder = recorder
        self.metrics = metrics

    def reconcile(self, node: Node) -> Optional[str]:
        if node.metadata.deletion_timestamp is None:
            return None
        if wk.TERMINATION_FINALIZER not in node.metadata.finalizers:
            return None
        # delete any owning NodeClaims first (controller.go:83)
        for nc in self.kube_client.list("NodeClaim"):
            if nc.status.provider_id and nc.status.provider_id == node.spec.provider_id:
                self.kube_client.delete(nc)
        self.terminator.taint(node)
        try:
            self.terminator.drain(node)
        except NodeDrainError as e:
            if self.recorder is not None:
                from ..events import events as ev

                self.recorder.publish(ev.node_failed_to_drain(node, e))
            return str(e)
        # drained: delete the instance then drop the finalizer
        claims = [
            nc
            for nc in self.kube_client.list("NodeClaim")
            if nc.status.provider_id == node.spec.provider_id
        ]
        try:
            if claims:
                self.cloud_provider.delete(claims[0])
            else:
                from ..apis.nodeclaim import NodeClaim

                stub = NodeClaim()
                stub.status.provider_id = node.spec.provider_id
                self.cloud_provider.delete(stub)
        except NodeClaimNotFoundError:
            pass
        self.kube_client.remove_finalizer(node, wk.TERMINATION_FINALIZER)
        if self.metrics is not None:
            self.metrics.nodes_terminated.inc(
                nodepool=node.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
            )
        return None

    def reconcile_all(self) -> None:
        for node in self.kube_client.list("Node"):
            self.reconcile(node)
