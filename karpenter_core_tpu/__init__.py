"""karpenter_core_tpu — a TPU-native cluster-provisioning framework.

Re-designs the capabilities of karpenter-core (reference: /root/reference,
pure Go, sigs.k8s.io/karpenter) around a batched JAX/TPU scheduling core:

- ``kube``        : k8s-shaped object model + in-memory API server fake
- ``apis``        : NodePool / NodeClaim data model (ref pkg/apis/v1beta1)
- ``scheduling``  : requirement algebra, taints, ports, volumes
                    (ref pkg/scheduling)
- ``cloudprovider``: provider SPI + fake (ref pkg/cloudprovider)
- ``state``       : cluster state cache (ref pkg/controllers/state)
- ``scheduler``   : greedy CPU oracle scheduler
                    (ref pkg/controllers/provisioning/scheduling)
- ``solver``      : the TPU path — tensorized constraints, vmapped
                    bin-packing, consolidation repack (no Go analogue;
                    replaces the greedy hot loop)
- ``provisioning``/``disruption``/``lifecycle``: controllers
- ``operator``    : composition root, options, metrics, events
"""

__version__ = "0.1.0"
