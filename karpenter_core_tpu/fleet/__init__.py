"""Fleet solver (ISSUE 9 tentpole): multiplex many tenant clusters
through one device.

The north star is thousands of small tenant clusters, not one giant
one — yet a single-cluster solve pays the full dispatch floor
(solver/calibrate.py) no matter how small the tenant. This package
amortizes that floor across the fleet:

- ``registry``  — per-tenant Cluster/CloudProvider/solver handles with
  strict isolation: no provider or cluster object may serve two
  tenants, and every identity/generation-scoped cross-solve memo a
  tenant's solver touches is tenant-scoped (enforced by the cachesound
  tenant-witness check + kill mutants).
- ``megasolve`` — the mega-solve engine: tenants' pack jobs coalesce
  into one dispatch through the PR-8 ``PackBackend`` seam (ffd and lp
  both batch), catalog archetypes dedupe onto canonical content-
  addressed entries, and job skeletons ride a fleet-wide content plane.
  ``KARPENTER_TPU_FLEET_ENGINE={batched,solo}`` — solo (independent
  per-tenant solves) stays the plan-identity oracle.
- ``scheduler`` — bounded admission with deficit-round-robin fairness
  across tenants, batch-window coalescing, and per-tenant
  decision-latency SLOs (serving/latency.py).
"""

from .megasolve import FleetEngine, TenantOutcome, fleet_engine_name
from .registry import FleetRegistry, TenantHandle
from .scheduler import FleetScheduler

__all__ = [
    "FleetEngine",
    "FleetRegistry",
    "FleetScheduler",
    "TenantHandle",
    "TenantOutcome",
    "fleet_engine_name",
]
