"""Fleet scheduler: bounded admission, deficit-round-robin fairness,
batch-window coalescing, per-tenant decision-latency SLOs.

Admission is backpressure, never loss: ``submit`` blocks while a
tenant's pending queue is at its cap (``KARPENTER_TPU_FLEET_ADMIT_CAP``
pods per tenant) — the PR-6 StageQueue discipline at fleet granularity.

Fairness is deficit round robin over pods: each round, every tenant
with queued work earns a quantum (``KARPENTER_TPU_FLEET_QUANTUM`` pods)
on top of its carried deficit and is admitted up to that budget, in a
fixed rotation order. A hog tenant with 50k queued pods therefore
drains at quantum-per-round while every small tenant's whole backlog
(≤ quantum) is admitted in its very next round — the starvation bound
tests/test_fleet.py asserts.

Latency: arrival is stamped at ``submit`` (first-seen wins), decision
when the round that admitted the pod returns — the same pod-pending →
plan-emitted interval the serving pipeline measures
(serving/latency.py), tracked per tenant and in a fleet-wide
histogram.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..tracing import flightrec, tracer
from .megasolve import FleetEngine, TenantOutcome, _env_int

# submitter trace links retained per tenant between rounds (newest win;
# a link is one trace_id string — the cap only bounds memory, links are
# attribution, not accounting)
_LINKS_KEEP = 64


class FleetScheduler:
    def __init__(
        self,
        engine: FleetEngine,
        metrics=None,
        quantum: Optional[int] = None,
        window_s: Optional[float] = None,
        on_round: Optional[Callable[[int, Dict[str, TenantOutcome]], None]] = None,
    ):
        self.engine = engine
        self.registry = engine.registry
        self.metrics = metrics
        self.quantum = quantum or _env_int("KARPENTER_TPU_FLEET_QUANTUM", 1000)
        if window_s is None:
            try:
                window_s = float(os.environ.get("KARPENTER_TPU_FLEET_WINDOW_MS", "2")) / 1000.0
            except ValueError:
                window_s = 0.002
        self.window_s = max(0.0, window_s)
        self.admit_cap = _env_int("KARPENTER_TPU_FLEET_ADMIT_CAP", 10_000)
        self.on_round = on_round
        burn_gauge = getattr(metrics, "decision_slo_burn", None)
        if burn_gauge is not None:
            flightrec.RECORDER.attach_burn_gauge(burn_gauge)
        # RLock-backed: locked helpers (_admit_locked) re-enter from
        # locked callers (run_round)
        self._cv = threading.Condition(threading.RLock())
        self._queues: Dict[str, deque] = {}
        # per-tenant submitter TraceContext ids since the last round
        # that admitted the tenant (ISSUE 10: fleet lane submissions
        # carry their decision context into the round that serves them)
        self._pending_links: Dict[str, deque] = {}
        self._deficit: Dict[str, float] = {}
        self._rotation: List[str] = []  # arrival order; stable across rounds
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.tick = 0
        self.rounds_run = 0
        self._blocked_submits = 0
        self._submitted = 0
        self._decided = 0
        # per-round admission compositions (fairness witnesses for tests
        # and /debug/fleet), bounded
        self.round_log: deque = deque(maxlen=64)

    # -- admission ----------------------------------------------------------

    def submit(self, tenant_id: str, pods: list, timeout: Optional[float] = None) -> bool:
        """Queue pods for one tenant. Blocks while the tenant's queue is
        full (backpressure — never drops). Returns False only on
        timeout; unknown tenants raise."""
        tenant_id = str(tenant_id)
        handle = self.registry.get(tenant_id)
        if handle is None:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            q = self._queues.get(tenant_id)
            if q is None:
                q = self._queues[tenant_id] = deque()
                self._rotation.append(tenant_id)
            blocked = False
            for pod in pods:
                while len(q) >= self.admit_cap and not self._stop:
                    if not blocked:
                        blocked = True
                        self._blocked_submits += 1
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        return False
                    self._cv.wait(timeout=remaining)
                if self._stop:
                    return False
                q.append(pod)
                self._submitted += 1
                handle.latency.pod_pending(pod.uid, step=self.tick)
            ctx = tracer.capture()
            if ctx is not None:
                links = self._pending_links.setdefault(tenant_id, deque(maxlen=_LINKS_KEEP))
                links.append(ctx.trace_id)
            self._cv.notify_all()
        return True

    def queued(self, tenant_id: Optional[str] = None) -> int:
        with self._cv:
            if tenant_id is not None:
                return len(self._queues.get(str(tenant_id), ()))
            return sum(len(q) for q in self._queues.values())

    def forget_tenant(self, tenant_id: str) -> int:
        """Drop a removed tenant's queue (pods are forgotten, not
        decided — the registry handle is already gone)."""
        tenant_id = str(tenant_id)
        handle = self.registry.get(tenant_id)
        with self._cv:
            q = self._queues.pop(tenant_id, None)
            if tenant_id in self._rotation:
                self._rotation.remove(tenant_id)
            self._deficit.pop(tenant_id, None)
            self._pending_links.pop(tenant_id, None)
            dropped = len(q) if q else 0
            if handle is not None and q:
                for pod in q:
                    handle.latency.forget(pod.uid)
            self._cv.notify_all()
        return dropped

    # -- rounds -------------------------------------------------------------

    def _admit_locked(self) -> Dict[str, list]:
        """Deficit-round-robin admission (re-enters the round's cv)."""
        admitted: Dict[str, list] = {}
        with self._cv:
            for tid in list(self._rotation):
                q = self._queues.get(tid)
                if not q:
                    # classic DRR: an emptied queue carries no credit
                    self._deficit[tid] = 0.0
                    continue
                budget = self._deficit.get(tid, 0.0) + self.quantum
                take = min(len(q), int(budget))
                if take > 0:
                    admitted[tid] = [q.popleft() for _ in range(take)]
                self._deficit[tid] = 0.0 if not q else budget - take
        return admitted

    def run_round(self) -> Dict[str, TenantOutcome]:
        """One synchronous round: DRR-admit, mega-solve, decide."""
        with self._cv:
            admitted = self._admit_locked()
            self.tick += 1
            tick = self.tick
            # the admitted tenants' accumulated submitter links ride
            # into the round; unadmitted tenants keep theirs queued
            links = {
                tid: list(self._pending_links.pop(tid, ()))
                for tid in admitted
                if self._pending_links.get(tid)
            }
            if admitted:
                self.round_log.append(
                    {
                        "tick": tick,
                        "admitted": {t: len(p) for t, p in admitted.items()},
                        "deficits": {t: d for t, d in self._deficit.items() if d},
                    }
                )
            self._cv.notify_all()  # admission freed queue space
        if not admitted:
            return {}
        outcomes = self.engine.solve_round(admitted, links=links)
        max_deficit = 0.0
        with self._cv:
            self.rounds_run += 1
            if self._deficit:
                max_deficit = max(self._deficit.values())
            self._decided += sum(len(p) for p in admitted.values())
        for tid, pods in admitted.items():
            handle = self.registry.get(tid)
            out = outcomes.get(tid)
            if handle is None:
                continue
            solve_tid = (getattr(handle.solver, "last_timings", None) or {}).get(
                "trace_id"
            )
            settled = handle.latency.pods_decided(
                [p.uid for p in pods],
                tick,
                error=out is None or out.error is not None,
                trace_id=solve_tid,
            )
            self._flight_record(tid, tick, handle, out, pods, settled, solve_tid)
        if self.metrics is not None:
            self.metrics.fleet_fairness_deficit.set(float(max_deficit))
            for tid, pods in admitted.items():
                handle = self.registry.get(tid)
                if handle is None:
                    continue
                solve_tid = (getattr(handle.solver, "last_timings", None) or {}).get(
                    "trace_id"
                )
                for s in handle.latency.decisions()[-len(pods):]:
                    self.metrics.fleet_decision_latency.observe(s[1], exemplar=solve_tid)
        if self.on_round is not None:
            self.on_round(tick, outcomes)
        return outcomes

    def _flight_record(self, tid, tick, handle, out, pods, settled, solve_tid) -> None:
        """One per-tenant-per-round decision record (kind=fleet): the
        tenant's pods went pending at submit and were decided when this
        round returned — the same interval the serving records carry."""
        try:
            from ..solver import stats as solver_stats

            flightrec.RECORDER.record(
                "fleet",
                tick,
                trace=tracer.RING.get(solve_tid) if solve_tid else None,
                solve=solver_stats.solve_stats(handle.solver),
                latency_ms=[s * 1000.0 for s in settled],
                pods_decided=len(pods),
                errors=1 if (out is None or out.error is not None) else 0,
                tenant=tid,
            )
        except Exception:  # noqa: BLE001 — telemetry must never fail the round
            import logging

            logging.getLogger("karpenter.fleet").debug(
                "fleet flight-record failed", exc_info=True
            )

    def run_until_idle(self, max_rounds: int = 1_000_000) -> int:
        """Synchronous drive (benches, tests): rounds until every queue
        drains. Returns the number of rounds run."""
        n = 0
        while self.queued() and n < max_rounds:
            self.run_round()
            n += 1
        return n

    # -- background loop ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        with self._cv:
            self._stop = False
        self._thread = threading.Thread(target=self._loop, name="fleet-scheduler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not any(self._queues.values()):
                    self._cv.wait(timeout=0.25)
                if self._stop:
                    return
            # batch window: let concurrent streams coalesce into the round
            if self.window_s:
                time.sleep(self.window_s)
            self.run_round()

    # -- introspection ------------------------------------------------------

    def debug_state(self) -> dict:
        with self._cv:
            return {
                "tick": self.tick,
                "rounds": self.rounds_run,
                "submitted": self._submitted,
                "decided": self._decided,
                "blocked_submits": self._blocked_submits,
                "queued": {t: len(q) for t, q in self._queues.items() if q},
                "deficits": {t: d for t, d in self._deficit.items() if d},
                "quantum": self.quantum,
                "admit_cap": self.admit_cap,
                "round_log": list(self.round_log)[-8:],
            }
