"""Tenant registry: per-tenant control-plane handles, strictly isolated.

One registry holds every tenant the fleet serves. Each tenant gets its
own solver (``TPUScheduler`` with a tenant scope), its own pinned
WarmState (solver/incremental.py), and its own decision-latency
tracker. Isolation is structural, not advisory:

- a CloudProvider or Cluster object registered to one tenant is
  REJECTED for any other tenant (object sharing is how cross-tenant
  cache aliasing starts — generation counters are per-object);
- the solver's tenant scope rides every identity/generation-scoped
  memo key (seed cache, job memo, warm-state resolution), so even a
  deliberately shared provider could not alias two tenants' caches;
- the only cross-tenant sharing is the mega-solve CONTENT plane
  (megasolve.py), which is content-addressed by construction — a hit
  is the same computation, not a neighbor's state.

Tenant catalogs reach the solver through a ``TenantCatalogView``
(megasolve.py): inactive (solo engine) it is a pass-through to the
tenant's own provider; active (batched engine) it resolves the catalog
to the fleet's canonical content-deduped snapshot so content-identical
tenants share one encoded catalog entry.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from ..serving.latency import DecisionLatencyTracker
from ..solver import TPUScheduler
from ..solver.incremental import WarmState

log = logging.getLogger("karpenter.fleet")


class TenantHandle:
    """Everything the fleet holds for one tenant. Mutable counters are
    guarded by the owning registry's lock."""

    def __init__(
        self,
        tenant_id: str,
        nodepools: list,
        provider,
        view,
        solver: TPUScheduler,
        cluster=None,
        kube_client=None,
        latency: Optional[DecisionLatencyTracker] = None,
    ):
        self.tenant_id = tenant_id
        self.nodepools = list(nodepools)
        self.provider = provider  # the tenant's own provider
        self.view = view  # what the solver actually reads (catalog view)
        self.solver = solver
        self.cluster = cluster
        self.kube_client = kube_client
        self.latency = latency or DecisionLatencyTracker()
        self.added_at = time.time()
        # round accounting (registry lock)
        self.solves = 0
        self.pods_solved = 0
        self.last_error: Optional[str] = None
        # admission jitsig-replay outcome (ISSUE 17), when the tenant
        # was admitted with restore_from
        self.prewarm_replay: Optional[dict] = None

    def summary(self) -> dict:
        return {
            "tenant": self.tenant_id,
            "nodepools": [np_.metadata.name for np_ in self.nodepools],
            "solves": self.solves,
            "pods_solved": self.pods_solved,
            "pending": self.latency.pending_count(),
            "decided": self.latency.decided_count(),
            "last_error": self.last_error,
            "prewarm_replay": self.prewarm_replay,
        }


class FleetRegistry:
    """Thread-safe tenant directory; add/remove are steady-state
    operations (the fleet scheduler keeps running through them)."""

    def __init__(self, plane=None, metrics=None, warmstore_dir=None):
        import os

        from .megasolve import CatalogPlane

        self._mu = threading.RLock()
        self._tenants: Dict[str, TenantHandle] = {}
        # object-identity ledgers backing the no-sharing invariant
        self._provider_owner: Dict[int, str] = {}
        self._cluster_owner: Dict[int, str] = {}
        # tenants mid-admission: reserved under _mu in add_tenant's
        # phase 1 so a concurrent duplicate add fails fast, while the
        # expensive phase 2 (prewarm/restore/device replay) runs with
        # the lock RELEASED (wait-under-lock rule)
        self._admitting: set = set()
        self.plane = plane or CatalogPlane()
        self.metrics = metrics
        self.generation = 0  # bumped by add/remove (debug/round snapshots)
        # warm-state persistence (ISSUE 13, solver/warmstore.py): with a
        # directory configured, tenant removal snapshots that tenant's
        # cache planes before eviction, and re-admission restores them —
        # tenant migration between schedulers rides the same seam
        self.warmstore_dir = warmstore_dir or (
            os.environ.get("KARPENTER_TPU_WARMSTORE_DIR", "").strip() or None
        )
        self.evicted_snapshots: Dict[str, str] = {}
        # the FleetEngine serving this registry (attached by its
        # constructor): tenant restores also warm its fleetjob plane
        self.engine = None

    # -- membership ---------------------------------------------------------

    def add_tenant(
        self,
        tenant_id: str,
        nodepools: list,
        provider,
        cluster=None,
        kube_client=None,
        restore_from: Optional[str] = None,
    ) -> TenantHandle:
        """Register a tenant. ``restore_from`` (a warm-state snapshot
        path — e.g. another registry's ``snapshot_tenant`` output, or
        this registry's own pre-eviction snapshot, consulted
        automatically) restores the tenant's cache planes into the new
        solver so its first round is warm (tenant migration)."""
        from .megasolve import TenantCatalogView

        tenant_id = str(tenant_id)
        # -- phase 1 (under _mu): validate + reserve. The identity
        # ledgers and the _admitting set make the reservation visible to
        # concurrent adds; nothing slow happens while the lock is held
        # (the wait-under-lock rule flags prewarm/restore/device replay
        # under _mu — they run in phase 2, unlocked).
        with self._mu:
            if tenant_id in self._tenants or tenant_id in self._admitting:
                raise ValueError(f"tenant {tenant_id!r} already registered")
            owner = self._provider_owner.get(id(provider))
            if owner is not None:
                raise ValueError(
                    f"cloud provider already registered to tenant {owner!r} — "
                    "tenants must not share provider objects (per-object "
                    "generation counters would alias their caches)"
                )
            if cluster is not None:
                c_owner = self._cluster_owner.get(id(cluster))
                if c_owner is not None:
                    raise ValueError(
                        f"cluster already registered to tenant {c_owner!r} — "
                        "tenants must not share cluster state"
                    )
            self._admitting.add(tenant_id)
            self._provider_owner[id(provider)] = tenant_id
            if cluster is not None:
                self._cluster_owner[id(cluster)] = tenant_id
            path = restore_from or self.evicted_snapshots.pop(tenant_id, None)
            popped_eviction = path is not None and restore_from is None

        # -- phase 2 (lock released): build the solver and pay the
        # expensive admission work — catalog prewarm, warm-state restore
        # (file I/O), jitsig device replay. Failures roll the
        # reservation back.
        published = False
        try:
            view = TenantCatalogView(provider, self.plane, tenant_id)
            solver = TPUScheduler(
                nodepools,
                view,
                kube_client=kube_client,
                cluster=cluster,
                tenant=tenant_id,
            )
            # one pinned WarmState per tenant: isolation plus a cache
            # home that cannot be evicted by other tenants' churn (the
            # global registry is a small LRU sized for single-tenant
            # processes)
            solver.warm_state_pin = WarmState(view)
            handle = TenantHandle(
                tenant_id,
                nodepools,
                provider,
                view,
                solver,
                cluster=cluster,
                kube_client=kube_client,
            )
            # admission pays the tenant's catalog fingerprints (once per
            # catalog generation), keeping its first round's timeline
            # clean — see CatalogPlane.prewarm
            self.plane.prewarm(tenant_id, provider, nodepools)
            # migration restore: an explicit snapshot path wins; else a
            # snapshot this registry took when the tenant was evicted
            # (re-admission = migration back). Restored planes re-anchor
            # against the LIVE catalog/cluster world — content that no
            # longer matches is dropped, never trusted (warmstore.py)
            if path is not None:
                from .megasolve import fleet_engine_name

                solver.fleet_plane = (
                    self.engine.skeletons if self.engine is not None else None
                )
                # resolve catalogs exactly as the configured engine's
                # rounds will (batched = canonical content-deduped
                # snapshots): the restored entries must rebind to the
                # SAME objects the first round's encode will look up
                was_active = self.plane.active()
                self.plane.activate(fleet_engine_name() == "batched")
                try:
                    solver.restore(path)
                finally:
                    self.plane.activate(was_active)
                    solver.fleet_plane = None
                # admission prewarm (ISSUE 17): replay the restored
                # jitsig inventory now, on the admitting thread, so the
                # migrated tenant's first round dispatches against warm
                # executables — compiles land under cause=prewarm_replay
                # (a cache hit when the compile-cache plane restored
                # clean), never on the tenant's first solve
                from ..solver import prewarm as prewarm_replay

                try:
                    handle.prewarm_replay = prewarm_replay.warmup_compile_only(solver)
                except Exception:  # noqa: BLE001 — replay must never fail admission
                    log.exception(
                        "tenant %s admission jitsig replay failed", tenant_id
                    )

            # -- phase 3 (under _mu): publish. The reservation made the
            # tenant id and object identities ours, so this cannot race.
            with self._mu:
                self._tenants[tenant_id] = handle
                self._admitting.discard(tenant_id)
                self.generation += 1
                published = True
            return handle
        finally:
            if not published:
                with self._mu:
                    self._admitting.discard(tenant_id)
                    if self._provider_owner.get(id(provider)) == tenant_id:
                        del self._provider_owner[id(provider)]
                    if (
                        cluster is not None
                        and self._cluster_owner.get(id(cluster)) == tenant_id
                    ):
                        del self._cluster_owner[id(cluster)]
                    # keep the migration path retryable: the snapshot
                    # file still exists, so re-admission can restore it
                    if popped_eviction:
                        self.evicted_snapshots.setdefault(tenant_id, path)

    def snapshot_tenant(self, tenant_id: str, directory: Optional[str] = None) -> Optional[str]:
        """Snapshot one tenant's cache planes → path (or None when the
        tenant is unknown or persistence is disabled). The snapshot
        carries the tenant scope, so restoring it into another
        scheduler's registry (``add_tenant(..., restore_from=path)``)
        migrates the tenant warm."""
        from .megasolve import fleet_engine_name

        with self._mu:
            handle = self._tenants.get(str(tenant_id))
        if handle is None:
            return None
        # resolve catalogs exactly as the configured engine's rounds do
        # (batched = canonical snapshots): the snapshotted entries must
        # be the ones the tenant's solves actually warmed
        was_active = self.plane.active()
        self.plane.activate(fleet_engine_name() == "batched")
        try:
            return handle.solver.snapshot(directory=directory or self.warmstore_dir)
        finally:
            self.plane.activate(was_active)

    def snapshot_plane(self, directory: Optional[str] = None) -> Optional[str]:
        """Snapshot the fleet's canonical-catalog content plane → path
        (content-addressed; restoring it into another registry's plane
        saves the first-of-content catalog clone per archetype)."""
        from ..solver import warmstore

        return warmstore.snapshot_fleet_plane(
            self.plane, directory or self.warmstore_dir
        )

    def restore_plane(self, path: str) -> dict:
        from ..solver import warmstore

        return warmstore.restore_fleet_plane(self.plane, path)

    def remove_tenant(self, tenant_id: str) -> bool:
        """Drop a tenant and its pinned caches. Safe during steady
        state: an in-flight round that already holds the handle finishes
        its solve; subsequent rounds no longer see the tenant. With a
        warmstore directory configured the tenant's planes are
        snapshotted BEFORE eviction, so re-admission (migration)
        restores them instead of starting cold."""
        tenant_id = str(tenant_id)
        if self.warmstore_dir:
            path = self.snapshot_tenant(tenant_id)
            if path is not None:
                with self._mu:
                    self.evicted_snapshots[tenant_id] = path
        with self._mu:
            handle = self._tenants.pop(tenant_id, None)
            if handle is None:
                return False
            self._provider_owner.pop(id(handle.provider), None)
            if handle.cluster is not None:
                self._cluster_owner.pop(id(handle.cluster), None)
            self.generation += 1
            return True

    # -- lookup -------------------------------------------------------------

    def get(self, tenant_id: str) -> Optional[TenantHandle]:
        with self._mu:
            return self._tenants.get(str(tenant_id))

    def tenant_ids(self) -> List[str]:
        with self._mu:
            return sorted(self._tenants)

    def __len__(self) -> int:
        with self._mu:
            return len(self._tenants)

    def record_solve(self, tenant_id: str, pods: int, error: Optional[str] = None) -> None:
        with self._mu:
            handle = self._tenants.get(tenant_id)
            if handle is None:
                return
            handle.solves += 1
            handle.pods_solved += pods
            handle.last_error = error

    def debug_state(self) -> dict:
        with self._mu:
            return {
                "generation": self.generation,
                "tenants": [h.summary() for _, h in sorted(self._tenants.items())],
            }
