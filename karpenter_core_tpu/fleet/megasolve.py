"""Mega-solve: N tenants' solves through one device, one dispatch.

Three cooperating pieces, all opt-in per round via
``KARPENTER_TPU_FLEET_ENGINE`` (default ``batched``; ``solo`` is the
plan-identity oracle — independent per-tenant solves, exactly what a
standalone per-tenant serving stack would run):

- **CatalogPlane** — content-dedupes tenant catalogs. Small tenants
  overwhelmingly run catalog *archetypes* (the same instance-type
  menu); solo serving re-encodes that menu once per tenant. The plane
  maps (tenant, pool, provider catalog generation) to a content
  fingerprint (the ``fleetenv`` memo — computed once per generation,
  not per solve) and fingerprints to one canonical deep-copied catalog
  snapshot (``fleetcanon``), so content-identical tenants resolve to
  the SAME catalog object and share one encoded `_CatalogEntry` (and
  with it the compat-row cache). Snapshots are plane-owned copies: a
  tenant mutating its own catalog in place can never corrupt what
  other tenants read.

- **SkeletonPlane** — the fleet-wide job-skeleton memo (``fleetjob``).
  A job key minus its trailing tenant scope is pure content (catalog
  entry identity+fingerprint, pool fingerprint, request digest, every
  mask, engine+backend tokens — solver._job_key), and the skeleton is
  a deterministic function of that content, so sharing across tenants
  is memoization, never approximation.

- **_MegaDispatcher** — pack-call coalescing. Each tenant solve runs on
  a worker thread with a thread-local ``_CoalescingBackend`` installed
  (solver/backends.set_thread_backend); its pack submissions park at a
  quiescence barrier and flush as ONE ``PackBackend.pack_jobs`` call —
  pack.batch_pack then buckets the combined fleet's jobs by padded
  shape into a few vmapped dispatches (the lp backend batches its dual
  relaxations the same way). Per-job results are independent of batch
  composition (vmap lanes are independent; the native packer is
  per-job), so demuxed results are byte-identical to solo packs by
  construction.

Identity invariant: batched plans are byte-identical to solo plans for
the same tenant inputs (bench config 11 and tests/test_fleet.py gate
it). Isolation invariant: the only cross-tenant sharing is
content-addressed; every identity/generation-scoped memo carries the
tenant scope (cachesound tenant-witness check + kill mutants).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..solver import backends as backends_mod
from ..solver.backends import PackBackend
from ..solver.incremental import LRU, CacheStats
from ..tracing import tracer


def fleet_engine_name() -> str:
    """Engine switch, read per round (the PR-2/7/8 pattern). Unknown
    names degrade to the default, never fail the round."""
    name = os.environ.get("KARPENTER_TPU_FLEET_ENGINE", "batched").strip().lower()
    return name if name in ("batched", "solo") else "batched"


def _env_int(name: str, default: int, floor: int = 1) -> int:
    try:
        return max(floor, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _clone_catalog(its: list) -> list:
    """Plane-owned snapshot of a tenant catalog: every field the
    encoding (and the emitted plans) read is copied, so no tenant's
    in-place mutation can reach the canonical entry. Field-level, not
    deepcopy — InstanceType carries a lazy-allocatable lock."""
    from ..cloudprovider.types import (
        InstanceType,
        InstanceTypeOverhead,
        Offering,
        Offerings,
    )

    out = []
    for it in its:
        out.append(
            InstanceType(
                it.name,
                it.requirements.copy(),
                Offerings(
                    Offering(o.capacity_type, o.zone, o.price, o.available)
                    for o in it.offerings
                ),
                dict(it.capacity),
                overhead=InstanceTypeOverhead(
                    dict(it.overhead.kube_reserved),
                    dict(it.overhead.system_reserved),
                    dict(it.overhead.eviction_threshold),
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# catalog content plane


class CatalogPlane:
    """Content-addressed canonical catalogs for the batched engine."""

    def __init__(self) -> None:
        self._mu = threading.RLock()
        # (tenant_id, pool name, provider catalog generation) -> content
        # fingerprint: the generation is the provider's own invalidation
        # witness (PR-4 trusted-generation contract), so the fingerprint
        # is computed once per catalog generation, not once per solve
        self._envelopes = LRU("fleetenv")
        # content fingerprint -> (canonical snapshot, plane generation)
        self._canon = LRU("fleetcanon")
        self._next_gen = 0
        self._active = False
        self.stats = CacheStats()

    def activate(self, on: bool) -> None:
        with self._mu:
            self._active = bool(on)

    def active(self) -> bool:
        with self._mu:
            return self._active

    def _fingerprint_for(self, tenant_id: str, pool_name, gen, its) -> bytes:
        from ..solver.solver import _catalog_fingerprint

        key = (tenant_id, pool_name, gen)
        fp = self._envelopes.get(key, self.stats)
        if fp is None:
            fp = _catalog_fingerprint(its)
            # the provider's generation in the key witnesses the catalog
            # content ``its`` (the generation bumps on every catalog
            # mutation — the trusted-generation contract the cache-
            # invalidation rule enforces), and (tenant_id, pool name)
            # witness WHICH provider's catalog this is
            # analysis: allow-cache-key(its)
            self._envelopes.put(key, fp, self.stats)
        return fp

    def _canonical_for(self, fp: bytes, its: list) -> tuple:
        with self._mu:
            canon = self._canon.get(fp, self.stats)
            if canon is None:
                # plane-owned deep copy: tenants keep their own objects,
                # the canonical snapshot can never be mutated under the
                # shared encoded entry's feet
                self._next_gen += 1
                canon = (_clone_catalog(its), ("fleet", self._next_gen))
                # content-addressed: the fingerprint IS the full read-set
                # of the snapshot (it digests every field the encoding
                # reads — solver._catalog_fingerprint)
                # analysis: allow-cache-key(its)
                self._canon.put(fp, canon, self.stats)
        return canon

    def prewarm(self, tenant_id: str, provider, nodepools) -> None:
        """Admission-time envelope warm: each pool catalog's content
        fingerprint (and, first-of-content, its canonical snapshot) is
        computed when the fleet LEARNS the tenant, not inside the
        tenant's first serving round — one fingerprint per catalog
        generation, ever (the solo engine pays none: it rides the
        provider's trusted generation directly). Mid-stream catalog
        mutations re-fingerprint lazily in-round, once."""
        cg = getattr(provider, "catalog_generation", None)
        for np_ in list(nodepools) or [None]:
            its = provider.get_instance_types(np_)
            gen = cg(np_) if callable(cg) else None
            if gen is None:
                continue
            pool_name = np_.metadata.name if np_ is not None else None
            fp = self._fingerprint_for(tenant_id, pool_name, gen, its)
            self._canonical_for(fp, its)

    def resolve(self, tenant_id: str, provider, nodepool) -> Tuple[list, object]:
        """→ (catalog, generation witness) for one tenant pool.

        Inactive, or for providers without a trusted generation counter
        (content changes would be invisible to the envelope memo), this
        is a pass-through of the tenant's own catalog."""
        its = provider.get_instance_types(nodepool)
        cg = getattr(provider, "catalog_generation", None)
        gen = cg(nodepool) if callable(cg) else None
        if not self.active() or gen is None:
            return its, gen
        pool_name = nodepool.metadata.name if nodepool is not None else None
        fp = self._fingerprint_for(tenant_id, pool_name, gen, its)
        return self._canonical_for(fp, its)

    def export_canon(self) -> list:
        """(fingerprint, canonical catalog) pairs for the warm-state
        snapshot writer (solver/warmstore.py). The ``fleetenv`` envelope
        memo is NOT exported: its keys are per-provider generation
        counters that do not survive a restart — admission prewarm
        recomputes them against the live counters (one fingerprint per
        catalog generation, the same cost it pays today)."""
        with self._mu:
            return [(fp, canon[0]) for fp, canon in self._canon.items()]

    def import_canon(self, entries: list) -> int:
        """Install persisted canonical catalogs. Content-addressed by
        construction (the fingerprint digests every field the encoding
        reads), and plane generations are RE-MINTED — a restored
        snapshot must never collide with generations this process
        already handed out."""
        n = 0
        with self._mu:
            for fp, catalog in entries:
                if self._canon.get(fp) is None:
                    self._next_gen += 1
                    # analysis: allow-cache-key(entries)
                    self._canon.put(fp, (list(catalog), ("fleet", self._next_gen)))
                    n += 1
        return n

    def debug_state(self) -> dict:
        with self._mu:
            return {
                "active": self._active,
                "envelopes": len(self._envelopes),
                "canonical_catalogs": len(self._canon),
                "stats": self.stats.to_dict(),
            }


class TenantCatalogView:
    """CloudProvider facade a tenant's solver reads: pass-through in
    solo mode, canonical content-deduped snapshots in batched mode.
    Everything except the catalog surface delegates to the tenant's own
    provider (create/delete/list stay strictly per-tenant)."""

    def __init__(self, provider, plane: CatalogPlane, tenant_id: str):
        self._provider = provider
        self._plane = plane
        self._tenant_id = tenant_id

    def get_instance_types(self, nodepool=None):
        catalog, _gen = self._plane.resolve(self._tenant_id, self._provider, nodepool)
        return catalog

    def catalog_generation(self, nodepool=None):
        _catalog, gen = self._plane.resolve(self._tenant_id, self._provider, nodepool)
        return gen

    def __getattr__(self, name):
        return getattr(self._provider, name)


# ---------------------------------------------------------------------------
# fleet-wide job-skeleton content plane


class SkeletonPlane:
    """Accessor pair around the ``fleetjob`` LRU — the solver consults
    it from ``_pack_and_finalize`` under the tenant-free content prefix
    of the job key (key[:-1]); see the soundness argument there."""

    def __init__(self) -> None:
        self._skeletons = LRU("fleetjob")

    def skeleton_get(self, key: tuple, stats: Optional[CacheStats] = None):
        return self._skeletons.get(key, stats)

    def skeleton_put(self, key: tuple, skel, stats: Optional[CacheStats] = None) -> None:
        self._skeletons.put(key, skel, stats)

    def __len__(self) -> int:
        return len(self._skeletons)


# ---------------------------------------------------------------------------
# pack coalescing: the one-dispatch mega-solve


class _PackWait:
    """One tenant thread's parked pack submission. ``ctx`` is the
    submitting lane's TraceContext (the tenant solve in flight on that
    worker): the flush records every parked lane's trace as a link on
    the shared mega-dispatch span, and each lane's trace links back —
    one batched dispatch ⇒ N tenant decisions, navigable both ways."""

    __slots__ = ("jobs", "metas", "mesh", "results", "flags", "error", "done", "ctx")

    def __init__(self, jobs, metas, mesh):
        self.jobs = jobs
        self.metas = metas
        self.mesh = mesh
        self.results = None
        self.flags: List[bool] = []
        self.error: Optional[BaseException] = None
        self.done = False
        self.ctx = tracer.capture()


class _MegaDispatcher:
    """Quiescence-flush coalescer: pack submissions from tenant worker
    threads park here; when every busy worker is parked (or the safety
    window expires), the LAST arrival flushes them all as ONE call into
    the real pack backend. Per-job pack results do not depend on batch
    composition, so flush grouping affects latency only, never plans."""

    def __init__(self, backend: PackBackend, window: float = 0.05):
        self._backend = backend
        self._window = window
        self._cv = threading.Condition()
        self._active = 0  # workers currently driving a tenant solve
        self._pending: List[_PackWait] = []
        self.stats = CacheStats()  # fleet-level relax-memo traffic (lp)
        # mega-dispatch observability (gauges + /debug/fleet)
        self.flushes = 0
        self.calls = 0
        self.jobs_in = 0
        self.max_occupancy = 0
        self.pad_real = 0
        self.pad_slots = 0
        # accumulated LP-backend outcome across flushes (ISSUE 19: the
        # branch frontier coalesces through this dispatcher — its
        # pruning/refinement counters must stay visible at fleet scale,
        # never vanish into the shared dispatch)
        self.lp_totals: dict = {}

    def target_token(self) -> tuple:
        """The REAL backend's job token: fleet job-memo keys must equal
        solo keys for identical content (that equality is what lets the
        content plane and the per-tenant memos interoperate)."""
        return self._backend.job_token()

    def worker_begin(self) -> None:
        with self._cv:
            self._active += 1

    def worker_end(self) -> None:
        with self._cv:
            self._active -= 1
            # a departing worker can complete quiescence for the rest
            self._cv.notify_all()

    def submit(self, jobs: list, metas: list, mesh) -> Tuple[list, List[bool]]:
        w = _PackWait(jobs, metas, mesh)
        with self._cv:
            self._pending.append(w)
            self.calls += 1
            self.jobs_in += len(jobs)
            self._cv.notify_all()
        while True:
            batch: Optional[List[_PackWait]] = None
            with self._cv:
                if w.done:
                    break
                if self._pending and len(self._pending) >= max(self._active, 1):
                    # quiescence: every busy worker is parked here
                    batch, self._pending = self._pending, []
                elif not self._cv.wait(timeout=self._window):
                    if not w.done and self._pending:
                        # safety flush: progress even if a worker stalls
                        # outside the barrier (grouping is latency-only)
                        batch, self._pending = self._pending, []
            if batch is not None:
                self._run_batch(batch)
                with self._cv:
                    self._cv.notify_all()
        if w.error is not None:
            raise w.error
        return w.results, w.flags

    def _run_batch(self, batch: List[_PackWait]) -> None:
        from ..solver.pack import _pad_class

        all_jobs = [j for w in batch for j in w.jobs]
        all_metas = [m for w in batch for m in w.metas]
        mesh = batch[0].mesh
        # the flushing lane executes the shared dispatch inside its own
        # tenant solve's trace; every coalesced lane's trace is recorded
        # as a link on the shared pack span (and reciprocally), so each
        # tenant's flight record can name the dispatch that served it
        links = [w.ctx.trace_id for w in batch if w.ctx is not None]
        flusher_id = tracer.current_trace_id()
        if flusher_id is not None:
            for w in batch:
                if w.ctx is not None and w.ctx.trace_id != flusher_id:
                    w.ctx.trace.add_link(flusher_id, via="fleet.megadispatch")
        try:
            with tracer.span(
                "fleet.megadispatch",
                jobs=len(all_jobs),
                tenant_calls=len(batch),
                links=links,
            ):
                # the real backend's lock spans the call and its per-call
                # outputs (the PR-8 singleton discipline)
                with self._backend.lock:
                    # analysis: allow-wait-under-lock(device — backend.lock exists to serialize this dispatch and its output reads; the flusher holds nothing else, so the edge cannot deadlock)
                    packed = self._backend.pack_jobs(
                        all_jobs, all_metas, mesh=mesh, stats=self.stats
                    )
                    flags = list(getattr(self._backend, "last_job_flags", ()) or ())
                    # per-call outputs read under the same lock that
                    # serialized the dispatch (the PR-8 discipline)
                    bstats = dict(getattr(self._backend, "last_stats", {}) or {})
            if len(flags) != len(all_jobs):
                flags = [False] * len(all_jobs)
            with self._cv:
                self.flushes += 1
                self.max_occupancy = max(self.max_occupancy, len(batch))
                for k, v in bstats.items():
                    # batch-level accumulation (guard wins, refinement
                    # rounds, branch outcomes, ascent iterations): the
                    # stats are batch-global — per-tenant attribution
                    # does not exist at this seam, so they surface via
                    # summary()/debug, never double-counted per tenant
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        self.lp_totals[k] = round(self.lp_totals.get(k, 0) + v, 6)
                for j in all_jobs:
                    p = int(j[0].shape[0])
                    self.pad_real += p
                    self.pad_slots += _pad_class(p)
            pos = 0
            for w in batch:
                n = len(w.jobs)
                w.results = packed[pos : pos + n]
                w.flags = flags[pos : pos + n]
                pos += n
        except BaseException as err:  # noqa: BLE001 — every waiter must wake with the error
            for w in batch:
                w.error = err
        finally:
            with self._cv:
                for w in batch:
                    w.done = True
                self._cv.notify_all()

    def summary(self) -> dict:
        with self._cv:
            waste = (
                round(1.0 - self.pad_real / self.pad_slots, 4) if self.pad_slots else 0.0
            )
            out = {
                "flushes": self.flushes,
                "pack_calls": self.calls,
                "jobs": self.jobs_in,
                "max_occupancy": self.max_occupancy,
                "padding_waste": waste,
            }
            if self.lp_totals:
                # fleet-level LP outcome (ISSUE 19): guard wins and the
                # refinement/branch counters of every coalesced dispatch
                out["lp"] = dict(self.lp_totals)
            return out


class _CoalescingBackend(PackBackend):
    """Per-tenant-thread facade over the mega-dispatcher. The job token
    delegates to the real backend so job-memo keys (and with them the
    content plane) are engine-agnostic."""

    name = "fleet"

    def __init__(self, dispatcher: _MegaDispatcher):
        super().__init__()
        self._dispatcher = dispatcher
        self.last_stats: dict = {}

    def job_token(self) -> tuple:
        return self._dispatcher.target_token()

    def pack_jobs(self, jobs, metas, mesh=None, stats=None):
        results, flags = self._dispatcher.submit(jobs, metas, mesh)
        self.last_job_flags = flags
        return results


# ---------------------------------------------------------------------------
# the fleet engine


class TenantOutcome:
    """One tenant's result for one round."""

    __slots__ = ("result", "error", "ms", "pods")

    def __init__(self, result=None, error: Optional[str] = None, ms: float = 0.0, pods: int = 0):
        self.result = result
        self.error = error
        self.ms = ms
        self.pods = pods


class FleetEngine:
    """Runs fleet rounds: a mapping {tenant_id: pending pods} in, a
    mapping {tenant_id: TenantOutcome} out, behind the engine switch."""

    def __init__(self, registry, metrics=None):
        self.registry = registry
        self.metrics = metrics
        self.skeletons = SkeletonPlane()
        # tenant warm-state restores (registry.add_tenant restore_from)
        # also publish restored job skeletons into this content plane
        registry.engine = self
        self._mu = threading.Lock()
        self._round = 0
        self.last_round: dict = {}
        self.last_dispatch: dict = {}
        # tenant-label cardinality cap for the per-tenant metrics: the
        # first N tenants keep their label, the rest collapse to
        # "_other" (a fleet of thousands must not mint thousands of
        # label sets per counter)
        self._label_cap = _env_int("KARPENTER_TPU_FLEET_TENANT_LABELS", 64)
        self._labeled: set = set()

    def _tenant_label(self, tenant_id: str) -> str:
        with self._mu:
            if tenant_id in self._labeled:
                return tenant_id
            if len(self._labeled) < self._label_cap:
                self._labeled.add(tenant_id)
                return tenant_id
            return "_other"

    # -- per-tenant solve ---------------------------------------------------

    def _solve_tenant(
        self, tenant_id: str, pods: list, engine: str, links: Optional[list] = None
    ) -> TenantOutcome:
        handle = self.registry.get(tenant_id)
        if handle is None:
            return TenantOutcome(error=f"unknown tenant {tenant_id!r}", pods=len(pods))
        t0 = time.perf_counter()
        try:
            result = handle.solver.solve(pods)
            out = TenantOutcome(
                result=result, ms=(time.perf_counter() - t0) * 1000.0, pods=len(pods)
            )
            if links:
                # the submitting lanes' contexts (FleetScheduler.submit
                # captures one per submission): linked onto the tenant
                # solve's trace so a submitter's decision navigates to
                # the solve (and the mega-dispatch) that served it
                tid = (getattr(handle.solver, "last_timings", None) or {}).get("trace_id")
                tr = tracer.RING.get(tid) if tid else None
                if tr is not None:
                    for link in links:
                        tr.add_link(link, via="fleet.submit")
        except Exception as err:  # noqa: BLE001 — one tenant's failure must not fail the round
            out = TenantOutcome(
                error=f"{type(err).__name__}: {err}",
                ms=(time.perf_counter() - t0) * 1000.0,
                pods=len(pods),
            )
        self.registry.record_solve(tenant_id, len(pods), out.error)
        if self.metrics is not None:
            label = self._tenant_label(tenant_id)
            self.metrics.fleet_solves.inc(tenant=label, engine=engine)
            self.metrics.fleet_pods.inc(len(pods), tenant=label)
        return out

    # -- rounds -------------------------------------------------------------

    def solve_round(
        self, work: Dict[str, list], links: Optional[Dict[str, list]] = None
    ) -> Dict[str, TenantOutcome]:
        """One fleet round over {tenant_id: pods}. Engine read per
        round. ``links`` optionally carries per-tenant submitter trace
        ids (FleetScheduler lane submissions) to attach to each tenant
        solve's trace."""
        engine = fleet_engine_name()
        t0 = time.perf_counter()
        order = sorted(work)
        links = links or {}
        plane = self.registry.plane
        plane.activate(engine == "batched")
        for tid in order:
            handle = self.registry.get(tid)
            if handle is not None:
                handle.solver.fleet_plane = self.skeletons if engine == "batched" else None
        if engine == "solo":
            outcomes = {
                tid: self._solve_tenant(tid, work[tid], engine, links.get(tid))
                for tid in order
            }
            dispatch: dict = {}
        else:
            outcomes, dispatch = self._solve_batched(work, order, engine, links)
        dt = time.perf_counter() - t0
        with self._mu:
            self._round += 1
            self.last_dispatch = dispatch
            self.last_round = {
                "round": self._round,
                "engine": engine,
                "tenants": len(order),
                "pods": sum(len(p) for p in work.values()),
                "ms": round(dt * 1000.0, 3),
                "errors": {t: o.error for t, o in outcomes.items() if o.error},
                "composition": [
                    {"tenant": t, "pods": len(work[t]), "ms": round(outcomes[t].ms, 3)}
                    for t in order
                ],
                "dispatch": dispatch,
            }
        if self.metrics is not None:
            self.metrics.fleet_round_duration.observe(dt, engine=engine)
            if dispatch:
                occ = dispatch.get("max_occupancy", 0)
                self.metrics.fleet_batch_occupancy.set(float(occ))
                self.metrics.fleet_padding_waste.set(float(dispatch.get("padding_waste", 0.0)))
        return outcomes

    def _solve_batched(
        self,
        work: Dict[str, list],
        order: List[str],
        engine: str,
        links: Optional[Dict[str, list]] = None,
    ) -> Tuple[Dict[str, TenantOutcome], dict]:
        links = links or {}
        dispatcher = _MegaDispatcher(backends_mod.active_backend())
        outcomes: Dict[str, TenantOutcome] = {}
        out_mu = threading.Lock()
        queue = list(order)
        q_mu = threading.Lock()

        def next_tenant() -> Optional[str]:
            with q_mu:
                return queue.pop(0) if queue else None

        def run_worker() -> None:
            dispatcher.worker_begin()
            backends_mod.set_thread_backend(_CoalescingBackend(dispatcher))
            try:
                while True:
                    tid = next_tenant()
                    if tid is None:
                        return
                    out = self._solve_tenant(tid, work[tid], engine, links.get(tid))
                    with out_mu:
                        outcomes[tid] = out
            finally:
                backends_mod.set_thread_backend(None)
                dispatcher.worker_end()

        # default 4 lanes: on the CPU fallback the tenant pipelines are
        # host-bound (more lanes just contend), while enough remain to
        # keep the quiescence barrier's mega-dispatches multi-tenant;
        # on a real device, raise it — lanes overlap device waits
        n_workers = min(len(order), _env_int("KARPENTER_TPU_FLEET_WORKERS", 4))
        threads = [
            threading.Thread(target=run_worker, name=f"fleet-worker-{i}", daemon=True)
            for i in range(max(n_workers, 1))
        ]
        for t in threads:
            t.start()
        # bounded join (wait-under-lock no-timeout sub-check): a wedged
        # worker lane must surface as a counted timeout outcome, never a
        # silent hang of the whole round
        deadline = time.monotonic() + _env_int("KARPENTER_TPU_FLEET_JOIN_TIMEOUT_S", 300)
        stragglers = 0
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                stragglers += 1
        summary = dispatcher.summary()
        summary["join_timeouts"] = stragglers
        if stragglers:
            with out_mu:
                for tid in order:
                    if tid not in outcomes:
                        outcomes[tid] = TenantOutcome(
                            error="fleet worker join timed out", pods=len(work[tid])
                        )
        return outcomes, summary

    def debug_state(self) -> dict:
        with self._mu:
            last_round = dict(self.last_round)
        return {
            "engine": fleet_engine_name(),
            "registry": self.registry.debug_state(),
            "catalog_plane": self.registry.plane.debug_state(),
            "skeleton_plane": len(self.skeletons),
            "last_round": last_round,
        }
