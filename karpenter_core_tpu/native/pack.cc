// Native K-open first-fit-decreasing packer — the host half of the
// hybrid solver engine.
//
// Exact per-pod mirror of the TPU scan in solver/pack.py::ffd_pack
// (itself the tensorized Scheduler.add loop, scheduler.go:238-285):
//   - pods arrive sorted descending; each goes to the open slot with the
//     fewest pods (ties to the oldest claim) whose accumulated usage
//     still fits under some Pareto-frontier allocatable point
//     (scheduler.go:247-254 "fewest pods first"),
//   - when none fits but a fresh node would, the slot with the least
//     primary-axis headroom is closed and a new node opens,
//   - pods that fit no frontier point emit node_id = -1.
//
// Why native: the pack is inherently sequential scalar work (each pod's
// placement depends on every prior placement) — a poor fit for the MXU
// and a ~10 us/step lax.scan, but ~500 int ops/pod in C++. The TPU owns
// what it is good at (S x T compat/offering matmuls, vmapped
// consolidation repacks); this packer owns the serial tail. Built once
// via native/build.py (g++ -O3), loaded with ctypes; the TPU scan
// remains the fallback when the toolchain is absent.

#include <cstdint>
#include <vector>

extern "C" {

// requests: (P, R) int32 row-major, pre-sorted descending by primary.
// frontier: (F, R) int32 Pareto-maximal allocatable vectors.
// node_ids_out: (P,) int32, -1 => unschedulable.
// Returns the number of nodes opened.
int32_t ffd_pack_native(const int32_t* requests, int64_t P, int64_t R,
                        const int32_t* frontier, int64_t F,
                        int32_t max_pods_per_node, int32_t k_open,
                        int32_t* node_ids_out) {
  const int64_t K = k_open;
  std::vector<int64_t> usage(K * R, 0);
  std::vector<int64_t> count(K, 0);
  std::vector<int64_t> node_id(K, -1);
  int32_t next_id = 0;

  // frontier max on the primary axis, for eviction headroom
  int64_t fmax0 = 0;
  for (int64_t f = 0; f < F; ++f) {
    if (frontier[f * R] > fmax0) fmax0 = frontier[f * R];
  }

  for (int64_t p = 0; p < P; ++p) {
    const int32_t* req = requests + p * R;

    // best fitting slot: fewest pods, ties to oldest claim. The order
    // score replicates the TPU kernel's float32 arithmetic exactly
    // (pack.py: count.f32 + node_id.f32 * 1e-7, first-min argmin) so the
    // two engines stay bit-identical even where f32 rounding collapses
    // nearby node ids.
    int64_t best_k = -1;
    float best_order = 0.0f;
    for (int64_t k = 0; k < K; ++k) {
      if (node_id[k] < 0 || count[k] >= max_pods_per_node) continue;
      const int64_t* u = usage.data() + k * R;
      bool fits = false;
      for (int64_t f = 0; f < F && !fits; ++f) {
        const int32_t* fr = frontier + f * R;
        bool ok = true;
        for (int64_t r = 0; r < R; ++r) {
          if (u[r] + req[r] > fr[r]) { ok = false; break; }
        }
        fits = ok;
      }
      if (!fits) continue;
      float order = static_cast<float>(count[k]) +
                    static_cast<float>(node_id[k]) * 1e-7f;
      if (best_k < 0 || order < best_order) {
        best_k = k;
        best_order = order;
      }
    }

    if (best_k >= 0) {
      int64_t* u = usage.data() + best_k * R;
      for (int64_t r = 0; r < R; ++r) u[r] += req[r];
      count[best_k] += 1;
      node_ids_out[p] = static_cast<int32_t>(node_id[best_k]);
      continue;
    }

    // fresh-node feasibility
    bool fresh = false;
    for (int64_t f = 0; f < F && !fresh; ++f) {
      const int32_t* fr = frontier + f * R;
      bool ok = true;
      for (int64_t r = 0; r < R; ++r) {
        if (req[r] > fr[r]) { ok = false; break; }
      }
      fresh = ok;
    }
    if (!fresh) {
      node_ids_out[p] = -1;
      continue;
    }

    // slot to (re)use: first inactive, else least primary headroom
    int64_t k_new = -1;
    for (int64_t k = 0; k < K; ++k) {
      if (node_id[k] < 0) { k_new = k; break; }
    }
    if (k_new < 0) {
      int64_t best_head = INT64_MAX;
      for (int64_t k = 0; k < K; ++k) {
        int64_t head = fmax0 - usage[k * R];
        if (head < best_head) { best_head = head; k_new = k; }
      }
    }
    int64_t* u = usage.data() + k_new * R;
    for (int64_t r = 0; r < R; ++r) u[r] = req[r];
    count[k_new] = 1;
    node_id[k_new] = next_id;
    node_ids_out[p] = next_id;
    ++next_id;
  }
  return next_id;
}

// First-fit onto EXISTING nodes in fixed order — the reference tries
// in-flight/real nodes before opening any new claim (scheduler.go:
// 241-246, existingnode.go:64-120), in initialized-then-name order.
// requests: (P, R) int32, pre-sorted descending by primary axis.
// sig_ids: (P,) int32 signature-group index per pod.
// compat: (S, M) uint8 — signature x node admissibility (taints
//   tolerated + node labels satisfy the pod's requirements).
// free_caps: (M, R) int32 remaining capacity, MUTATED in place.
// assign_out: (P,) int32 node index or -1 (pod left for new-node pack).
// Returns the number of pods assigned.
int64_t pack_existing_native(const int32_t* requests, int64_t P, int64_t R,
                             const int32_t* sig_ids, const uint8_t* compat,
                             int64_t S, int32_t* free_caps, int64_t M,
                             int32_t* assign_out) {
  (void)S;
  int64_t assigned = 0;
  for (int64_t p = 0; p < P; ++p) {
    const int32_t* req = requests + p * R;
    const uint8_t* row = compat + static_cast<int64_t>(sig_ids[p]) * M;
    int64_t chosen = -1;
    for (int64_t m = 0; m < M; ++m) {
      if (!row[m]) continue;
      int32_t* f = free_caps + m * R;
      if (f[0] < req[0]) continue;  // cheap primary-axis reject
      bool ok = true;
      for (int64_t r = 1; r < R; ++r) {
        if (req[r] > f[r]) { ok = false; break; }
      }
      if (!ok) continue;
      for (int64_t r = 0; r < R; ++r) f[r] -= req[r];
      chosen = m;
      break;
    }
    assign_out[p] = static_cast<int32_t>(chosen);
    if (chosen >= 0) ++assigned;
  }
  return assigned;
}

// Cheapest viable instance type per packed node
// (fake/cloudprovider.go:105-110 launch decision): for each node's
// summed usage, the min-price type whose allocatable holds it.
// usage: (N, R) int64; allocatable: (T, R) int32; prices: (T,) double.
// out: (N,) int32 type index, -1 if none fits.
void cheapest_types_native(const int64_t* usage, int64_t N, int64_t R,
                           const int32_t* allocatable, int64_t T,
                           const double* prices, int32_t* out) {
  for (int64_t n = 0; n < N; ++n) {
    const int64_t* u = usage + n * R;
    double best_price = 0;
    int64_t best_t = -1;
    for (int64_t t = 0; t < T; ++t) {
      const int32_t* a = allocatable + t * R;
      bool ok = true;
      for (int64_t r = 0; r < R; ++r) {
        if (u[r] > a[r]) { ok = false; break; }
      }
      if (!ok) continue;
      if (best_t < 0 || prices[t] < best_price) {
        best_t = t;
        best_price = prices[t];
      }
    }
    out[n] = static_cast<int32_t>(best_t);
  }
}

}  // extern "C"
