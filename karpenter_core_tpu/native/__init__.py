"""Native (C++) host-side solver components.

The hybrid solver engine splits work by hardware affinity: the TPU runs
the massively parallel tensor stages (signature x type compat matmuls,
offering masks, vmapped consolidation repacks) while the inherently
sequential FFD pack tail runs in C++ (see pack.cc). This mirrors the
reference, whose hot loops are compiled Go (scheduler.go:140-285) —
a Python-only pack would be neither faithful to that nor fast.

The shared library is compiled on first use with g++ (cached next to
the source); everything degrades gracefully to the TPU lax.scan path
when a toolchain is unavailable or KARPENTER_TPU_NATIVE=0.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "pack.cc")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _lib_path() -> str:
    """Build artifact named by the source's content hash — a binary is
    reused only when it provably matches pack.cc (mtimes don't survive
    git checkouts, so an mtime staleness check would silently prefer a
    stale binary on fresh clones)."""
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(os.path.dirname(__file__), f"_libpack-{digest}.so")


def _build(lib_path: str) -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", lib_path],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except Exception:
        return False
    # drop build artifacts of older pack.cc revisions (gitignored, so
    # they'd otherwise accumulate invisibly across source edits)
    import glob

    for stale in sorted(glob.glob(os.path.join(os.path.dirname(__file__), "_libpack-*.so"))):
        if stale != lib_path:
            try:
                os.unlink(stale)
            except OSError:
                pass
    return True


def load() -> Optional[ctypes.CDLL]:
    """The packer library, building it on first call; None if unavailable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried:
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("KARPENTER_TPU_NATIVE", "1") == "0":
            return None
        lib_path = _lib_path()
        if not os.path.exists(lib_path):
            if not _build(lib_path):
                return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            return None
        lib.ffd_pack_native.restype = ctypes.c_int32
        lib.ffd_pack_native.argtypes = [
            ctypes.POINTER(ctypes.c_int32),  # requests
            ctypes.c_int64,  # P
            ctypes.c_int64,  # R
            ctypes.POINTER(ctypes.c_int32),  # frontier
            ctypes.c_int64,  # F
            ctypes.c_int32,  # max_pods_per_node
            ctypes.c_int32,  # k_open
            ctypes.POINTER(ctypes.c_int32),  # node_ids_out
        ]
        lib.pack_existing_native.restype = ctypes.c_int64
        lib.pack_existing_native.argtypes = [
            ctypes.POINTER(ctypes.c_int32),  # requests
            ctypes.c_int64,  # P
            ctypes.c_int64,  # R
            ctypes.POINTER(ctypes.c_int32),  # sig_ids
            ctypes.POINTER(ctypes.c_uint8),  # compat
            ctypes.c_int64,  # S
            ctypes.POINTER(ctypes.c_int32),  # free_caps (in-out)
            ctypes.c_int64,  # M
            ctypes.POINTER(ctypes.c_int32),  # assign_out
        ]
        lib.cheapest_types_native.restype = None
        lib.cheapest_types_native.argtypes = [
            ctypes.POINTER(ctypes.c_int64),  # usage
            ctypes.c_int64,  # N
            ctypes.c_int64,  # R
            ctypes.POINTER(ctypes.c_int32),  # allocatable
            ctypes.c_int64,  # T
            ctypes.POINTER(ctypes.c_double),  # prices
            ctypes.POINTER(ctypes.c_int32),  # out
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def ffd_pack_native(
    requests: np.ndarray,  # (P, R) int32, sorted descending by primary
    frontier: np.ndarray,  # (F, R) int32
    max_pods_per_node: int,
    k_open: int = 16,
):
    """→ (node_ids (P,) int32, node_count int). Exact semantic twin of
    solver.pack.ffd_pack (asserted by tests/test_native_pack.py)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native packer unavailable")
    requests = np.ascontiguousarray(requests, dtype=np.int32)
    frontier = np.ascontiguousarray(frontier, dtype=np.int32)
    P, R = requests.shape
    node_ids = np.empty(P, dtype=np.int32)
    count = lib.ffd_pack_native(
        requests.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        P,
        R,
        frontier.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        frontier.shape[0],
        np.int32(min(int(max_pods_per_node), 2**31 - 1)),
        k_open,
        node_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return node_ids, int(count)


def pack_existing_native(
    requests: np.ndarray,  # (P, R) int32, sorted descending by primary
    sig_ids: np.ndarray,  # (P,) int32
    compat: np.ndarray,  # (S, M) uint8/bool
    free_caps: np.ndarray,  # (M, R) int32 — MUTATED in place
):
    """First-fit pods onto existing nodes in fixed node order; semantic
    twin of solver.pack.pack_existing (the lax.scan device variant).
    → (assign (P,) int32 node index or -1, n_assigned int)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native packer unavailable")
    requests = np.ascontiguousarray(requests, dtype=np.int32)
    sig_ids = np.ascontiguousarray(sig_ids, dtype=np.int32)
    compat = np.ascontiguousarray(compat, dtype=np.uint8)
    assert free_caps.dtype == np.int32 and free_caps.flags.c_contiguous
    P, R = requests.shape
    S, M = compat.shape
    assign = np.empty(P, dtype=np.int32)
    n = lib.pack_existing_native(
        requests.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        P,
        R,
        sig_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        compat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        S,
        free_caps.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        M,
        assign.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return assign, int(n)


def cheapest_types_native(
    usage: np.ndarray,  # (N, R) int
    allocatable: np.ndarray,  # (T, R) int32
    prices: np.ndarray,  # (T,) f64
) -> np.ndarray:
    lib = load()
    if lib is None:
        raise RuntimeError("native packer unavailable")
    usage = np.ascontiguousarray(usage, dtype=np.int64)
    allocatable = np.ascontiguousarray(allocatable, dtype=np.int32)
    prices = np.ascontiguousarray(prices, dtype=np.float64)
    N, R = usage.shape
    out = np.empty(N, dtype=np.int32)
    lib.cheapest_types_native(
        usage.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        N,
        R,
        allocatable.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        allocatable.shape[0],
        prices.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out
