"""Per-solve device-attributable time accounting (VERDICT r4: no metric
reported the device-vs-host split, so "TPU-native" wasn't measurable).

Thread-local accumulator; the solver resets it per solve and every
device boundary (dispatch, transfer, blocking conversion) runs under
``track()``. The figure is *device-attributable wall time* — dispatch +
transfer + time blocked waiting on device results — not on-chip
execution time (XLA overlaps that with host work by design; an exact
split needs the xprof trace, KARPENTER_TPU_PROFILE_DIR).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from ..tracing.tracer import span as _span

_tls = threading.local()


def reset() -> None:
    _tls.seconds = 0.0


def seconds() -> float:
    return getattr(_tls, "seconds", 0.0)


@contextmanager
def track():
    """Accumulate device-attributable time; under an active solve trace
    each tracked region is also a ``device_wait`` span, so the exported
    trace shows *where* in the host pipeline the device waits sit."""
    t0 = time.perf_counter()
    try:
        with _span("device_wait"):
            yield
    finally:
        _tls.seconds = getattr(_tls, "seconds", 0.0) + (time.perf_counter() - t0)
