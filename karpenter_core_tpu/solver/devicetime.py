"""Per-solve device-attributable time accounting (VERDICT r4: no metric
reported the device-vs-host split, so "TPU-native" wasn't measurable).

Thread-local accumulator; the solver resets it per solve and every
device boundary (dispatch, transfer, blocking conversion) runs under
``track()``. The figure is *device-attributable wall time* — dispatch +
transfer + time blocked waiting on device results — not on-chip
execution time (XLA overlaps that with host work by design; an exact
split needs the xprof trace, KARPENTER_TPU_PROFILE_DIR).

ISSUE 16 extends the seam in two directions:

- ``track(phase=...)`` labels each ``device_wait`` span with the solve
  phase it belongs to (pack, shard, lp, screen, existing) so the
  host/device split in ``phase_breakdown_ms`` attributes correctly, and
  ``transfer()`` rides the same boundary to account H2D/D2H bytes per
  phase into the device plane (tracing/deviceplane.py).
- ``device_memory_stats()`` polls the backend's HBM watermarks. It
  lives HERE, not in deviceplane, because the tracing tier is host-only
  by rule (jnp-host-only): jax stays behind the solver boundary.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional

from ..tracing import deviceplane
from ..tracing.tracer import span as _span

_tls = threading.local()


def reset() -> None:
    _tls.seconds = 0.0


def seconds() -> float:
    return getattr(_tls, "seconds", 0.0)


@contextmanager
def track(phase: str = "solve"):
    """Accumulate device-attributable time; under an active solve trace
    each tracked region is also a ``device_wait`` span (labeled with its
    solve ``phase``), so the exported trace shows *where* in the host
    pipeline the device waits sit."""
    t0 = time.perf_counter()
    try:
        with _span("device_wait", phase=phase):
            yield
    finally:
        _tls.seconds = getattr(_tls, "seconds", 0.0) + (time.perf_counter() - t0)


def transfer(direction: str, *arrays, phase: str = "solve", nbytes: Optional[int] = None) -> None:
    """Account one host/device transfer at a tracked boundary:
    ``direction`` is ``h2d`` (arguments shipped to the device) or
    ``d2h`` (results synced back). Pass the arrays themselves (sized
    duck-typed) or an explicit ``nbytes``."""
    n = nbytes if nbytes is not None else deviceplane.nbytes_of(*arrays)
    deviceplane.record_transfer(direction, n, phase=phase)


def device_memory_stats() -> Optional[dict]:
    """HBM watermarks of device 0, where the backend exposes them
    (TPU PJRT does; cpu returns None and the device block falls back to
    the padded-footprint estimate). Never raises — telemetry must not
    take down a solve."""
    if not deviceplane.enabled():
        return None
    try:
        import jax

        devices = jax.local_devices()
        if not devices:
            return None
        stats = getattr(devices[0], "memory_stats", None)
        raw = stats() if callable(stats) else None
        if not raw:
            return None
        out = {}
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit", "largest_alloc_size"):
            if key in raw:
                out[key] = int(raw[key])
        return out or None
    except Exception:  # noqa: BLE001 — a missing/odd backend degrades to "no HBM numbers"
        return None
