"""Per-solve device-attributable time accounting (VERDICT r4: no metric
reported the device-vs-host split, so "TPU-native" wasn't measurable).

Thread-local accumulator; the solver resets it per solve and every
device boundary (dispatch, transfer, blocking conversion) runs under
``track()``. The figure is *device-attributable wall time* — dispatch +
transfer + time blocked waiting on device results — not on-chip
execution time (XLA overlaps that with host work by design; an exact
split needs the xprof trace, KARPENTER_TPU_PROFILE_DIR).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

_tls = threading.local()


def reset() -> None:
    _tls.seconds = 0.0


def seconds() -> float:
    return getattr(_tls, "seconds", 0.0)


@contextmanager
def track():
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _tls.seconds = getattr(_tls, "seconds", 0.0) + (time.perf_counter() - t0)
