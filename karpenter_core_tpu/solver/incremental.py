"""Cross-tick incremental solve state (ISSUE 4 tentpole).

The provisioner ticks continuously; between ticks 90%+ of pending pods,
NodePools, and the instance-type catalog are unchanged. This module
holds the state that lets a *warm* solve skip the host phases a cold
solve would recompute — under one invariant: **reuse is memoization,
never approximation**. Every cache below is content-addressed by the
exact inputs of a deterministic computation, so a warm solve is
plan-identical to a cold solve of the same inputs by construction
(the same discipline PR 2 established for the merge engines).

Cache layers, coarsest first:

- **solve replay** (``WarmState.try_replay``): when a tick's inputs are
  provably identical to the previous tick's (same pod objects at the
  same positions with unchanged resource_versions, same pool
  fingerprints, same catalog generation/fingerprint, same daemonsets,
  no external state the solve could read: no kube client, no cluster,
  no state nodes, no oracle fallback last tick), the previous result is
  re-materialized without entering the pipeline. Anything unprovable →
  automatic full-solve fallback.
- **route cache**: the tensor/parked/oracle split is a pure function of
  the batch's ordered interned-signature tuple (signatures embed every
  label key any selector in the batch can match), so the split is
  memoized on that tuple.
- **compat rows** (stored on ``_CatalogEntry.sig_rows``): per (pool
  fingerprint, interned signature id), the ``SignaturePoolCompat``
  verdict plus the kernel's allowed/zone/capacity-type rows. Rows are
  *semantic* — vocab growth interns new values but never changes the
  verdict for an existing (signature, type) pair — so they key on the
  catalog entry (identity + fingerprint/generation) and pool
  fingerprint only.
- **job memo** (``WarmState.jobs``): per pack job, keyed by a digest of
  the sorted request matrix plus every mask/price input the finalize
  step reads, the pack result and the finalize skeleton (node
  memberships by *position*, chosen types, offerings). A hit skips the
  pack dispatch (zero H2D for that job) and the whole finalize
  recompute; positions rebind to the tick's batch indices.
- **merge memo** (``WarmState.merges``): keyed by the ordered stream of
  record identities ((job key, node ordinal)); a hit replays the
  recorded absorption trails and emitted offerings instead of
  re-screening.
- **seed cache** (``WarmState.seeds``): topology seed counts keyed by
  (constraint, cluster generation) — valid only while the cluster's
  generation counter (state/cluster.py) is unchanged.
- **intersects**: the merge screen's Requirements.intersects verdicts
  are fingerprint-addressed, so they persist across solves.

Kill switch: ``KARPENTER_TPU_INCREMENTAL=0`` disables every layer (the
cold path is the reference the tests compare against). Each cache is
LRU-capped (env-tunable, see ``_CAPS``) with eviction counters so a
long-lived operator cannot grow host memory without bound.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

# env-tunable LRU caps (entries), one knob per cache
_CAPS = {
    "route": ("KARPENTER_TPU_ROUTE_CACHE_MAX", 64),
    "compat": ("KARPENTER_TPU_COMPAT_CACHE_MAX", 4096),
    "job": ("KARPENTER_TPU_JOB_CACHE_MAX", 256),
    "merge": ("KARPENTER_TPU_MERGE_CACHE_MAX", 32),
    "emit": ("KARPENTER_TPU_EMIT_CACHE_MAX", 2048),
    "mergerow": ("KARPENTER_TPU_MERGEROW_CACHE_MAX", 2048),
    "seeds": ("KARPENTER_TPU_SEED_CACHE_MAX", 256),
    # LP-relaxation memo (solver/backends/lp.py): content-addressed dual
    # solves (request digest + capacity/price tables + iteration budget)
    "lprelax": ("KARPENTER_TPU_LPRELAX_CACHE_MAX", 512),
    # disruption-engine memos (disruption/engine.py): family bounds per
    # candidate set, negative drain verdicts per drained subset
    "disruptbounds": ("KARPENTER_TPU_DISRUPT_BOUNDS_CACHE_MAX", 64),
    "disruptverify": ("KARPENTER_TPU_DISRUPT_VERIFY_CACHE_MAX", 4096),
    # fleet mega-solve memos (fleet/megasolve.py): per-tenant catalog
    # content fingerprints keyed by trusted generation, canonical
    # catalog snapshots keyed by content, and the fleet-wide content
    # plane of job skeletons
    "fleetenv": ("KARPENTER_TPU_FLEET_ENV_CACHE_MAX", 1024),
    "fleetcanon": ("KARPENTER_TPU_FLEET_CANON_CACHE_MAX", 64),
    "fleetjob": ("KARPENTER_TPU_FLEET_JOB_CACHE_MAX", 2048),
}
_INTERSECTS_MAX = 4096  # content-addressed; clearing only costs re-derivation


def enabled() -> bool:
    """Master switch, read per solve (tests flip it per case)."""
    return os.environ.get("KARPENTER_TPU_INCREMENTAL", "1") != "0"


def cache_cap(name: str) -> int:
    env, default = _CAPS[name]
    try:
        return max(1, int(os.environ.get(env, default)))
    except ValueError:
        return default


class CacheStats:
    """Per-solve hit/miss/eviction counters, one bucket per cache."""

    __slots__ = ("hits", "misses", "evictions")

    def __init__(self) -> None:
        self.hits: Dict[str, int] = {}
        self.misses: Dict[str, int] = {}
        self.evictions: Dict[str, int] = {}

    def hit(self, cache: str, n: int = 1) -> None:
        self.hits[cache] = self.hits.get(cache, 0) + n

    def miss(self, cache: str, n: int = 1) -> None:
        self.misses[cache] = self.misses.get(cache, 0) + n

    def evict(self, cache: str, n: int = 1) -> None:
        self.evictions[cache] = self.evictions.get(cache, 0) + n

    def to_dict(self) -> dict:
        out: dict = {"hits": dict(self.hits), "misses": dict(self.misses)}
        if self.evictions:
            out["evictions"] = dict(self.evictions)
        total_h = sum(self.hits.values())
        total = total_h + sum(self.misses.values())
        if total:
            out["hit_rate"] = round(total_h / total, 4)
        return out


class LRU:
    """Tiny thread-safe LRU with per-operation stats accounting."""

    def __init__(self, name: str):
        self.name = name
        self._d: OrderedDict = OrderedDict()
        self._mu = threading.Lock()

    def get(self, key, stats: Optional[CacheStats] = None):
        with self._mu:
            v = self._d.get(key)
            if v is None:
                if stats is not None:
                    stats.miss(self.name)
                return None
            self._d.move_to_end(key)
        if stats is not None:
            stats.hit(self.name)
        return v

    def put(self, key, value, stats: Optional[CacheStats] = None) -> None:
        cap = cache_cap(self.name)
        with self._mu:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > cap:
                self._d.popitem(last=False)
                if stats is not None:
                    stats.evict(self.name)

    def __len__(self) -> int:
        with self._mu:
            return len(self._d)

    def items(self) -> list:
        """Point-in-time (key, value) snapshot in LRU order, oldest
        first — the warm-state snapshot writer (solver/warmstore.py)
        serializes planes through this so iteration never races puts."""
        with self._mu:
            return list(self._d.items())

    def clear(self) -> None:
        with self._mu:
            self._d.clear()


@dataclass
class SigRow:
    """One cached (signature, pool) compat verdict + kernel rows.

    ``allowed``/``zone_ok``/``ct_ok`` are semantic over the catalog
    entry's types/zones/capacity-types — invariant under vocab growth
    (new interned values never flip an existing pair's verdict)."""

    compat: object  # encode.SignaturePoolCompat
    allowed: np.ndarray  # (T,)
    zone_ok: np.ndarray  # (Z,)
    ct_ok: np.ndarray  # (C,)


@dataclass
class JobSkeleton:
    """Pack + finalize products of one job, positional (rebindable).

    Every array indexes the job's size-sorted pod order, so a hit tick
    rebinds members through its own sorted ``idx`` without recomputing
    the pack, the per-node usage, the type choice, or the offerings."""

    node_count: int
    positions: np.ndarray  # valid sorted positions, grouped by node
    bounds: np.ndarray  # (node_count + 1,) into positions
    unsched: np.ndarray  # positions whose pod packed nowhere
    ok: np.ndarray  # (N,) node has a fitting type
    underfull: np.ndarray  # (N,) usage*2 <= alloc_cap
    usage64: np.ndarray  # (N, R) int64
    alloc_cap: np.ndarray  # (R,) int32 — the merge pass's cheap-reject seed
    ok_ord: np.ndarray  # (N,) ordinal among ok nodes
    t_global: np.ndarray  # (n_ok,) chosen type per ok node
    off_zone: list  # (n_ok,)
    off_ct: list
    off_price: np.ndarray
    # True when the LP backend's cost guard chose this partition over
    # FFD's: downstream merges of these nodes must not raise plan cost
    # (solver/backends/lp.py; the merge pass reads it via ``_cost_guard``)
    cost_guard: bool = False


@dataclass
class MergeSkeleton:
    """Recorded outcome of one merge pass over an identified record
    stream: per emitted cluster, the absorption trail (record keys in
    first-fit order) and the emitted offering."""

    clusters: list  # [(rkeys tuple, t, zone, ct, price, failed)]
    applied: int


@dataclass
class _Snapshot:
    """Previous tick's inputs + result, for whole-solve replay."""

    pods: list  # strong refs — keeps id()s stable
    rvs: list
    pools_fp: tuple
    catalog_ids: tuple  # per pool: tuple(map(id, catalog))
    catalogs: list  # strong refs backing catalog_ids
    catalog_keys: tuple  # per pool: ("gen", g) | ("fp", f)
    ds_pods: list
    ds_key: tuple
    plans: list  # cloned NodePlans (never handed out)
    errors: dict


class WarmState:
    """All cross-tick state for one cloud-provider's solves."""

    def __init__(self, provider) -> None:
        self.provider = provider  # strong ref keeps the id() key stable
        self.lock = threading.RLock()
        self.routes = LRU("route")
        self.jobs = LRU("job")
        self.merges = LRU("merge")
        # per-cluster emitted offering, keyed by the cluster's absorption
        # trail (a content address: trail ⇒ folded cluster ⇒ emit choice)
        # — valid even when the surrounding record stream changed
        self.emits = LRU("emit")
        # per-record packed screen rows for the vector merge bucket
        self.screen_rows = LRU("mergerow")
        self.seed_lru = LRU("seeds")
        self.seed_generation: Optional[int] = None
        self.intersects: Dict[tuple, bool] = {}
        self.snapshot: Optional[_Snapshot] = None

    # -- bounded cross-solve intersects memo ----------------------------

    def intersects_cache(self) -> Dict[tuple, bool]:
        if len(self.intersects) > _INTERSECTS_MAX:
            self.intersects.clear()  # content-addressed: only costs re-derivation
        return self.intersects

    # -- topology seed counts (cluster-generation scoped) ----------------

    def seeds_get(self, key: tuple, generation: Optional[int], stats: CacheStats):
        if generation is None:
            return None
        with self.lock:
            if self.seed_generation != generation:
                return None
            return self.seed_lru.get(key, stats)

    def seeds_put(self, key: tuple, generation: Optional[int], seeds, stats: CacheStats) -> None:
        if generation is None:
            return
        with self.lock:
            if self.seed_generation != generation:
                self.seed_lru.clear()
                self.seed_generation = generation
            self.seed_lru.put(key, dict(seeds), stats)

    # -- whole-solve replay ----------------------------------------------

    def record(
        self,
        solver,
        pods: list,
        state_nodes,
        daemonset_pods: list,
        result,
        ctx: Optional[tuple],
    ) -> None:
        """Store this solve for replay — only when every input the solve
        read is captured by the keys (``ctx`` is the probe's computed
        (pools_fp, catalog_ids, catalogs, catalog_keys)). Anything else
        clears the snapshot: stale replay must be impossible."""
        replayable = (
            ctx is not None
            and result.oracle_results is None
            and not result.existing_plans
            and not state_nodes
            and solver.kube_client is None
            and solver.cluster is None
        )
        if not replayable:
            with self.lock:
                self.snapshot = None
            return
        pools_fp, catalog_ids, catalogs, catalog_keys = ctx
        ds = list(daemonset_pods or ())
        rvs = getattr(solver, "_batch_rvs", None)
        snap = _Snapshot(
            pods=list(pods),
            rvs=list(rvs)
            if rvs is not None and len(rvs) == len(pods)
            else [p.metadata.resource_version for p in pods],
            pools_fp=pools_fp,
            catalog_ids=catalog_ids,
            catalogs=list(catalogs),
            catalog_keys=catalog_keys,
            ds_pods=ds,
            ds_key=tuple((id(p), p.metadata.resource_version) for p in ds),
            # live plan refs: cloning is deferred to replay (only no-op
            # ticks pay it). Post-solve consumers set presentation
            # fields (``pods``) but never mutate the stored containers.
            plans=list(result.node_plans),
            errors=dict(result.pod_errors),
        )
        with self.lock:
            self.snapshot = snap

    def try_replay(
        self,
        solver,
        pods: list,
        rvs: list,
        state_nodes,
        daemonset_pods: list,
        ctx: tuple,
        stats: CacheStats,
    ):
        """Return a re-materialized SolverResult when this tick's inputs
        are provably identical to the recorded tick's, else None.
        ``rvs`` is the batch's resource_version list (read once by the
        memo walk); identity = same objects at same positions with
        unchanged rvs."""
        with self.lock:
            snap = self.snapshot
        if snap is None:
            stats.miss("warmstart")
            return None
        pools_fp, catalog_ids, _catalogs, catalog_keys = ctx
        ds = list(daemonset_pods or ())
        if (
            state_nodes
            or solver.kube_client is not None
            or solver.cluster is not None
            or pools_fp != snap.pools_fp
            or catalog_ids != snap.catalog_ids
            or catalog_keys != snap.catalog_keys
            or len(ds) != len(snap.ds_pods)
            or any(
                p is not q or (id(p), p.metadata.resource_version) != k
                for p, q, k in zip(ds, snap.ds_pods, snap.ds_key)
            )
            or len(pods) != len(snap.pods)
            or rvs != snap.rvs
            or any(p is not q for p, q in zip(pods, snap.pods))
        ):
            stats.miss("warmstart")
            return None
        stats.hit("warmstart")
        from .solver import SolverResult

        out = SolverResult()
        out.node_plans = [_clone_plan(p) for p in snap.plans]
        out.pod_errors = dict(snap.errors)
        return out


def _clone_plan(p):
    """Fresh NodePlan with copied containers (instance_type /
    requirements are shared immutably; post-solve consumers set fields
    like ``pods`` on their own clone, never on the stored one).

    Built via ``__new__`` + dict copy rather than the dataclass
    constructor: replay clones every stored plan per served tick, and
    large LP fleets (config-10 runs 60–90 plans/solve) made the
    keyword-arg ``__init__`` the dominant warm-path cost. Presentation
    (``pods``) and lazily-merged (``_requests``) fields reset to their
    constructor defaults — the stored plan may carry consumer-set
    values the clone must not inherit."""
    q = object.__new__(type(p))
    d = q.__dict__
    d.update(p.__dict__)
    d["pods"] = None
    d["_requests"] = None
    d["pod_indices"] = list(p.pod_indices)
    d["node_limits"] = list(p.node_limits)
    reqs = p._pod_requests
    d["_pod_requests"] = list(reqs) if reqs is not None else None
    return q


# -- per-provider state registry --------------------------------------------

_STATES: "OrderedDict[int, WarmState]" = OrderedDict()
_STATES_LOCK = threading.Lock()
_STATES_MAX = 4


def warm_state_for(solver) -> Optional[WarmState]:
    """The WarmState for this solver's cloud provider (None when the
    incremental path is disabled or there is no provider to key on).

    Tenant isolation (fleet/registry.py): the key carries the solver's
    tenant scope, so two tenants can never resolve to one WarmState even
    when they share a provider object — the seed cache's generation
    guard and the replay snapshot are identity-scoped and would alias
    across clusters otherwise. A fleet registry additionally PINS one
    WarmState per tenant solver (``warm_state_pin``), which both skips
    the global LRU and keeps a large fleet from thrashing its
    ``_STATES_MAX`` bound."""
    if not enabled():
        return None
    provider = solver.cloud_provider
    if provider is None:
        return None
    pin = getattr(solver, "warm_state_pin", None)
    if pin is not None and pin.provider is provider:
        return pin
    key = (id(provider), getattr(solver, "_tenant_scope", ()))
    with _STATES_LOCK:
        st = _STATES.get(key)
        if st is None or st.provider is not provider:
            st = WarmState(provider)
            _STATES[key] = st
        _STATES.move_to_end(key)
        while len(_STATES) > _STATES_MAX:
            _STATES.popitem(last=False)
    return st


def reset() -> None:
    """Test hook: drop every warm state."""
    with _STATES_LOCK:
        _STATES.clear()


# -- fingerprints / keys -----------------------------------------------------


def pool_fingerprint(pool) -> tuple:
    """Content identity of the pool-side compat inputs (the 'pool
    generation' of the cache key): template requirements (incl. labels
    + the nodepool label), taints, weight, and name. Any mutation of
    these changes the fingerprint and invalidates dependent rows."""
    np_ = pool.nodepool
    return (
        np_.name,
        getattr(np_.spec, "weight", None),
        pool.template_requirements.fingerprint(),
        tuple(
            sorted((t.key, t.value, t.effect) for t in np_.spec.template.taints)
        ),
    )


def pool_replay_fingerprint(np_) -> tuple:
    """Wider pool identity for whole-solve replay: everything the solve
    reads from the pool, limits included."""
    from ..scheduling.requirements import node_selector_requirements

    return (
        np_.name,
        getattr(np_.spec, "weight", None),
        node_selector_requirements(np_.spec.template.requirements).fingerprint(),
        tuple(sorted(np_.spec.template.metadata.labels.items())),
        tuple(sorted((t.key, t.value, t.effect) for t in np_.spec.template.taints)),
        tuple(sorted(np_.spec.limits.items())) if np_.spec.limits else (),
    )


def catalog_key(provider, nodepool, catalog) -> tuple:
    """Catalog invalidation witness: the provider's generation counter
    when it maintains one (bumped on any mutation), else a content
    fingerprint that catches in-place price/capacity/requirement
    mutation."""
    gen = None
    cg = getattr(provider, "catalog_generation", None)
    if callable(cg):
        gen = cg(nodepool)
    if gen is not None:
        return ("gen", gen)
    from .solver import _catalog_fingerprint

    return ("fp", _catalog_fingerprint(catalog))


def route_key(groups) -> Optional[tuple]:
    """Ordered interned-signature tuple, or None when any group lacks a
    stable id (relaxation retries build ad-hoc groups)."""
    key = tuple(g.sig_id for g in groups)
    return None if any(s is None for s in key) else key


def job_digest(reqs: np.ndarray) -> bytes:
    """Collision-safe digest of a job's sorted request matrix (the key
    must not alias two different packings: 128-bit blake2b)."""
    h = hashlib.blake2b(digest_size=16)
    h.update(reqs.tobytes())
    h.update(str(reqs.shape).encode())
    return h.digest()


def pack_engine_token(mesh) -> tuple:
    """The pack-engine configuration a job result depends on."""
    from .. import native
    from .pack import NATIVE_K_OPEN
    from .sharding import pod_shard_token

    return (
        bool(native.available()),
        int(mesh.devices.size) if mesh is not None else 0,
        int(NATIVE_K_OPEN),
        # pod-axis mega-shard config (ISSUE 11): with a mesh active, a
        # job at/past the shard threshold is chunk-packed, and (engine,
        # threshold, mesh size) decide that partition — so the chunk
        # config is key material. Its env reads happen inside the pack
        # dispatch, invisible to the cachesound read-set slice, but the
        # config-provenance rule (ISSUE 20) machine-checks that this
        # token carries pod_shard_token();
        # tests/test_sharding.py::TestShardEngineMemoKeys holds the
        # behavioral side.
        pod_shard_token(mesh),
    )
