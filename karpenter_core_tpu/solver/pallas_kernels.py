"""Pallas TPU kernel: fused signature×type requirement-compat.

The XLA path (kernels.compat_kernel) emits one (S×Vk)·(Vk×T) matmul plus
three elementwise combines PER KEY, each materializing an (S, T)
intermediate in HBM. This kernel fuses the whole key loop: per-key masks
are packed into 128-lane-aligned chunks of one wide (S, W) / (T, W)
matrix, the kernel walks the (static) key offsets doing one MXU matmul
per key, and the running AND lives in VMEM — the (S, T) result is
written to HBM exactly once. This is the "vocab-sparse mask" case
SURVEY §7 (step 4) flags as the place XLA fuses badly.

Semantics are identical to kernels.compat_kernel (asserted by
tests/test_pallas_compat.py, which runs the kernel in interpret mode on
CPU): per key, compatible ⇔ ¬(both sides constrain the key) ∨ the value
sets overlap ∨ both sides are complements (requirements.go:241-255
Intersects with the both-negative carve-out).
"""

from __future__ import annotations

import functools
import os
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..tracing import deviceplane

LANE = 128  # TPU lane width; per-key chunks are padded to this
TILE_S = 128
TILE_T = 128


def compat_row_block(T: int) -> int:
    """Signature rows per compat_pallas dispatch so the kernel's padded
    (Sp, Tp) f32 output — its only (S, T)-shaped HBM transient — stays
    under the tile budget (KARPENTER_TPU_COMPAT_TILE_MB, default 64 MB).
    At mega-shard scale (10k types) this caps one dispatch at ~1.6k
    signature rows instead of letting S grow the transient unboundedly
    (ISSUE 11: tiled compat past HBM limits)."""
    try:
        mb = float(os.environ.get("KARPENTER_TPU_COMPAT_TILE_MB", "64"))
    except ValueError:
        mb = 64.0
    rows = int(mb * 1e6 / 4.0 / max(T, 1))
    return max(TILE_S, (rows // TILE_S) * TILE_S)


def pack_masks(
    key_masks: Dict[str, np.ndarray],  # key → (N, Vk) bool
    key_has: Dict[str, np.ndarray],  # key → (N,) bool
    key_neg: Dict[str, np.ndarray],  # key → (N,) bool
    keys: Sequence[str],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Tuple[int, ...], Tuple[int, ...]]:
    """Concatenate per-key masks into a lane-aligned (N, W) f32 matrix
    plus (N, K) has/neg planes. Returns (packed, has, neg, offsets,
    widths); offsets[k]/widths[k] are key k's static lane-aligned chunk
    bounds (pad lanes are zero in both operands so they never add
    overlap)."""
    n = next(iter(key_masks.values())).shape[0] if key_masks else 0
    chunks: List[np.ndarray] = []
    offsets: List[int] = []
    widths: List[int] = []
    w = 0
    for key in keys:
        m = key_masks[key]
        vk = m.shape[1]
        pad = (-vk) % LANE if vk else LANE
        chunks.append(np.pad(m.astype(np.float32), ((0, 0), (0, pad))))
        offsets.append(w)
        widths.append(vk + pad)
        w += vk + pad
    packed = np.concatenate(chunks, axis=1) if chunks else np.zeros((n, 0), np.float32)
    has = np.stack([key_has[k] for k in keys], axis=1).astype(np.float32) if keys else np.zeros((n, 0), np.float32)
    neg = np.stack([key_neg[k] for k in keys], axis=1).astype(np.float32) if keys else np.zeros((n, 0), np.float32)
    return packed, has, neg, tuple(offsets), tuple(widths)


def _compat_tile_kernel(
    sig_ref,  # (TILE_S, W) f32
    typ_ref,  # (TILE_T, W) f32
    sh_ref,  # (TILE_S, Kp) f32
    sn_ref,  # (TILE_S, Kp) f32
    th_ref,  # (TILE_T, Kp) f32
    tn_ref,  # (TILE_T, Kp) f32
    out_ref,  # (TILE_S, TILE_T) f32
    *,
    offsets: Tuple[int, ...],
    widths: Tuple[int, ...],
):
    ok = jnp.ones((TILE_S, TILE_T), dtype=jnp.bool_)
    # static unroll over keys: one MXU matmul per key, combines on VPU,
    # accumulator never leaves VMEM
    for k, (start, width) in enumerate(zip(offsets, widths)):
        q = sig_ref[:, start : start + width]
        t = typ_ref[:, start : start + width]
        overlap = (
            jax.lax.dot_general(
                q,
                t,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            > 0.0
        )
        both_has = (sh_ref[:, k : k + 1] * th_ref[:, k : k + 1].T) > 0.0
        both_neg = (sn_ref[:, k : k + 1] * tn_ref[:, k : k + 1].T) > 0.0
        ok = ok & (~both_has | overlap | both_neg)
    out_ref[:] = ok.astype(jnp.float32)


@deviceplane.observe_jit("pallas.compat_pallas", static_names=("offsets", "widths", "interpret"))
@functools.partial(
    jax.jit, static_argnames=("offsets", "widths", "interpret")
)
def compat_pallas(
    sig_packed: jnp.ndarray,  # (S, W) f32
    typ_packed: jnp.ndarray,  # (T, W) f32
    sig_has: jnp.ndarray,  # (S, K) f32
    sig_neg: jnp.ndarray,
    typ_has: jnp.ndarray,  # (T, K) f32
    typ_neg: jnp.ndarray,
    offsets: Tuple[int, ...],
    widths: Tuple[int, ...],
    interpret: bool = False,
) -> jnp.ndarray:
    """→ (S, T) bool compat matrix, one fused pallas_call."""
    from jax.experimental import pallas as pl

    S, W = sig_packed.shape
    T = typ_packed.shape[0]
    K = sig_has.shape[1]
    # pad every axis to its tile multiple (lane/sublane alignment)
    Sp = -(-S // TILE_S) * TILE_S
    Tp = -(-T // TILE_T) * TILE_T
    Kp = -(-max(K, 1) // LANE) * LANE
    Wp = max(W, LANE)
    sig_packed = jnp.pad(sig_packed, ((0, Sp - S), (0, Wp - W)))
    typ_packed = jnp.pad(typ_packed, ((0, Tp - T), (0, Wp - W)))
    sig_has = jnp.pad(sig_has, ((0, Sp - S), (0, Kp - K)))
    sig_neg = jnp.pad(sig_neg, ((0, Sp - S), (0, Kp - K)))
    typ_has = jnp.pad(typ_has, ((0, Tp - T), (0, Kp - K)))
    typ_neg = jnp.pad(typ_neg, ((0, Tp - T), (0, Kp - K)))

    kernel = functools.partial(
        _compat_tile_kernel, offsets=offsets, widths=widths
    )
    grid = (Sp // TILE_S, Tp // TILE_T)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_S, Wp), lambda i, j: (i, 0)),
            pl.BlockSpec((TILE_T, Wp), lambda i, j: (j, 0)),
            pl.BlockSpec((TILE_S, Kp), lambda i, j: (i, 0)),
            pl.BlockSpec((TILE_S, Kp), lambda i, j: (i, 0)),
            pl.BlockSpec((TILE_T, Kp), lambda i, j: (j, 0)),
            pl.BlockSpec((TILE_T, Kp), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((TILE_S, TILE_T), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Sp, Tp), jnp.float32),
        interpret=interpret,
    )(sig_packed, typ_packed, sig_has, sig_neg, typ_has, typ_neg)
    return out[:S, :T] > 0.0


@deviceplane.observe_jit("pallas.allowed_pallas", static_names=("offsets", "widths", "interpret"))
@functools.partial(jax.jit, static_argnames=("offsets", "widths", "interpret"))
def allowed_pallas(
    sig_packed: jnp.ndarray,  # (S, W) f32
    sig_has: jnp.ndarray,  # (S, K) f32
    sig_neg: jnp.ndarray,
    valid: jnp.ndarray,  # (S,) bool
    typ_packed: jnp.ndarray,  # (T, W) f32 — device-resident catalog side
    typ_has: jnp.ndarray,
    typ_neg: jnp.ndarray,
    zone_ok: jnp.ndarray,  # (S, Z) bool
    ct_ok: jnp.ndarray,  # (S, C) bool
    avail: jnp.ndarray,  # (T, Z, C) bool — device-resident
    offsets: Tuple[int, ...],
    widths: Tuple[int, ...],
    interpret: bool = False,
) -> jnp.ndarray:
    """Large-S twin of kernels.allowed_kernel: fused pallas compat ∧
    offering in one dispatch, catalog tensors already on device."""
    from .kernels import offering_kernel

    compat = compat_pallas(
        sig_packed, typ_packed, sig_has, sig_neg, typ_has, typ_neg,
        offsets, widths, interpret=interpret,
    )
    compat = compat & valid[:, None]
    return compat & offering_kernel(zone_ok, ct_ok, avail)


def compat_via_pallas(
    sig_arrays: Dict[str, np.ndarray],
    type_masks: Dict[str, np.ndarray],
    type_has: Dict[str, np.ndarray],
    type_neg: Dict[str, np.ndarray],
    keys: Tuple[str, ...],
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in for kernels.compat_kernel taking the same host inputs.
    Callers must route keys == () to the XLA path (no work to fuse)."""
    assert keys, "compat_via_pallas requires at least one key"
    sig_masks = {k: sig_arrays[f"mask:{k}"] for k in keys}
    sig_has = {k: sig_arrays[f"has:{k}"] for k in keys}
    sig_neg = {k: sig_arrays[f"neg:{k}"] for k in keys}
    sp, sh, sn, offsets, widths = pack_masks(sig_masks, sig_has, sig_neg, keys)
    tp, th, tn, t_offsets, t_widths = pack_masks(type_masks, type_has, type_neg, keys)
    assert offsets == t_offsets and widths == t_widths, "sig/type chunk layouts must agree"
    T = tp.shape[0]
    tpj, thj, tnj = jnp.asarray(tp), jnp.asarray(th), jnp.asarray(tn)
    block = compat_row_block(T)
    S = sp.shape[0]
    rows = []
    # row-blocked over signatures: each dispatch's padded (Sp, Tp) f32
    # output stays under the tile budget; the type side uploads once
    Tp_est = -(-T // TILE_T) * TILE_T
    for s0 in range(0, max(S, 1), block):
        s1 = min(s0 + block, S)
        # the budgeted transient: this dispatch's padded (Sp, Tp) f32
        # output — reported so tile headroom vs KARPENTER_TPU_COMPAT_TILE_MB
        # is a per-solve observable (ISSUE 16)
        Sp_est = -(-max(s1 - s0, 1) // TILE_S) * TILE_S
        deviceplane.record_footprint(Sp_est * Tp_est * 4)
        rows.append(
            compat_pallas(
                jnp.asarray(sp[s0:s1]),
                tpj,
                jnp.asarray(sh[s0:s1]),
                jnp.asarray(sn[s0:s1]),
                thj,
                tnj,
                offsets,
                widths,
                interpret=interpret,
            )
        )
    ok = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=0)
    return ok & jnp.asarray(sig_arrays["valid"])[:, None]
