"""TPUScheduler: the batched scheduling pipeline with CPU-oracle
fallback.

Pipeline per solve:
  host: signature-group pods → per-(signature, pool) set algebra
  TPU:  compat kernel (S×T masks) + offering kernel + fits
  host: zone-spread splitting (balanced assignment = min-skew)
  TPU:  ffd_pack scan per (group, zone)
  host: cheapest-type/offering per packed node → NodePlans

Remaining ORACLE-ONLY terms (everything else — including cross-selector
topology spread, multi-term required pod affinity, required anti-
affinity with batch-external selectors, and topology-free host-port /
PVC-volume groups, ISSUE 12 — runs on the tensor path):
  - pod ANTI-affinity whose selector matches another BATCH group
    (inverse-anti semantics, topology.go:190-219: later placements of
    the counted group could violate an earlier group's term — needs the
    oracle's per-pod interleaving)
  - anti-affinity with preferred terms, or on keys other than
    zone/hostname
  - affinity+anti-affinity, affinity+spread, anti+spread (beyond the
    hostname-self shape), and stateful×topology combinations
  - preferred pod affinity
  - affinity terms with namespace selectors / cross-namespace lists,
    or nil affinity selectors
  - groups whose counting selectors interact with oracle-routed groups
    (either direction — the two worlds can't see each other's
    placements mid-solve)
The newly tensorized classes keep the engine-switch discipline:
``KARPENTER_TPU_CONSTRAINT_ENGINE={tensor,oracle}`` — ``oracle``
restores the pre-ISSUE-12 routing and is the identity reference the
parity suites and bench config 13 gate against. The oracle also serves
as the parity reference: ``SolverResult`` exposes node count and total
price for comparison.
"""

from __future__ import annotations

import hashlib
import logging
import math
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("karpenter.solver")

from ..apis import labels as wk
from ..apis.nodepool import NodePool, order_by_weight
from ..cloudprovider.types import CloudProvider, InstanceType
from ..kube.objects import Pod
from ..scheduling import Requirements, Taints, resources
from ..scheduling.requirements import node_selector_requirements
from .encode import (
    EncodedInstanceTypes,
    PoolEncoding,
    ResourceAxis,
    SignatureGroup,
    build_axis_from_capacities,
    build_catalog_axis,
    build_requests_matrix_ids,
    encode_instance_types,
    encode_signature_for_pool,
    extend_axis,
    extend_encoded_masks,
    finalize_signature_masks,
    group_pods,
    quantize_capacity,
    quantize_requests,
    unique_requests,
)
from .kernels import allowed_host, allowed_kernel, build_compat_inputs, zone_ct_masks
from . import devicetime, incremental
from .stablehash import feed as stable_feed, stable_hash
from ..tracing import deviceplane, tracer
from .pack import (
    assign_cheapest_types,
    batch_pack,
    node_usage_from_assignment,
    pareto_frontier,
    run_pack_existing,
)
from .vocab import Vocab


@dataclass
class _CatalogEntry:
    """Cross-solve cache entry: one catalog generation's tensorization.

    Keyed by the catalog's object identity (the strong `catalog` ref
    keeps ids stable) plus an offering fingerprint that catches in-place
    availability/price mutation. The vocab grows monotonically as pod
    batches intern new values; cached masks are re-extended in place
    (encode.extend_encoded_masks) — SURVEY §6's "persistent solver
    process, vocab interning maintained incrementally"."""

    catalog: List[InstanceType]
    fingerprint: int
    vocab: Vocab
    axis: ResourceAxis
    enc: EncodedInstanceTypes
    # device-resident packed type masks for the pallas compat path,
    # keyed by a vocab-width snapshot so vocab growth triggers repack:
    # (snapshot, (keys, tp, th, tn, offsets, widths, avail_dev))
    device_packed: Optional[tuple] = None
    # mesh-sharded catalog tensors for the multi-chip compat path,
    # keyed by (vocab snapshot, mesh size): (key, prepared)
    sharded_packed: Optional[tuple] = None
    # provider catalog generation this entry was validated against (a
    # matching generation skips the content fingerprint on lookup)
    generation: Optional[int] = None
    # cross-solve compat/route rows: (pool fingerprint, interned sig id)
    # -> incremental.SigRow — LRU-capped, lives and dies with the entry
    sig_rows: "OrderedDict[tuple, object]" = field(default_factory=OrderedDict)


def _sig_rows_put(entry: "_CatalogEntry", key: tuple, row, stats) -> None:
    """Bounded insert into an entry's compat-row cache (callers hold
    _CATALOG_LOCK — the entry is shared across solvers)."""
    entry.sig_rows[key] = row
    entry.sig_rows.move_to_end(key)
    cap = incremental.cache_cap("compat")
    while len(entry.sig_rows) > cap:
        entry.sig_rows.popitem(last=False)
        if stats is not None:
            stats.evict("compat")


_CATALOG_CACHE: "OrderedDict[tuple, _CatalogEntry]" = OrderedDict()


def _catalog_cache_max() -> int:
    """Env-tunable catalog-entry cap (long-lived operators must not
    grow host memory without bound)."""
    try:
        return max(1, int(os.environ.get("KARPENTER_TPU_CATALOG_CACHE_MAX", "8")))
    except ValueError:
        return 8
# guards the cache dict AND in-place mutation of cached entries (vocab
# interning, extend_encoded_masks, device_packed): solve() is normally
# called only by the provisioner singleton, but concurrent reconcilers
# (disruption simulations) may share catalog entries
_CATALOG_LOCK = threading.RLock()


class _DeferredHostCompat:
    """Host-compat job captured under _CATALOG_LOCK, executed at the
    solve's sync point — the lock must not be held for the matmul (a
    concurrent disruption simulation would serialize behind it)."""

    __slots__ = ("args",)

    def __init__(self, *args):
        self.args = args

    def __call__(self) -> np.ndarray:
        return allowed_host(*self.args)


def constraint_engine() -> str:
    """ISSUE 12 engine switch, read per solve (the PR-2/PR-7 pattern):
    ``tensor`` (default) routes the newly tensorized constraint classes
    — non-self required anti-affinity, multi-term required affinity,
    topology-free host-port/volume groups — through the device path;
    ``oracle`` restores the pre-ISSUE-12 routing (the identity
    reference the parity gates compare against)."""
    eng = os.environ.get("KARPENTER_TPU_CONSTRAINT_ENGINE", "tensor").strip().lower()
    return "oracle" if eng == "oracle" else "tensor"


def _group_node_limits(group: SignatureGroup) -> list:
    """Hostname-level per-node constraints a node holding this group's
    pods must keep satisfying if other pods merge onto it:
    (selector, namespace, max matching pods per node) triples from
    hostname topology spread and hostname anti-affinity. A NON-self
    hostname anti term contributes cap 0: the node must never gain a
    selector-matching pod (routing guarantees no batch group matches,
    so the limit is defense-in-depth on merges/joins)."""
    limits = []
    ns = group.exemplar.namespace
    hs = group.hostname_spread()
    if hs is not None:
        limits.append((hs.label_selector, ns, int(hs.max_skew)))
    anti_terms = group.tensor_anti_terms() or []
    for term in anti_terms:
        if term.topology_key != wk.LABEL_HOSTNAME or term.label_selector is None:
            continue
        if group._is_self_term(term):
            limits.append((term.label_selector, ns, 1))
        else:
            limits.append((term.label_selector, ns, 0))
    return limits


def _viable_zones(
    enc: EncodedInstanceTypes,
    viable: np.ndarray,
    zone_ok: np.ndarray,
    ct_ok: np.ndarray,
) -> Tuple[List[str], Dict[str, np.ndarray]]:
    """Zones the signature allows that have ≥1 viable type with an
    available allowed offering, plus each zone's viable-type mask —
    shared by the spread and affinity assignment paths."""
    zones = [z for zi, z in enumerate(enc.zones) if zone_ok[zi]]
    zone_types = {
        z: viable & enc.offering_avail[:, enc.zones.index(z), :][:, ct_ok].any(axis=1)
        for z in zones
    }
    return [z for z in zones if zone_types[z].any()], zone_types


def _cache_put(enc: "EncodedInstanceTypes", key: tuple, value: np.ndarray) -> None:
    """Bounded insert into an encoding's cross-solve cache under
    _CATALOG_LOCK (its contract covers in-place mutation of shared
    cached entries — concurrent disruption simulations)."""
    with _CATALOG_LOCK:
        if len(enc.runtime_caches) > 256:
            enc.runtime_caches.clear()
        enc.runtime_caches[key] = value


def _offering_pmin(
    enc: "EncodedInstanceTypes", zmask: np.ndarray, ct_ok: np.ndarray
) -> np.ndarray:
    """(T,) cheapest offering price per type within a (zone, capacity-
    type) mask, cached on the encoding (offering_price is already inf
    where no offering exists, so a plain min is the masked min)."""
    key = ("pmin", zmask.tobytes(), ct_ok.tobytes())
    cached = enc.runtime_caches.get(key)
    if cached is None:
        T = len(enc.instance_types)
        prices = enc.offering_price[:, zmask][:, :, ct_ok].reshape(T, -1)
        cached = prices.min(axis=1) if prices.size else np.full(T, np.inf)
        _cache_put(enc, key, cached)
    return cached


def _offering_rank(enc: "EncodedInstanceTypes") -> np.ndarray:
    """(Z, C) stable offering ordinal: lexicographic rank of the
    (zone name, capacity-type name) pair, cached on the encoding.

    Price-tie argmins must break on this STABLE offering id, never on
    array position — vocab/axis order is an encoding artifact (growth
    appends), so two content-identical catalogs observed in different
    orders would otherwise emit different offerings for equal prices
    (the PR-5 determinism discipline, applied to plan choice)."""
    key = ("offrank",)
    cached = enc.runtime_caches.get(key)
    if cached is None:
        pairs = [(z, c) for z in enc.zones for c in enc.capacity_types]
        order = sorted(range(len(pairs)), key=lambda i: pairs[i])
        rank = np.empty(len(pairs), dtype=np.int64)
        rank[order] = np.arange(len(pairs))
        cached = rank.reshape(len(enc.zones), len(enc.capacity_types))
        _cache_put(enc, key, cached)
    return cached


def _type_rank(enc: "EncodedInstanceTypes") -> np.ndarray:
    """(T,) stable type ordinal: rank of the instance type's name
    (ties on duplicate names fall back to catalog position), cached on
    the encoding — the type-axis analogue of ``_offering_rank``."""
    key = ("typerank",)
    cached = enc.runtime_caches.get(key)
    if cached is None:
        names = [it.name for it in enc.instance_types]
        order = sorted(range(len(names)), key=lambda i: (names[i], i))
        cached = np.empty(len(names), dtype=np.int64)
        cached[order] = np.arange(len(names))
        _cache_put(enc, key, cached)
    return cached


def _stable_argmin(values: np.ndarray, rank: np.ndarray) -> int:
    """Index of the minimum of ``values``; exact ties resolve to the
    smallest ``rank`` (a stable content id), not the first position."""
    lo = values.min()
    if not np.isfinite(lo):
        return int(np.argmin(values))
    tied = values == lo
    return int(np.flatnonzero(tied)[np.argmin(rank[tied])])


def _rank_order(idx: np.ndarray, rank: np.ndarray) -> np.ndarray:
    """``idx`` reordered by its elements' ``rank`` (stable): the viable
    type axis in name-rank order, so positional price-tie breaks are
    content-stable (see _type_rank)."""
    return idx[np.argsort(rank[idx], kind="stable")]


def _job_prices(meta: dict) -> np.ndarray:
    """Per viable type, the cheapest offering price admitted by the
    job's zone/capacity-type requirements (zone-pinned when set) — THE
    price model for packed nodes, shared between the finalize step
    (_job_skeleton) and the pack backends (solver/backends/ re-exports
    it as ``job_prices``) so a backend's cost reasoning cannot drift
    from what the finalize step will charge. Lives in this module so
    the cachesound read-set analysis sees the job memo's price reads
    inline (cross-module reads are invisible to its dataflow)."""
    enc = meta["enc"]
    viable_idx = meta["viable_idx"]
    zone_ok, ct_ok, zone = meta["zone_ok"], meta["ct_ok"], meta["zone"]
    if zone is not None:
        zi = enc.zones.index(zone)
        zprices = enc.offering_price[viable_idx, zi, :][:, ct_ok]
        return np.where(np.isfinite(zprices), zprices, np.inf).min(axis=1)
    op = enc.offering_price[viable_idx][:, zone_ok, :][:, :, ct_ok].reshape(
        len(viable_idx), -1
    )
    if not op.size:
        return np.full(len(viable_idx), np.inf)
    return np.where(np.isfinite(op), op, np.inf).min(axis=1)


def _requirements_fingerprint(reqs) -> tuple:
    """Canonical identity of a merged Requirements set (full algebra:
    operator polarity, values, Gt/Lt bounds) for class-merge equality.
    Cached on the Requirements object (invalidated by its mutators) —
    the catalog fingerprint recomputes this per type per solve."""
    if reqs is None:
        return ()
    return reqs.fingerprint()


def _catalog_fingerprint(catalog: List[InstanceType]) -> bytes:
    """Content fingerprint catching mutation of the fields the encoding
    depends on: requirements (by value — an id() check would alias a
    replaced object onto a freed one's recycled id and serve stale
    masks), capacity, and the full offering tuples. A process-stable
    digest (the stablehash canonical encoding), not builtin ``hash()``:
    the bench's restart-shaped cold solver and any future checkpointed
    warm state must reproduce it under a different PYTHONHASHSEED.

    Streams one blake2b walk (length-prefixed strings, stablehash
    scalar encoding for numerics, per-Requirements digests cached on
    the objects) instead of materializing the nested tuple per call:
    generation-less providers pay this on EVERY solve, and the tuple
    walk was the largest single host phase of the warm headline solve
    (r06→r07 ledger creep — encode.catalog 87→108 ms)."""
    h = hashlib.blake2b(digest_size=16)
    up = h.update
    for it in catalog:
        nb = it.name.encode()
        up(b"t%d:" % len(nb))
        up(nb)
        reqs = it.requirements
        up(reqs.fingerprint_digest() if reqs is not None else b"N")
        cap = it.capacity
        for k in sorted(cap):
            kb = k.encode()
            up(b"c%d:" % len(kb))
            up(kb)
            stable_feed(h, cap[k])
        for o in it.offerings:
            zb = o.zone.encode()
            cb = o.capacity_type.encode()
            up(b"o%d:" % len(zb))
            up(zb)
            up(b"%d:" % len(cb))
            up(cb)
            up(b"T" if o.available else b"F")
            stable_feed(h, o.price)
    return h.digest()


def _catalog_entry(
    catalog: List[InstanceType], generation: Optional[int] = None, stats=None
) -> _CatalogEntry:
    # identity key, never persisted: the entry holds strong refs (no id
    # recycling while cached) and every lookup revalidates content via
    # generation or fingerprint below
    key = tuple(map(id, catalog))  # analysis: allow-cache-determinism(id)
    if generation is not None:
        # trusted-generation fast path: the provider bumps its counter
        # on every catalog mutation, so an unchanged generation skips
        # the O(T) content fingerprint entirely
        with _CATALOG_LOCK:
            entry = _CATALOG_CACHE.get(key)
            if entry is not None and entry.generation == generation:
                _CATALOG_CACHE.move_to_end(key)
                if stats is not None:
                    stats.hit("catalog")
                return entry
    fp = _catalog_fingerprint(catalog)
    with _CATALOG_LOCK:
        entry = _CATALOG_CACHE.get(key)
        if entry is not None and entry.fingerprint == fp:
            entry.generation = generation
            _CATALOG_CACHE.move_to_end(key)
            if stats is not None:
                stats.hit("catalog")
            return entry
        if stats is not None:
            stats.miss("catalog")
        vocab = Vocab()
        axis = build_catalog_axis(catalog)
        enc = encode_instance_types(list(catalog), axis, vocab)
        entry = _CatalogEntry(list(catalog), fp, vocab, axis, enc, generation=generation)
        # generation is not key material — it is the guard every lookup
        # above revalidates (entry.generation == generation, else content
        # fingerprint), stored alongside the value
        # analysis: allow-cache-key(generation)
        _CATALOG_CACHE[key] = entry
        _CATALOG_CACHE.move_to_end(key)
        while len(_CATALOG_CACHE) > _catalog_cache_max():
            _CATALOG_CACHE.popitem(last=False)
            if stats is not None:
                stats.evict("catalog")
        return entry


# Engine policy, set from measured shootout data (BENCH_r03 engines):
#
# - pallas compat lost to plain XLA on the real chip (81.2 ms vs 65.2 ms
#   at S=512, interpret=false), so the fused pallas path is OPT-IN now:
#   lower KARPENTER_TPU_PALLAS_MIN_S to re-enable it.
# - on the tunneled TPU a compat dispatch has a ~65 ms floor
#   (transfer/dispatch dominated: same kernel is 2.6 ms on CPU/XLA),
#   while the numpy twin runs in single-digit ms at small S — so compat
#   only goes to the device when S·T ≥ COMPAT_MIN_DEVICE_WORK
#   (default 2^24 ≈ S=8192 × T=2048, where host numpy crosses ~200 ms
#   and the chip's fixed dispatch cost is finally amortized).
def _pallas_min_s() -> int:
    """The pallas routing threshold, read at call time so a warmstore-
    restored process (and the pallas-parity tests) can still flip it."""
    try:
        return int(os.environ.get("KARPENTER_TPU_PALLAS_MIN_S", str(1 << 30)))
    except ValueError:
        return 1 << 30


def _pallas_interpret_ok() -> bool:
    """Interpret-mode escape hatch for the pallas route off-TPU, read at
    call time (the parity tests drive the pallas path on CPU with it)."""
    return os.environ.get("KARPENTER_TPU_PALLAS_INTERPRET", "0") == "1"


# import-time fallback only, kept as a module attribute so tests can
# monkeypatch it: the LIVE routing threshold re-reads the env (then the
# on-chip calibration) at call time via calibrate.compat_min_device_work.
try:  # analysis: allow-knob-inventory(KARPENTER_TPU_COMPAT_MIN_WORK — monkeypatchable fallback; the live threshold re-reads the env at call time)
    COMPAT_MIN_DEVICE_WORK = int(os.environ.get("KARPENTER_TPU_COMPAT_MIN_WORK", str(1 << 24)))
except ValueError:
    COMPAT_MIN_DEVICE_WORK = 1 << 24


def _compat_threshold() -> int:
    """Live compat routing threshold (calibrate.compat_min_device_work:
    env override > on-chip calibration > fallback). The fallback reads
    the module attribute at call time so tests can monkeypatch
    COMPAT_MIN_DEVICE_WORK."""
    from .calibrate import compat_min_device_work

    return compat_min_device_work(fallback=COMPAT_MIN_DEVICE_WORK)


def _entry_device_packed(entry: _CatalogEntry):
    """Packed, device-resident type-side mask tensors for `entry`,
    re-uploaded only when the vocab grew (pinned-buffer design from
    SURVEY §6's latency-budget note)."""
    import jax
    import jax.numpy as jnp

    from .pallas_kernels import pack_masks

    enc = entry.enc
    snapshot = tuple(
        (k, entry.vocab.key_vocab(k).size) for k in sorted(enc.key_masks.keys())
    )
    if entry.device_packed is not None and entry.device_packed[0] == snapshot:
        return entry.device_packed[1]
    keys = tuple(sorted(enc.key_masks.keys()))
    tp, th, tn, offsets, widths = pack_masks(enc.key_masks, enc.key_has, enc.key_neg, keys)
    with devicetime.track():  # catalog upload is device-attributable
        data = (
            keys,
            jax.device_put(jnp.asarray(tp)),
            jax.device_put(jnp.asarray(th)),
            jax.device_put(jnp.asarray(tn)),
            offsets,
            widths,
            jax.device_put(jnp.asarray(enc.offering_avail)),
        )
    entry.device_packed = (snapshot, data)
    return data


def _entry_sharded(entry: _CatalogEntry, mesh) -> tuple:
    """Mesh-sharded, device-resident catalog tensors for `entry` —
    re-transferred only when the vocab grew or the mesh changed (the
    same pinned-buffer pattern as _entry_device_packed)."""
    from .sharding import prepare_sharded_catalog

    enc = entry.enc
    key = (
        tuple((k, entry.vocab.key_vocab(k).size) for k in sorted(enc.key_masks.keys())),
        int(mesh.devices.size),
    )
    if entry.sharded_packed is not None and entry.sharded_packed[0] == key:
        return entry.sharded_packed[1]
    prepared = prepare_sharded_catalog(
        mesh, enc.key_masks, enc.key_has, enc.key_neg, enc.offering_avail
    )
    entry.sharded_packed = (key, prepared)
    return prepared


def existing_node_compat(groups: List["SignatureGroup"], nodes: list) -> np.ndarray:
    """(S, M) uint8 admissibility of each signature group on each
    existing node: taints tolerated + node labels satisfy the group's
    requirements (existingnode.go:64-82). Computed once per node CLASS
    (labels minus hostname + taints) — fleets have few classes, so the
    host set algebra is O(S·classes); hostname-pinned signatures resolve
    per node."""
    from ..kube.objects import OP_IN
    from ..scheduling import Requirement
    from ..scheduling.requirements import label_requirements
    from ..scheduling.requirements import pod_requirements as _pod_reqs

    S, M = len(groups), len(nodes)
    sig_reqs = [_pod_reqs(g.exemplar) for g in groups]
    hostname_sigs = {s for s, r in enumerate(sig_reqs) if wk.LABEL_HOSTNAME in r}
    compat = np.zeros((S, M), dtype=np.uint8)
    node_taints = [Taints(n.taints()) for n in nodes]
    class_cols: Dict[tuple, np.ndarray] = {}
    for m, node in enumerate(nodes):
        labels = node.labels()
        ckey = (
            tuple(sorted((k, v) for k, v in labels.items() if k != wk.LABEL_HOSTNAME)),
            tuple(sorted((t.key, t.value, t.effect) for t in node.taints())),
        )
        col = class_cols.get(ckey)
        if col is None:
            class_reqs = label_requirements(
                {k: v for k, v in labels.items() if k != wk.LABEL_HOSTNAME}
            )
            col = np.zeros(S, dtype=np.uint8)
            for s, g in enumerate(groups):
                if s in hostname_sigs:
                    continue  # resolved per node below
                col[s] = (
                    node_taints[m].tolerates(g.exemplar) is None
                    and class_reqs.compatible(sig_reqs[s], hint=False) is None
                )
            class_cols[ckey] = col
        compat[:, m] = col
    for s in hostname_sigs:
        g = groups[s]
        for m, node in enumerate(nodes):
            node_reqs = label_requirements(node.labels())
            node_reqs.add(Requirement(wk.LABEL_HOSTNAME, OP_IN, [node.hostname()]))
            compat[s, m] = (
                node_taints[m].tolerates(g.exemplar) is None
                and node_reqs.compatible(sig_reqs[s], hint=False) is None
            )
    return compat


@dataclass
class NodePlan:
    """One node the solver decided to create."""

    nodepool_name: str
    instance_type: InstanceType
    zone: str
    capacity_type: str
    price: float
    pod_indices: List[int]  # into the solve batch
    pods: Optional[List[Pod]] = None  # resolved by the provisioner for events
    # merged (template ∩ pods) requirement set for the node — stamped
    # onto the NodeClaim so the launched node carries every label the
    # member pods select on (nodeclaimtemplate.go:55)
    requirements: Optional[object] = None
    # per-node pod cap carried from the packed group (hostname spread /
    # self-anti-affinity); backfill must not append to capped plans
    max_pods_per_node: int = 2**31 - 1
    # hostname-level (selector, namespace, cap) constraints active on
    # this node — joins/backfills must keep them satisfied
    node_limits: list = field(default_factory=list)
    # this plan's pods' exact request dicts (nanos) — merged lazily off
    # the solve's critical path (only read at NodeClaim-creation time)
    _pod_requests: Optional[list] = field(default=None, repr=False)
    _requests: Optional[dict] = field(default=None, repr=False)

    @property
    def requests(self) -> Optional[dict]:
        if self._requests is None and self._pod_requests is not None:
            self._requests = resources.merge(*self._pod_requests)
        return self._requests


@dataclass
class ExistingNodePlan:
    """Pods the solver placed onto an already-existing/in-flight node —
    nominations, not NodeClaim creations (scheduler.go:241-246 tries
    existing capacity before opening claims)."""

    state_node: object  # StateNode
    pod_indices: List[int]  # into the solve batch
    pods: Optional[List[Pod]] = None  # resolved by the provisioner for events


@dataclass
class SolverResult:
    node_plans: List[NodePlan] = field(default_factory=list)
    existing_plans: List[ExistingNodePlan] = field(default_factory=list)
    pod_errors: Dict[str, str] = field(default_factory=dict)  # pod uid → error
    oracle_results: Optional[object] = None  # scheduler.Results for fallback pods

    @property
    def node_count(self) -> int:
        n = len(self.node_plans)
        if self.oracle_results is not None:
            n += len(self.oracle_results.new_node_claims)
        return n

    @property
    def total_price(self) -> float:
        return sum(p.price for p in self.node_plans)

    @property
    def pods_scheduled(self) -> int:
        n = sum(len(p.pod_indices) for p in self.node_plans)
        n += sum(len(p.pod_indices) for p in self.existing_plans)
        if self.oracle_results is not None:
            n += sum(len(c.pods) for c in self.oracle_results.new_node_claims)
            n += sum(len(e.pods) for e in self.oracle_results.existing_nodes)
        return n


class TPUScheduler:
    def __init__(
        self,
        nodepools: List[NodePool],
        cloud_provider: CloudProvider,
        kube_client=None,
        cluster=None,
        recorder=None,
        metrics=None,
        tenant=None,
    ):
        self.nodepools = order_by_weight(
            [np_ for np_ in nodepools if np_.metadata.deletion_timestamp is None]
        )
        self.cloud_provider = cloud_provider
        self.kube_client = kube_client
        self.cluster = cluster
        self.recorder = recorder
        self.metrics = metrics
        # device/host wall-time split of the most recent solve
        self.last_timings: Optional[Dict[str, float]] = None
        # serving double-buffer hook: called (no args) the moment the
        # authoritative encode phase hands off to device pack — the
        # pipeline's prewarm stage uses it to start speculatively
        # encoding the NEXT batch while this pack is in flight
        self.encode_done_listener: Optional[Callable[[], None]] = None
        # cross-group merge observability: engine, merge_ms, and the
        # screened/applied counters (reset per solve; bench.py reads
        # last_merge_stats per config)
        self._merge_stats: Dict[str, object] = {}
        self.last_merge_stats: Optional[Dict[str, object]] = None
        # incremental-solve observability: per-solve cache hit/miss/
        # eviction counts (bench `_split`, /debug/traces, and the
        # karpenter_tpu_solver_cache_* counters all read from here)
        self._cstats = incremental.CacheStats()
        self._warm: Optional[incremental.WarmState] = None
        self.last_cache_stats: Optional[dict] = None
        # pod/type-axis shard padding of the most recent solve (ISSUE
        # 11: mesh padding is never silent — solver/sharding.py stats
        # + the karpenter_tpu_shard_padding_waste gauge); None when the
        # solve never touched a mesh
        self.last_shard_stats: Optional[dict] = None
        # multi-objective report of the most recent solve (plancost
        # pareto_report, ISSUE 19); None when no new plans were emitted
        self.last_pareto: Optional[dict] = None
        # prep-time topology ledger state (rebuilt per tensor pass;
        # empty defaults keep direct sub-method calls in tests working)
        self._batch_pods: List[Pod] = []
        self._batch_uids_cache: Optional[set] = None
        self._prep_zone_ledger: List[Tuple[int, str]] = []
        self._ledger_selectors: List[tuple] = []
        self._postpass_matrix = None
        self._postpass_remaining: Optional[Dict[str, dict]] = None
        self._sim_drained: Optional[tuple] = None
        # ISSUE 12: per-solve route split (tensor/parked/oracle pod
        # counts + oracle share) — /debug/solve/stats "route" block,
        # bench `route` column, solver_route_pods counter
        self.last_route_stats: Optional[dict] = None
        # ISSUE 12 per-solve constraint caches: anti-affinity excluded
        # zones per group, resolved group volumes per group
        self._anti_zone_excl_cache: Dict[int, frozenset] = {}
        self._group_vols_cache: Dict[int, object] = {}
        # fleet tenancy (fleet/registry.py): a non-empty scope isolates
        # every identity/generation-scoped cross-solve memo this solver
        # touches — the warm state it resolves to, the topology seed
        # keys, the job memo keys. Generation counters (cluster,
        # catalog) are per-object, not global: two tenants' counters at
        # equal values must never let their cached results alias.
        self._tenant_scope: tuple = ("tenant", str(tenant)) if tenant is not None else ()
        # fleet content plane (fleet/megasolve.py): when the batched
        # fleet engine installs it, job skeletons are additionally
        # shared fleet-wide under the tenant-free CONTENT prefix of the
        # job key (see _pack_and_finalize)
        self.fleet_plane = None
        # warm-state persistence (ISSUE 13, solver/warmstore.py): the
        # most recent snapshot/restore outcome — /debug/solve/stats
        # "warmstore" block (stats.py SCHEMA=4) + bench `_split`
        self.last_warmstore_stats: Optional[dict] = None
        # device-plane observatory (ISSUE 16, tracing/deviceplane.py):
        # per-solve compile/transfer/HBM attribution — /debug/solve/stats
        # "device" block (stats.py SCHEMA=5), flight-recorder records,
        # bench `_split`; None when the plane is disabled or the solve
        # never dispatched
        self.last_device_stats: Optional[dict] = None

    # ------------------------------------------------------------------

    def solve(
        self,
        pods: List[Pod],
        state_nodes=None,
        daemonset_pods: Optional[List[Pod]] = None,
        sim_drained: Optional[tuple] = None,
    ) -> SolverResult:
        """One batched solve, span-traced end to end (tracing/ — SURVEY
        §5's tracing obligation; the reference's --enable-profiling
        pprof, operator.go:144-160). With KARPENTER_TPU_PROFILE_DIR set,
        the whole solve additionally runs under jax.profiler.trace so
        device dispatches land in an xprof-readable trace.

        ``sim_drained`` marks a disruption simulation ("what if we drain
        these nodes") and carries the sorted provider-id tuple of the
        drained candidates. It rides every cross-solve memo key the
        simulated world could shift (the topology seed cache) so a
        drained-node solve can never alias the undrained one, and it
        suppresses the whole-solve replay snapshot — a simulation must
        not evict the provisioner's recorded tick. The content caches
        (route, compat rows, job, merge, intersects) stay shared: they
        are keyed by the exact inputs of their computation, so a warm
        simulation probe reuses the live path's work by construction
        (ISSUE 7: a probe is a warm solve, not a cold pipeline)."""
        import time as _time

        profile_dir = os.environ.get("KARPENTER_TPU_PROFILE_DIR")
        t0 = _time.perf_counter()
        devicetime.reset()
        deviceplane.reset_solve()
        sink = self.metrics.solver_phase_duration if self.metrics is not None else None
        with tracer.trace_root(
            "solve", metrics_sink=sink, buffer_if="solve", is_solve=True, pods=len(pods)
        ) as tr:
            try:
                if profile_dir:
                    import jax

                    with jax.profiler.trace(profile_dir):
                        return self._solve(
                            pods, state_nodes, daemonset_pods, sim_drained
                        )
                return self._solve(pods, state_nodes, daemonset_pods, sim_drained)
            finally:
                total = _time.perf_counter() - t0
                device = devicetime.seconds()
                # the device-vs-host split per solve (VERDICT r4: "TPU-
                # native" must be measurable) — also exposed in bench
                # engines blocks. host is derived: clamp at 0 (device
                # waits accumulated on other threads can exceed this
                # thread's wall clock)
                self.last_timings = {
                    "total_ms": total * 1000.0,
                    "device_ms": device * 1000.0,
                    "host_ms": max(total - device, 0.0) * 1000.0,
                }
                if tr is not None:
                    self.last_timings["trace_id"] = tr.trace_id
                    # derived device rollup on its own trace lane,
                    # anchored at this solve's start
                    tr.add_synthetic(
                        "device_total",
                        _time.perf_counter_ns() - int(total * 1e9),
                        int(device * 1e9),
                        note="sum of device_wait spans (dispatch+transfer+blocked)",
                    )
                self.last_merge_stats = dict(self._merge_stats)
                self.last_pack_stats = dict(self._pack_backend_stats)
                if tr is not None and self.last_pack_stats.get("backend") not in (
                    None,
                    "ffd",
                ):
                    tr.args["pack_backend"] = self.last_pack_stats
                if tr is not None and getattr(self, "last_pareto", None):
                    # the per-solve multi-objective report rides the
                    # solve trace → flight recorder / /debug/traces
                    tr.args["pareto"] = self.last_pareto
                self.last_cache_stats = self._cstats.to_dict()
                if tr is not None and (self._cstats.hits or self._cstats.misses):
                    # hit rates ride on the solve trace → /debug/traces
                    tr.args["cache"] = self.last_cache_stats
                # mesh shard padding (ISSUE 11): drain this solve's
                # accumulator — per-solve stats field, trace args, and
                # the padding-waste gauge (never silent)
                from .sharding import consume_shard_stats

                shard_stats = consume_shard_stats()
                self.last_shard_stats = shard_stats or None
                if shard_stats:
                    if tr is not None:
                        tr.args["shard"] = shard_stats
                    if self.metrics is not None and hasattr(
                        self.metrics, "shard_padding_waste"
                    ):
                        for axis in ("pods", "types"):
                            waste = shard_stats.get(f"{axis}_waste")
                            if waste is not None:
                                self.metrics.shard_padding_waste.set(
                                    float(waste), axis=axis
                                )
                # device-plane drain (ISSUE 16): compile attribution,
                # transfer bytes, and HBM watermark for THIS solve —
                # per-solve stats field, trace args, and the xla-compile/
                # transfer/HBM metrics (recompiles are never silent)
                device_stats = deviceplane.consume_solve(
                    memory=devicetime.device_memory_stats()
                )
                self.last_device_stats = device_stats
                if device_stats:
                    if tr is not None:
                        tr.args["device"] = {
                            k: v
                            for k, v in device_stats.items()
                            if k != "compile_events"
                        }
                    if self.metrics is not None:
                        for ev in device_stats.get("compile_events", ()):
                            if hasattr(self.metrics, "xla_compiles"):
                                self.metrics.xla_compiles.inc(
                                    1, fn=ev["fn"], cause=ev["cause"]
                                )
                        for phase, dirs in device_stats.get(
                            "transfer_by_phase", {}
                        ).items():
                            for direction, nbytes in dirs.items():
                                if hasattr(self.metrics, "transfer_bytes"):
                                    self.metrics.transfer_bytes.inc(
                                        nbytes, direction=direction, phase=phase
                                    )
                        hbm = device_stats.get("hbm")
                        if hbm and hasattr(self.metrics, "hbm_high_water"):
                            peak = hbm.get("peak_bytes_in_use") or hbm.get(
                                "bytes_in_use"
                            )
                            if peak is not None:
                                self.metrics.hbm_high_water.set(float(peak))
                if self.metrics is not None:
                    self.metrics.solver_duration.observe(total)
                    self.metrics.solver_device_duration.observe(device)
                    for cache, n in self._cstats.hits.items():
                        self.metrics.solver_cache_hits.inc(n, cache=cache)
                    for cache, n in self._cstats.misses.items():
                        self.metrics.solver_cache_misses.inc(n, cache=cache)
                    for cache, n in self._cstats.evictions.items():
                        self.metrics.solver_cache_evictions.inc(n, cache=cache)

    def _solve(
        self,
        pods: List[Pod],
        state_nodes=None,
        daemonset_pods: Optional[List[Pod]] = None,
        sim_drained: Optional[tuple] = None,
    ) -> SolverResult:
        result = SolverResult()
        # drained-node delta of a disruption simulation (None = live
        # solve); a component of every memo key whose result the
        # simulated world could shift — see solve()
        self._sim_drained = tuple(sim_drained) if sim_drained is not None else None
        self._merge_stats = {
            "merge_ms": 0.0,
            "merge_records": 0,
            "merge_candidates_screened": 0,
            "merge_pairs_applied": 0,
        }
        # pack-backend outcome for this solve (solver/backends/): which
        # engine partitioned the jobs, LP guard wins, bound sums
        self._pack_backend_stats = {}
        # per-solve Pareto report (plancost, ISSUE 19); replayed ticks
        # emit no new plans, so they keep None
        self.last_pareto = None
        # fresh per-solve shard-padding accumulator (solver/sharding.py)
        from .sharding import reset_shard_stats

        reset_shard_stats()
        # cross-tick incremental state (solver/incremental.py): replay
        # probe first — a provably unchanged tick skips the pipeline
        # entirely; everything unprovable falls through to a full solve
        self._cstats = incremental.CacheStats()
        self._warm = ws = incremental.warm_state_for(self)
        self._replay_ctx: Optional[tuple] = None
        # cluster-generation witness for the cross-tick seed cache; the
        # lazy exclusion key covers batch pods the seed listing could
        # count (bound pods of deleting nodes / disruption simulations)
        self._cluster_gen = (
            self.cluster.generation()
            if self.cluster is not None and hasattr(self.cluster, "generation")
            else None
        )
        self._seed_excl: Optional[tuple] = None
        self._anti_zone_excl_cache = {}
        self._group_vols_cache = {}
        # PV/StorageClass zone pins must reach the tensor path's compat
        # algebra (the oracle injects them in build_scheduler): fold
        # them into volume-bearing pods' node affinity BEFORE the memo
        # read, skipping pods whose pin is already present (ISSUE 12)
        if self.kube_client is not None:
            self._inject_volume_zones(pods)
        from . import podcache

        with tracer.span("pod_memos"):
            memos, rvs = podcache.get_memos_rvs(pods)
            self._batch_rvs = rvs
        if ws is not None:
            replayed = self._try_replay(ws, pods, rvs, state_nodes, daemonset_pods)
            if replayed is not None:
                return replayed
        with tracer.span("pod_tensors"):
            self._all_requests = [m.requests for m in memos]
            self._req_ids = np.fromiter(
                (m.req_id for m in memos), dtype=np.int64, count=len(memos)
            )
            # this batch's own id→request view: immune to intern-table
            # resets
            self._req_map = {m.req_id: m.requests for m in memos}
        # spread-count seeding excludes the batch being scheduled
        # (topology.go:71-75) and is cached per constraint per solve;
        # the uid set materializes lazily — only topology-seeded paths
        # read it, and the per-pod uid walk is measurable at 50k pods
        self._batch_pods = pods
        self._batch_uids_cache: Optional[set] = None
        self._seed_cache: Dict[tuple, Dict[str, int]] = {}
        # selector-content fingerprint caches: many groups carry distinct
        # selector OBJECTS with identical content (one per signature), so
        # match results key on content, not identity
        self._match_cache: Dict[Tuple[tuple, int], bool] = {}
        # (sel_fp, id(plan)) -> (members_len, matched) — anchor rescans
        # only when a plan grew
        self._plan_match_cache: Dict[Tuple[tuple, int], Tuple[int, bool]] = {}
        # per-selector incremental committed-placement counters (cursors
        # over the append/grow-only plan lists); cleared if limit
        # enforcement ever strips plans
        self._fold_cache: Dict[tuple, dict] = {}
        # (plan-reqs fp, joiner fp, zone, ct) -> admissible type indices
        # for post-pass joins (plans share requirement sets heavily)
        self._join_types_cache: Dict[tuple, tuple] = {}
        # merge-pass pairwise Requirements.intersects memo (fingerprint
        # keyed — content-addressed, so the warm state shares one
        # bounded map across solves; same pairs recur tick after tick)
        self._intersects_cache = (
            ws.intersects_cache() if ws is not None else {}
        )
        # prep-time (pod index, zone) ledger of zone-pinned assignments:
        # later counting groups fold these so mutually-counting groups
        # see a serially-consistent order (each group counts everything
        # assigned before it, exactly like the oracle's Record stream)
        self._prep_zone_ledger: List[Tuple[int, str]] = []
        with tracer.span("group_pods"):
            groups = group_pods(pods, memos=memos)
        with tracer.span("group_routing"):
            tensor_groups, parked, oracle_pods = self._route_groups(pods, groups)

        self._committed_plans: set = set()
        if tensor_groups or parked:
            sns = list(state_nodes or ())
            with tracer.span("tensor_pass"):
                self._solve_tensor(
                    pods, tensor_groups, daemonset_pods or [], result,
                    state_nodes=sns, parked_groups=parked,
                )
            with tracer.span("relax_retry"):
                self._relax_and_retry(
                    pods, tensor_groups + parked, daemonset_pods or [], result, sns
                )
        if oracle_pods:
            # the oracle must see capacity net of tensor-path placements:
            # commit them onto the (already deep-copied) state nodes
            self._commit_existing_plans(pods, result)
            with tracer.span("oracle_fallback", pods=len(oracle_pods)):
                self._solve_oracle(oracle_pods, state_nodes, daemonset_pods, result)
        if ws is not None and self._sim_drained is None:
            # simulations never record: clearing the snapshot here would
            # evict the provisioner's replayable tick every time a
            # disruption probe runs in between (the probe reads nothing
            # the snapshot keys miss — it just must not write)
            ws.record(
                self, pods, state_nodes, daemonset_pods, result, self._replay_ctx
            )
        if result.node_plans:
            # the multi-objective report (ISSUE 19): reporting only —
            # computed AFTER the plans are final, so it can never feed
            # back into this solve's choices
            from . import plancost

            self.last_pareto = plancost.pareto_report(result.node_plans)
        return result

    @property
    def _batch_uids(self) -> set:
        """Lazy uid set of the solve batch (seed paths only)."""
        if self._batch_uids_cache is None:
            self._batch_uids_cache = {p.uid for p in self._batch_pods}
        return self._batch_uids_cache

    @_batch_uids.setter
    def _batch_uids(self, value: set) -> None:
        self._batch_uids_cache = value

    def _seed_exclusion_key(self) -> tuple:
        """Sorted uids of batch pods the seed listing could actually
        count (pods with a live binding in cluster state) — the only
        part of the batch-exclusion set that moves seed results."""
        if self._seed_excl is None:
            if self.cluster is None:
                self._seed_excl = ()
            else:
                bindings = self.cluster.bindings
                self._seed_excl = tuple(
                    sorted(
                        p.uid
                        for p in self._batch_pods
                        if (p.namespace, p.name) in bindings
                    )
                )
        return self._seed_excl

    def _try_replay(self, ws, pods, rvs, state_nodes, daemonset_pods):
        """Whole-solve replay probe: compute this tick's invalidation
        context (pool fingerprints + catalog generations/fingerprints),
        stash it for the end-of-solve record, and replay the previous
        result when every input matches. External state the keys cannot
        witness (kube client, cluster, state nodes) → no replay."""
        if state_nodes or self.kube_client is not None or self.cluster is not None:
            return None
        with tracer.span("solve.replay_probe"):
            pools_fp: List[tuple] = []
            catalogs: List[list] = []
            keys: List[tuple] = []
            for np_ in self.nodepools:
                try:
                    its = self.cloud_provider.get_instance_types(np_) or []
                except Exception:  # noqa: BLE001 — probe must never fail the solve
                    return None
                pools_fp.append(incremental.pool_replay_fingerprint(np_))
                catalogs.append(its)
                keys.append(incremental.catalog_key(self.cloud_provider, np_, its))
            ctx = (
                tuple(pools_fp),
                tuple(tuple(map(id, c)) for c in catalogs),
                catalogs,
                tuple(keys),
            )
            self._replay_ctx = ctx
            return ws.try_replay(
                self, pods, rvs, state_nodes, daemonset_pods, ctx, self._cstats
            )

    def _route_groups(
        self, pods: List[Pod], groups: List[SignatureGroup]
    ) -> Tuple[List[SignatureGroup], List[SignatureGroup], List[Pod]]:
        """Split the batch's signature groups between the tensor
        pipeline, the post-pack parked (pod-affinity) path, and the
        oracle fallback → (tensor_groups, parked, oracle_pods).

        The split is a pure function of the batch's ordered signature
        set (signatures embed every label key any selector in the batch
        can match) AND the constraint-engine switch, so it is memoized
        across solves on the interned signature-id tuple plus the
        engine token (solver/incremental.py). The env read rides the
        explicit ("ce", constraint_engine()) component, and dropping it
        is an analyzer kill: the config-provenance rule (ISSUE 20)
        requires the route key slice to witness constraint_engine();
        tests/test_constraint_tensors.py::TestRouteCacheEngineToken
        holds the behavioral side."""
        ws = self._warm
        key = incremental.route_key(groups) if ws is not None else None
        if key is not None:
            key = key + (("ce", constraint_engine()),)
            cached = ws.routes.get(key, self._cstats)
            if cached is not None:
                t_idx, p_idx, o_idx = cached
                split = (
                    [groups[i] for i in t_idx],
                    [groups[i] for i in p_idx],
                    [pods[i] for gi in o_idx for i in groups[gi].pod_indices],
                )
                self._observe_route_split(*split)
                return split
        tensor_groups, parked, oracle_groups = self._route_groups_impl(pods, groups)
        if key is not None:
            pos = {id(g): i for i, g in enumerate(groups)}
            ws.routes.put(
                key,
                (
                    tuple(pos[id(g)] for g in tensor_groups),
                    tuple(pos[id(g)] for g in parked),
                    tuple(pos[id(g)] for g in oracle_groups),
                ),
            )
        oracle_pods: List[Pod] = [
            pods[i] for g in oracle_groups for i in g.pod_indices
        ]
        self._observe_route_split(tensor_groups, parked, oracle_pods)
        return tensor_groups, parked, oracle_pods

    def _observe_route_split(self, tensor_groups, parked, oracle_pods) -> None:
        """ISSUE 12 satellite: the per-solve route split is visible and
        gateable, never silent — per-solve stats (→ /debug/solve/stats,
        bench `route` column) plus the
        karpenter_tpu_solver_route_pods{route=} counter."""
        counts = {
            "tensor": sum(len(g.pod_indices) for g in tensor_groups),
            "parked": sum(len(g.pod_indices) for g in parked),
            "oracle": len(oracle_pods),
        }
        total = sum(counts.values())
        self.last_route_stats = {
            **counts,
            "engine": constraint_engine(),
            "oracle_share": round(counts["oracle"] / total, 4) if total else 0.0,
        }
        if self.metrics is not None and hasattr(self.metrics, "solver_route_pods"):
            for route, n in counts.items():
                if n:
                    self.metrics.solver_route_pods.inc(n, route=route)

    def _route_groups_impl(
        self, pods: List[Pod], groups: List[SignatureGroup]
    ) -> Tuple[List[SignatureGroup], List[SignatureGroup], List[SignatureGroup]]:
        """The routing computation → (tensor, parked, oracle GROUPS)."""
        def exclude(pool: List[SignatureGroup], subset: List[SignatureGroup]):
            """pool minus subset, by identity (dataclass __eq__ is deep)."""
            ids = {id(g) for g in subset}
            return [g for g in pool if id(g) not in ids]

        engine = constraint_engine()
        if engine == "oracle":
            # identity reference: the pre-ISSUE-12 split (every stateful
            # group and every non-self/multi-term shape → oracle)
            relational = [
                g
                for g in groups
                if g.has_relational_legacy or g.has_stateful_node_constraints
            ]
        else:
            relational = [
                g
                for g in groups
                if g.has_relational
                or (g.has_stateful_node_constraints and not g.tensor_stateful)
            ]
        tensor_groups = exclude(groups, relational)
        # pods *selected by* a relational pod's affinity terms must schedule
        # in the same (oracle) world, or affinity can't anchor to them
        selectors = []
        for g in relational:
            a = g.exemplar.spec.affinity
            if a is None:  # stateful (port/volume) group, no affinity terms
                continue
            for terms in (
                (a.pod_affinity.required if a.pod_affinity else []),
                ([w.pod_affinity_term for w in a.pod_affinity.preferred] if a.pod_affinity else []),
                (a.pod_anti_affinity.required if a.pod_anti_affinity else []),
                ([w.pod_affinity_term for w in a.pod_anti_affinity.preferred] if a.pod_anti_affinity else []),
            ):
                for t in terms:
                    if t.label_selector is not None:
                        selectors.append(t.label_selector)
        pulled = [
            g
            for g in tensor_groups
            if any(sel.matches(g.exemplar.metadata.labels) for sel in selectors)
        ]
        tensor_groups = exclude(tensor_groups, pulled)
        oracle_groups = relational + pulled
        # zone-spread groups stay on the tensor path (seeded per-domain
        # counters + closed-form min-skew, topology_tensor.py) — EXCEPT
        # when their selector matches pods outside the group, where
        # counting needs the oracle's global view. Hostname topologies
        # with existing capacity also go oracle: their per-node counts
        # interleave with the existing-node pack in a way the batched
        # pack doesn't model.
        # cross-selector SPREAD tensorizes (r5): a non-self-selecting
        # group's counts are static (all pods take the min-count domain,
        # topologygroup.go:166-175), and self-selecting groups that also
        # count other groups see them through the prep-time zone ledger
        # (_fold_ledger) in a serially-consistent order. Only AFFINITY
        # selectors matching other groups still need the oracle's world.
        cross = []
        for g in tensor_groups:
            sels = []
            a = g.exemplar.spec.affinity
            if engine == "oracle":
                if a is not None and (g.zone_anti_isolated or g.hostname_isolated):
                    if a.pod_anti_affinity is not None:
                        sels.extend(
                            t.label_selector
                            for t in a.pod_anti_affinity.required
                            if t.label_selector is not None
                        )
            else:
                # ISSUE 12: EVERY tensor-routed anti group (self or
                # exclusion terms) whose selector matches another batch
                # group needs the oracle's interleaving — the counted
                # group's later placements could violate the term
                # (topology.go:190-219 inverse-anti semantics); with no
                # batch match the counts are static seeds, which is what
                # makes the exclusion masks sound
                sels.extend(
                    t.label_selector
                    for t in (g.tensor_anti_terms() or ())
                    if t.label_selector is not None
                )
            if sels and any(
                sel.matches(h.exemplar.metadata.labels)
                for h in groups
                if h is not g
                for sel in sels
            ):
                cross.append(g)
        tensor_groups = exclude(tensor_groups, cross)
        oracle_groups = oracle_groups + cross
        # pod-affinity groups of the tensorizable shape (single required
        # zone/hostname term) resolve POST-PACK, sequentially, against the
        # batch's committed placements — park them (r5; topologygroup.go:
        # 215-247 semantics under the ordering that places counted groups
        # first). Self-selecting single-term groups take the same path.
        # A tensor spread group whose selector matches a PARKED group's
        # labels deliberately does NOT see the parked placements: parked
        # groups resolve last, which is the valid serial order "spread
        # pods first" — their counts at placement time are exactly the
        # seeds+ledger, and later unconstrained-by-that-constraint
        # placements may unbalance them, as the reference permits.
        if engine == "oracle":
            parked = [
                g
                for g in tensor_groups
                if g.tensor_pod_affinity() is not None
                and len(g.tensor_affinity_terms() or ()) == 1
            ]
        else:
            parked = [g for g in tensor_groups if g.tensor_pod_affinity() is not None]
        tensor_groups = exclude(tensor_groups, parked)
        # hostname topologies stay tensor even with existing capacity:
        # hostname domains always see a global min of 0
        # (topologygroup.go:193-196), so the semantics reduce to a
        # per-node quota of max_skew minus the node's existing matching
        # count — handled by _pack_hostname_existing + max_per_node
        # plain groups whose labels match an oracle-routed group's spread
        # OR affinity selectors must schedule in the same (oracle) world,
        # or the oracle's topology/anchor counts would miss their
        # placements. Fixpoint: a pulled group's own selectors can pull
        # further groups.
        def counting_selectors(g: SignatureGroup) -> list:
            sels = [
                c.label_selector
                for c in g.exemplar.spec.topology_spread_constraints
                if c.label_selector is not None
            ]
            a = g.exemplar.spec.affinity
            if a is not None:
                for pa in (a.pod_affinity, a.pod_anti_affinity):
                    if pa is None:
                        continue
                    sels.extend(
                        t.label_selector
                        for t in pa.required
                        if t.label_selector is not None
                    )
                    sels.extend(
                        w.pod_affinity_term.label_selector
                        for w in pa.preferred
                        if w.pod_affinity_term.label_selector is not None
                    )
            return sels

        frontier = list(oracle_groups)
        while frontier and (tensor_groups or parked):
            frontier_sels = [s for g in frontier for s in counting_selectors(g)]
            frontier_labels = [g.exemplar.metadata.labels for g in frontier]
            moved = []
            if frontier_sels:
                # groups the oracle world counts must live in it
                moved += [
                    g
                    for g in tensor_groups + parked
                    if any(
                        s.matches(g.exemplar.metadata.labels)
                        for s in frontier_sels
                    )
                ]
            # parked groups ANCHORING on oracle pods must live there too:
            # their admissible domains depend on placements the oracle
            # makes after the tensor pass
            moved_ids = {id(m) for m in moved}
            for g in parked:
                if id(g) in moved_ids:
                    continue
                if any(
                    t.label_selector is not None and t.label_selector.matches(labels)
                    for t in g.affinity_terms()
                    for labels in frontier_labels
                ):
                    moved.append(g)
            if not moved:
                break
            tensor_groups = exclude(tensor_groups, moved)
            parked = exclude(parked, moved)
            oracle_groups = oracle_groups + moved
            frontier = moved
        return tensor_groups, parked, oracle_groups

    def _commit_existing_plans(self, pods: List[Pod], result: SolverResult) -> None:
        """Reflect tensor placements in the state-node copies (once per
        plan) so later passes — relaxation retries, the oracle — see
        capacity net of what's already promised."""
        for plan in result.existing_plans:
            if id(plan) in self._committed_plans:
                continue
            self._committed_plans.add(id(plan))
            for i in plan.pod_indices:
                plan.state_node.update_for_pod(pods[i])

    def _relax_and_retry(
        self,
        pods: List[Pod],
        groups: List[SignatureGroup],
        daemonset_pods: List[Pod],
        result: SolverResult,
        state_nodes: list,
    ) -> None:
        """Preference relaxation fixpoint for the tensor path
        (preferences.go:38-60 ladder, scheduler.go:163-169 re-queue):
        each round strips ONE soft constraint from every failed group's
        exemplar (the whole group shares the signature) and re-enters the
        pipeline with just the failed pods; stops when nothing relaxes.

        Known divergence from the oracle's requeue: retried pods see
        existing state nodes (net of committed placements) but not this
        solve's earlier NEW-node plans, so a relaxed group can open a
        node where the oracle would back-fill an in-flight claim —
        bounded to relaxed groups, which are rare in large batches."""
        if not result.pod_errors:
            return  # nothing failed — no group can need relaxation
        from ..kube.objects import EFFECT_PREFER_NO_SCHEDULE
        from ..scheduler.preferences import Preferences

        prefs = Preferences(
            any(
                t.effect == EFFECT_PREFER_NO_SCHEDULE
                for np_ in self.nodepools
                for t in np_.spec.template.taints
            )
        )
        import copy as _copy

        for _ in range(10):  # ladder depth bound (terms strip one per round)
            retry: List[SignatureGroup] = []
            for g in groups:
                failed = [i for i in g.pod_indices if pods[i].uid in result.pod_errors]
                if not failed:
                    continue
                # relax a COPY: the exemplar is the live stored Pod (the
                # kube client returns its objects), and a persisted
                # relaxation would survive into future reconciles — the
                # reference resets by re-listing fresh pods each loop
                exemplar = _copy.deepcopy(g.exemplar)
                if not prefs.relax(exemplar):
                    continue
                retry.append(
                    SignatureGroup(
                        signature=g.signature, exemplar=exemplar, pod_indices=failed
                    )
                )
            if not retry:
                return
            for g in retry:
                for i in g.pod_indices:
                    result.pod_errors.pop(pods[i].uid, None)
            # capacity promised to earlier placements must be visible
            # before the retry packs onto existing nodes again
            self._commit_existing_plans(pods, result)
            # in-flight claims first: a relaxed pod back-fills a node
            # plan already emitted this solve before opening a new one
            # (scheduler.go:163-169 re-queues through existing claims)
            retry = self._backfill_node_plans(pods, retry, daemonset_pods, result)
            if not retry:
                return
            parked_retry = [g for g in retry if g.tensor_pod_affinity() is not None]
            regular_retry = [g for g in retry if g.tensor_pod_affinity() is None]
            self._solve_tensor(
                pods, regular_retry, daemonset_pods, result,
                state_nodes=state_nodes, parked_groups=parked_retry,
            )
            groups = retry

    _BACKFILL_SCAN_CAP = 256  # plans examined per retry group

    def _backfill_node_plans(
        self,
        pods: List[Pod],
        retry: List[SignatureGroup],
        daemonset_pods: List[Pod],
        result: SolverResult,
    ) -> List[SignatureGroup]:
        """Place relaxed-retry pods onto NodePlans already emitted this
        solve when the plan's node would admit them and its pinned
        instance type still has room — the oracle's re-queued pods see
        earlier in-flight claims (scheduler.go:163-169,241-246); without
        this, a relaxed pod opens a node the oracle would back-fill.
        Returns the groups still needing a full retry pass."""
        from ..scheduling.requirements import (
            ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
            pod_requirements as _pod_reqs,
        )

        if not result.node_plans:
            return retry
        pools_by_name = {np_.name: np_ for np_ in self.nodepools}
        # per-pool daemon overhead once, not per (pod × plan)
        daemon_by_pool = {
            name: self._daemon_overhead_for(np_, daemonset_pods)
            for name, np_ in pools_by_name.items()
        }
        remaining: List[SignatureGroup] = []
        for g in retry:
            if (
                g.zone_spread() is not None
                or g.hostname_spread() is not None
                or g.hostname_isolated
                or g.tensor_pod_affinity() is not None
                or g.zone_anti_isolated
                or g.anti_exclusion_terms()
                or g.has_stateful_node_constraints
            ):
                # topology/affinity/stateful-constrained pods must go
                # through their seeded domain-assignment / masked pack
                # paths; a plain backfill append ignores domain counts,
                # per-node caps, and port/volume conflict state
                remaining.append(g)
                continue
            pod_reqs = _pod_reqs(g.exemplar)
            unplaced: List[int] = []
            for i in g.pod_indices:
                placed = False
                for plan in result.node_plans[: self._BACKFILL_SCAN_CAP]:
                    np_ = pools_by_name.get(plan.nodepool_name)
                    if np_ is None or plan.requirements is None:
                        continue
                    if plan.max_pods_per_node < 2**31 - 1 or plan.node_limits:
                        # capped/limited plans (hostname spread / anti-
                        # affinity groups) never take foreign pods: the
                        # constraint the cap models may be violated
                        continue
                    if Taints(np_.spec.template.taints).tolerates(g.exemplar):
                        continue
                    # the launched node carries the plan's merged labels
                    # plus its pinned type/zone/capacity-type
                    node_reqs = Requirements(*plan.requirements.values_list())
                    node_reqs.add(*plan.instance_type.requirements.values_list())
                    from ..kube.objects import OP_IN
                    from ..scheduling import Requirement

                    node_reqs.add(
                        Requirement(wk.LABEL_TOPOLOGY_ZONE, OP_IN, [plan.zone]),
                        Requirement(
                            wk.CAPACITY_TYPE_LABEL_KEY, OP_IN, [plan.capacity_type]
                        ),
                    )
                    if node_reqs.compatible(
                        pod_reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS, hint=False
                    ):
                        continue
                    load = resources.merge(
                        *(plan._pod_requests or ()),
                        self._all_requests[i],
                        daemon_by_pool[plan.nodepool_name],
                    )
                    if not resources.fits(load, plan.instance_type.allocatable()):
                        continue
                    plan.pod_indices.append(i)
                    if plan._pod_requests is not None:
                        plan._pod_requests.append(self._all_requests[i])
                    plan._requests = None  # recompute lazily
                    merged = Requirements(*plan.requirements.values_list())
                    merged.add(*pod_reqs.values_list())
                    plan.requirements = merged
                    placed = True
                    break
                if not placed:
                    unplaced.append(i)
            if unplaced:
                remaining.append(
                    SignatureGroup(
                        signature=g.signature, exemplar=g.exemplar, pod_indices=unplaced
                    )
                )
        return remaining

    def _daemon_overhead_for(self, nodepool, daemonset_pods: List[Pod]) -> dict:
        """Daemonset request total for a pool's nodes (matches the
        per-pool computation in _solve_tensor)."""
        from ..scheduling.requirements import node_selector_requirements
        from ..scheduling.requirements import label_requirements
        from ..scheduling.requirements import pod_requirements as _pod_reqs

        if not daemonset_pods:
            return {}
        template_reqs = node_selector_requirements(nodepool.spec.template.requirements)
        template_reqs.add(
            *label_requirements(
                {**nodepool.spec.template.metadata.labels, wk.NODEPOOL_LABEL_KEY: nodepool.name}
            ).values_list()
        )
        taints = Taints(nodepool.spec.template.taints)
        daemons = [
            p
            for p in daemonset_pods
            if taints.tolerates(p) is None
            and template_reqs.compatible(_pod_reqs(p), frozenset(wk.WELL_KNOWN_LABELS), hint=False)
            is None
        ]
        return resources.requests_for_pods(*daemons) if daemons else {}

    # ------------------------------------------------------------------

    def _solve_oracle(self, pods, state_nodes, daemonset_pods, result: SolverResult) -> None:
        from ..scheduler.builder import build_scheduler

        scheduler = build_scheduler(
            self.kube_client,
            self.cluster,
            self.nodepools,
            self.cloud_provider,
            pods,
            state_nodes=state_nodes,
            daemonset_pods=daemonset_pods,
            recorder=self.recorder,
        )
        res = scheduler.solve(pods)
        result.oracle_results = res
        for uid, err in res.pod_errors.items():
            result.pod_errors[uid] = err

    # ------------------------------------------------------------------

    def _pack_existing(
        self,
        pods: List[Pod],
        groups: List[SignatureGroup],
        daemonset_pods: List[Pod],
        state_nodes: list,
        leftover: Dict[int, List[int]],
        result: SolverResult,
    ) -> None:
        """Pack signature groups onto existing/in-flight capacity before
        opening any new node (scheduler.go:241-246; existingnode.go:64-120
        semantics: taints → node-label/requirement compat → resource fits;
        host-port/volume-bearing groups never reach this path — they
        route to the oracle at solve() group split).

        Zone-spread groups are NOT packed here: their pods get zones
        first (seeded min-skew quotas in _prepare_class_jobs) and then
        try existing nodes zone-pinned (_pack_spread_existing) against
        the free-capacity state stashed in ``self._existing_ctx``.

        Encoding: nodes become an (M, R) free-capacity matrix (available
        minus remaining daemon overhead) in the oracle's try-order
        (initialized first, then name); admissibility comes from
        existing_node_compat; the pack itself is the native/scan
        first-fit."""
        from ..scheduling.requirements import label_requirements
        from ..scheduling.requirements import pod_requirements as _pod_reqs

        nodes = sorted(state_nodes, key=lambda n: (not n.initialized(), n.name()))
        M = len(nodes)
        if M == 0:
            return
        # axis spans ALL batch requests (spread pods quantize against the
        # same axis later, zone-pinned)
        axis = extend_axis(
            build_axis_from_capacities([n.allocatable() for n in nodes]),
            unique_requests(self._req_ids, self._req_map),
        )

        # one Taints/label-requirements view per node, shared by the
        # daemon-overhead, class-column, and hostname passes below
        node_taints = [Taints(n.taints()) for n in nodes]
        node_labels = [n.labels() for n in nodes]
        node_label_reqs = [label_requirements(lbls) for lbls in node_labels]

        # free capacity: available minus REMAINING daemon overhead
        # (expected daemons that fit the node, less those already present,
        # floored at zero — existingnode.go:43-52)
        free = np.zeros((M, axis.count), dtype=np.int32)
        for m, node in enumerate(nodes):
            daemons = [
                p
                for p in daemonset_pods
                if node_taints[m].tolerates(p) is None
                and node_label_reqs[m].compatible(_pod_reqs(p), hint=False) is None
            ]
            expected = resources.requests_for_pods(*daemons) if daemons else {}
            remaining_daemon = {
                k: v
                for k, v in resources.subtract(
                    expected, node.daemonset_request_total()
                ).items()
                if v > 0
            }
            avail = resources.subtract(node.available(), remaining_daemon)
            # an overcommitted node (any negative axis) rejects every pod
            # in the oracle (resources.fits: 0 ≤ negative is false) — a
            # zero row reproduces that, since every pod requests pods≥1
            if not any(v < 0 for v in avail.values()):
                free[m] = quantize_capacity(avail, axis)

        # stash the shared free-capacity state for the zone-pinned spread
        # pack that runs later (quotas need pool/zone eligibility first)
        free = np.ascontiguousarray(free, dtype=np.int32)
        self._existing_ctx = dict(
            nodes=nodes,
            free=free,
            axis=axis,
            node_zones=np.array(
                [lbls.get(wk.LABEL_TOPOLOGY_ZONE, "") for lbls in node_labels]
            ),
            compat_rows={},
        )

        if not groups:
            return  # parked-only batch: ctx stashed for the post-pass
        # topology-constrained groups (zone spread, self-affinity, zone
        # anti-affinity) are domain-assigned before touching existing
        # capacity — exclude them from this selector-blind pack.
        # Stateful (host-port / volume) groups pack AFTER it through the
        # per-group masked path (_pack_stateful_existing): their
        # per-node conflict state is live across placements (ISSUE 12).
        pack = [
            (gi, g)
            for gi, g in enumerate(groups)
            if g.zone_spread() is None
            and g.tensor_pod_affinity() is None
            and not g.zone_anti_isolated
            and g.hostname_spread() is None
            and not g.hostname_isolated
            and not g.has_stateful_node_constraints
        ]
        stateful = [
            (gi, g)
            for gi, g in enumerate(groups)
            if g.has_stateful_node_constraints
            and g.zone_spread() is None
            and g.tensor_pod_affinity() is None
            and not g.zone_anti_isolated
            and g.hostname_spread() is None
            and not g.hostname_isolated
        ]
        if pack:
            sub_groups = [g for _, g in pack]
            # signature × node admissibility (shared with the consolidation
            # repack — disruption/tpu_repack.py); non-self anti-affinity
            # exclusion masks (zones/hosts with seeded matching pods) fold
            # in per group
            compat = existing_node_compat(sub_groups, nodes)
            for s, g in enumerate(sub_groups):
                excl = self._anti_exclusion_row(g, self._existing_ctx)
                if excl is not None:
                    compat[s] &= ~excl
            if compat.any():
                # global pack in the oracle's pod order: all pods
                # descending by (primary, memory) — queue.go:76
                pod_idx = np.array(
                    [i for g in sub_groups for i in g.pod_indices], dtype=np.int64
                )
                sig_ids = np.array(
                    [s for s, g in enumerate(sub_groups) for _ in g.pod_indices],
                    dtype=np.int32,
                )
                reqs = build_requests_matrix_ids(
                    self._req_ids[pod_idx], axis, self._req_map
                )
                order = np.lexsort((-reqs[:, 1], -reqs[:, 0]))
                pod_idx, sig_ids, reqs = pod_idx[order], sig_ids[order], reqs[order]
                assign, free_out = run_pack_existing(reqs, sig_ids, compat, free)
                self._existing_ctx["free"] = np.ascontiguousarray(
                    free_out, dtype=np.int32
                )

                by_node: Dict[int, List[int]] = {}
                for j in np.flatnonzero(assign >= 0):
                    by_node.setdefault(int(assign[j]), []).append(int(pod_idx[j]))
                if by_node:
                    assigned = {i for members in by_node.values() for i in members}
                    for gi, g in pack:
                        leftover[gi] = [
                            i for i in g.pod_indices if i not in assigned
                        ]
                    for m in sorted(by_node):
                        result.existing_plans.append(
                            ExistingNodePlan(
                                state_node=nodes[m], pod_indices=by_node[m]
                            )
                        )
        if stateful:
            with tracer.span("existing_pack.stateful", groups=len(stateful)):
                self._pack_stateful_existing(stateful, leftover, result)

    def _pack_stateful_existing(
        self,
        stateful: List[tuple],
        leftover: Dict[int, List[int]],
        result: SolverResult,
    ) -> None:
        """Pack host-port / volume groups onto existing capacity with
        their per-node conflict state enforced IN the scan (ISSUE 12):

        - host ports ride as pseudo-resource columns appended to the
          free matrix (constraint_tensors feature axes — the exact
          additive encoding of HostPort.matches), so conflicts with
          node reservations AND between this dispatch's own placements
          are both native to the first-fit kernel;
        - volume admissibility is a per-(group, node) mask over the
          union check (shared claim sets charge a node once), with
          generic-ephemeral PVCs as additive per-driver columns; groups
          run sequentially against a live usage overlay, so cross-group
          driver interactions stay exact (the oracle's one-at-a-time
          accounting, batched per group)."""
        from .constraint_tensors import (
            PortFeatures,
            eph_free_columns,
            node_reserved_ports,
            volume_admit_row,
        )
        from ..scheduling.volumes import Volumes

        ctx = self._existing_ctx
        nodes = ctx["nodes"]
        M = len(nodes)
        # live port reservations: node's own + this pass's placements
        reserved = [list(node_reserved_ports(n)) for n in nodes]
        # live volume overlay: driver→ids added by this pass, per node
        vol_overlay: Dict[int, Volumes] = {}

        for gi, g in stateful:
            idx = np.asarray(leftover.get(gi, list(g.pod_indices)), dtype=np.int64)
            if idx.size == 0:
                continue
            row = self._existing_compat_row(g, ctx).astype(bool)
            gv = self._group_volumes(g) if g.has_volumes else None
            if gv is not None:
                for m in np.flatnonzero(row):
                    vu = nodes[m].volume_usage
                    base = vu.volumes
                    over = vol_overlay.get(int(m))
                    merged = base.union(over) if over else base
                    if not volume_admit_row(gv, merged, vu.csi_limits):
                        row[m] = False
            if not row.any():
                continue
            reqs = build_requests_matrix_ids(
                self._req_ids[idx], ctx["axis"], self._req_map
            )
            order = np.lexsort((-reqs[:, 1], -reqs[:, 0]))
            idx, reqs = idx[order], reqs[order]
            ports = g.host_ports()
            feats = PortFeatures([ports]) if ports else None
            eph_drivers = sorted(gv.eph_counts) if gv is not None else []
            free = ctx["free"]
            cols = [free]
            req_cols = [reqs]
            if feats is not None and feats.count:
                free_p = feats.free_matrix([reserved[m] for m in range(M)])
                load_p = feats.load_row(ports)
                cols.append(free_p)
                req_cols.append(np.tile(load_p, (len(idx), 1)))
            if eph_drivers:
                free_v = eph_free_columns(eph_drivers, nodes, vol_overlay)
                load_v = np.array(
                    [gv.eph_counts[d] for d in eph_drivers], dtype=np.int32
                )
                cols.append(free_v)
                req_cols.append(np.tile(load_v, (len(idx), 1)))
            free_ext = np.ascontiguousarray(np.hstack(cols), dtype=np.int32)
            reqs_ext = np.ascontiguousarray(np.hstack(req_cols), dtype=np.int32)
            assign, free_out = run_pack_existing(
                reqs_ext,
                np.zeros(len(idx), dtype=np.int32),
                row[None, :].astype(np.uint8),
                free_ext,
            )
            ctx["free"] = np.ascontiguousarray(
                free_out[:, : free.shape[1]], dtype=np.int32
            )
            placed = assign >= 0
            by_node: Dict[int, List[int]] = {}
            for j in np.flatnonzero(placed):
                by_node.setdefault(int(assign[j]), []).append(int(idx[j]))
            from .constraint_tensors import ports_from_triples

            for m in sorted(by_node):
                members = by_node[m]
                if ports:
                    reserved[m].extend(
                        ports_from_triples(ports) * len(members)
                    )
                if gv is not None and not gv.empty:
                    over = vol_overlay.setdefault(m, Volumes())
                    for driver, ids in gv.shared.items():
                        for pid in ids:
                            over.add(driver, pid)
                    for driver, n_per_pod in gv.eph_counts.items():
                        for k, i in enumerate(members):
                            pod = self._batch_pods[i]
                            for e in range(n_per_pod):
                                over.add(driver, f"{pod.namespace}/{pod.name}-eph{e}")
                result.existing_plans.append(
                    ExistingNodePlan(state_node=nodes[m], pod_indices=members)
                )
            leftover[gi] = [int(i) for i in idx[~placed]]

    # ------------------------------------------------------------------

    def _build_pools(self) -> Tuple[List[PoolEncoding], List[List[InstanceType]]]:
        """Per-pool template encoding + catalog fetch, shared by the
        authoritative tensor pass and the serving pipeline's speculative
        ``encode_prewarm`` (the pool list is a pure function of the
        nodepool specs and the provider catalog)."""
        pools: List[PoolEncoding] = []
        pool_catalogs: List[List[InstanceType]] = []
        with tracer.span("encode.pool_templates"):
            for np_ in self.nodepools:
                try:
                    its = self.cloud_provider.get_instance_types(np_)
                except Exception as e:  # noqa: BLE001 — one bad pool must not stop the solve
                    log.debug(
                        "skipping nodepool %s: instance-type fetch failed: %s",
                        np_.name,
                        e,
                    )
                    continue
                if not its:
                    continue
                template_reqs = node_selector_requirements(np_.spec.template.requirements)
                from ..scheduling.requirements import label_requirements

                template_reqs.add(
                    *label_requirements(
                        {**np_.spec.template.metadata.labels, wk.NODEPOOL_LABEL_KEY: np_.name}
                    ).values_list()
                )
                pools.append(
                    PoolEncoding(np_, template_reqs, Taints(np_.spec.template.taints))
                )
                pool_catalogs.append(its)
        return pools, pool_catalogs

    # -- staged serving entry point (serving/pipeline.py) -------------------

    def encode_prewarm(
        self, pods: List[Pod], daemonset_pods: Optional[List[Pod]] = None
    ) -> dict:
        """Speculative encode stage for the serving pipeline's double
        buffer: run the host-side encode (pod memos, signature grouping,
        route split, catalog tensorization, per-(pool, signature) compat
        kernel rows) for a batch that has not been authoritatively
        scheduled yet, then discard the outputs.

        Overlap-safety invariant: this method only *warms* the
        content-addressed cross-solve caches (podcache interning, the
        catalog entries and their ``sig_rows`` under ``_CATALOG_LOCK``,
        the route LRU) whose soundness the cache-key analysis family
        proves — reuse is memoization, never approximation, so running
        it concurrently with an authoritative solve on another thread
        (and even on a stale guess of the next batch) can change
        timings, never plans. It reads no cluster state and emits
        nothing.

        Call it on a dedicated ``TPUScheduler`` instance: per-instance
        scratch state (``_cstats``, ``_req_map``, ...) is not shared
        between a prewarm and a live solve, only the module-level caches
        are. Returns the prewarm's cache-traffic stats."""
        import time as _time

        from . import podcache

        t0 = _time.perf_counter()
        self._cstats = incremental.CacheStats()
        self._warm = ws = incremental.warm_state_for(self)
        with tracer.trace_root("encode_prewarm", buffer_if="never", pods=len(pods)):
            with tracer.span("pod_memos"):
                memos, _rvs = podcache.get_memos_rvs(pods)
            self._all_requests = [m.requests for m in memos]
            self._req_ids = np.fromiter(
                (m.req_id for m in memos), dtype=np.int64, count=len(memos)
            )
            self._req_map = {m.req_id: m.requests for m in memos}
            self._batch_pods = pods
            self._batch_uids_cache = None
            self._intersects_cache = ws.intersects_cache() if ws is not None else {}
            with tracer.span("group_pods"):
                groups = group_pods(pods, memos=memos)
            with tracer.span("group_routing"):
                tensor_groups, parked, _oracle_pods = self._route_groups(pods, groups)
            encode_groups = list(tensor_groups) + list(parked)
            pools, pool_catalogs = self._build_pools()
            if encode_groups and pools:
                with tracer.span("encode"):
                    self._encode_phase(
                        encode_groups, pools, pool_catalogs, list(daemonset_pods or ())
                    )
        stats = self._cstats.to_dict()
        stats["groups"] = len(groups)
        stats["prewarm_ms"] = round((_time.perf_counter() - t0) * 1000.0, 3)
        self.last_prewarm_stats = stats
        return stats

    def prewarm_catalog(self) -> dict:
        """Speculative catalog re-tensorization for the serving
        pipeline: after a provider catalog/price event, re-encode each
        pool's catalog entry off the authoritative path (the shared
        ``_CATALOG_CACHE`` under ``_CATALOG_LOCK`` — same key, same
        guard, so the next authoritative solve hits it warm). The
        tick-shaped loop pays this on its first post-event solve; the
        pipeline's prewarm stage absorbs it into idle time."""
        import time as _time

        t0 = _time.perf_counter()
        self._cstats = incremental.CacheStats()
        cg = getattr(self.cloud_provider, "catalog_generation", None)
        # _build_pools spans (encode.pool_templates) must run INSIDE the
        # root: on the serving prewarm thread there is no enclosing
        # trace, and a span opened before the root is an orphan (the
        # tracer counts those now — the serving identity tests gate on
        # zero)
        with tracer.trace_root("prewarm_catalog", buffer_if="never"):
            pools, pool_catalogs = self._build_pools()
            # generation probes go through the cloud provider's own lock;
            # hoisted before _CATALOG_LOCK so the global catalog lock
            # never nests a foreign lock
            gens = [cg(p.nodepool) if callable(cg) else None for p in pools]
            with _CATALOG_LOCK:
                with tracer.span("encode.catalog"):
                    for gen, cat in zip(gens, pool_catalogs):
                        _catalog_entry(cat, generation=gen, stats=self._cstats)
        stats = self._cstats.to_dict()
        stats["pools"] = len(pools)
        stats["prewarm_ms"] = round((_time.perf_counter() - t0) * 1000.0, 3)
        return stats

    # -- warm-state persistence (ISSUE 13, solver/warmstore.py) --------------

    def snapshot(self, directory: Optional[str] = None) -> Optional[str]:
        """Serialize this solver's cross-solve cache planes (catalog
        entries + sig_rows, job/merge/emit skeletons, route LRU, seeds,
        intersects) to a versioned on-disk snapshot → path, or None when
        persistence is disabled/failed (never raises)."""
        from . import warmstore

        return warmstore.snapshot(self, directory=directory)

    def restore(self, path: str) -> dict:
        """Restore a snapshot into this solver's warm world with full
        generation re-anchoring (catalog fingerprints and the cluster
        witness are revalidated against the LIVE world; mismatches are
        dropped and counted, never trusted). → outcome dict, also in
        ``last_warmstore_stats``."""
        from . import warmstore

        return warmstore.restore(
            self, path, metrics=self.metrics, fleet_plane=self.fleet_plane
        )

    def _solve_tensor(
        self,
        pods: List[Pod],
        groups: List[SignatureGroup],
        daemonset_pods: List[Pod],
        result: SolverResult,
        state_nodes: Optional[list] = None,
        parked_groups: tuple = (),
    ) -> None:
        # the prep-time ledger is PER PASS: once this pass's pack commits,
        # placements live in result.node_plans and _fold_committed counts
        # them — a retry pass folding stale ledger entries would count the
        # same pods twice (and count pods whose pack failed)
        self._prep_zone_ledger = []
        # ledger only pods a CROSS-counting selector can see: a spread
        # selector matching only its own group is fully accounted by that
        # group's water-fill, and the fold is a Python scan — at headline
        # scale (50k pods, self-selecting spread) ledgering every bucketed
        # pod costs ~1 s for entries nothing ever reads
        self._ledger_selectors = []
        for g in groups:
            zc = g.zone_spread()
            if zc is None:
                continue
            sel = zc.label_selector
            if sel is None or any(
                h is not g
                and h.exemplar.namespace == g.exemplar.namespace
                and sel.matches(h.exemplar.metadata.labels)
                for h in groups
            ):
                self._ledger_selectors.append((sel, g.exemplar.namespace))
        # parked (pod-affinity) groups join the catalog/compat encode but
        # skip the round pipeline — they resolve post-pack, sequentially
        parked_from = len(groups)
        groups = list(groups) + list(parked_groups)
        # --- existing capacity first (scheduler.go:241-246) -------------
        # per-group indices still needing placement after the existing-
        # node pack; starts as every pod in the group
        self._existing_ctx: Optional[dict] = None
        leftover: Dict[int, List[int]] = {
            gi: list(groups[gi].pod_indices) for gi in range(parked_from)
        }
        if state_nodes:
            with tracer.span("existing_pack"):
                self._pack_existing(
                    pods, groups[:parked_from], daemonset_pods, state_nodes, leftover, result
                )
            if not any(leftover.values()) and not parked_groups:
                return

        # --- encode catalog per pool -----------------------------------
        pools, pool_catalogs = self._build_pools()
        if not pools:
            for gi in range(parked_from):
                for i in leftover[gi]:
                    result.pod_errors[pods[i].uid] = "no nodepool found"
            for g in groups[parked_from:]:
                for i in g.pod_indices:
                    result.pod_errors[pods[i].uid] = "no nodepool found"
            return

        with tracer.span("encode"):
            ctx = self._encode_phase(groups, pools, pool_catalogs, daemonset_pods)
        listener = self.encode_done_listener
        if listener is not None:
            try:
                listener()
            except Exception:  # noqa: BLE001 — a listener bug must not fail the solve
                log.debug("encode_done_listener failed", exc_info=True)
        with tracer.span("pack"):
            self._pack_phase(
                pods, groups, parked_from, pools, leftover, state_nodes, result, ctx
            )

    def _encode_phase(
        self,
        groups: List[SignatureGroup],
        pools: List[PoolEncoding],
        pool_catalogs: List[List[InstanceType]],
        daemonset_pods: List[Pod],
    ) -> dict:
        """Encode half of the tensor pass (split out of _solve_tensor so
        the tracer brackets it): catalog/signature tensorization, ONE
        fused compat dispatch per pool, per-pod encoding overlapped with
        the device compute, then the sync. Returns the pack phase's
        inputs."""
        # --- per-pool encoding + compat kernels -------------------------
        # backend resolution can block on a subprocess probe (broken TPU
        # plugin) — resolve it before taking the catalog lock so a slow
        # first probe can't stall concurrent solvers
        from .backend import default_backend

        with tracer.span("encode.backend_resolve"):
            backend = default_backend()
            # calibration (first call measures the chip's dispatch floor)
            # must also run before the catalog lock — it blocks on device
            # roundtrips
            compat_threshold = _compat_threshold() if backend == "tpu" else 0
            # multi-chip: shard the compat type-axis and the pack
            # group-axis over the mesh (SURVEY §5); None on single-device
            # — behavior there is untouched
            from .sharding import active_mesh

            mesh = active_mesh(backend)
        # catalog tensors come from the cross-solve cache (encode once per
        # catalog generation, extend masks as pod batches grow the vocab);
        # the lock covers every in-place mutation of shared cache entries
        # (vocab interning, mask extension, device repack, compat rows)
        ws = self._warm
        cg = getattr(self.cloud_provider, "catalog_generation", None)
        # provider generation probes take the provider's own lock —
        # hoisted so _CATALOG_LOCK never nests a foreign lock
        gens = [cg(p.nodepool) if callable(cg) else None for p in pools]
        with _CATALOG_LOCK:
            with tracer.span("encode.catalog"):
                pool_entries = []
                for gen, cat in zip(gens, pool_catalogs):
                    pool_entries.append(
                        _catalog_entry(cat, generation=gen, stats=self._cstats)
                    )
            # job-memo catalog witness (id is stable while the entry's
            # strong ref lives in _CATALOG_CACHE; fingerprint guards
            # recycled ids)
            self._enc_keys = {
                id(e.enc): (id(e), e.fingerprint) for e in pool_entries
            }
            pool_fps = [incremental.pool_fingerprint(p) for p in pools]
            self._pool_fp_by_name = {
                p.nodepool.name: fp for p, fp in zip(pools, pool_fps)
            }
            # cross-solve compat rows: per pool, split the batch into
            # cached signatures (rows replayed — the verdicts are
            # vocab-invariant) and missing ones, which run the full
            # encode + kernel restricted to the missing subset
            cached_rows: List[list] = []
            missing_per_pool: List[List[int]] = []
            with tracer.span("encode.cache_lookup"):
                for pf, e in zip(pool_fps, pool_entries):
                    rows: list = [None] * len(groups)
                    missing: List[int] = []
                    if ws is None:
                        missing = list(range(len(groups)))
                    else:
                        sr = e.sig_rows
                        hits = 0
                        for gi, g in enumerate(groups):
                            sid = g.sig_id
                            row = sr.get((pf, sid)) if sid is not None else None
                            if row is None:
                                missing.append(gi)
                            else:
                                sr.move_to_end((pf, sid))
                                rows[gi] = row
                                hits += 1
                        if hits:
                            self._cstats.hit("compat", hits)
                        if missing:
                            self._cstats.miss("compat", len(missing))
                    cached_rows.append(rows)
                    missing_per_pool.append(missing)
            with tracer.span("encode.signatures"):
                sig_compats: List[List] = []
                for pool, e, rows, missing in zip(
                    pools, pool_entries, cached_rows, missing_per_pool
                ):
                    miss_set = set(missing)
                    sig_compats.append(
                        [
                            rows[gi].compat
                            if gi not in miss_set
                            else encode_signature_for_pool(groups[gi], pool, e.vocab)
                            for gi in range(len(groups))
                        ]
                    )
            with tracer.span("encode.masks"):
                # only pools with missing rows interned new values and
                # need their masks extended/finalized — cached rows never
                # re-enter the kernel
                dirty = {
                    id(e): e
                    for e, miss in zip(pool_entries, missing_per_pool)
                    if miss
                }
                for e in dirty.values():
                    extend_encoded_masks(e.enc, e.vocab)
                for compats, e, missing in zip(
                    sig_compats, pool_entries, missing_per_pool
                ):
                    if missing:
                        finalize_signature_masks(
                            [compats[gi] for gi in missing], e.vocab
                        )
            encoded: List[EncodedInstanceTypes] = [e.enc for e in pool_entries]

            # ONE fused device dispatch per pool (compat ∧ offering) over
            # that pool's MISSING signatures only, all pools dispatched
            # before any sync so the per-pod host encoding below overlaps
            # with device compute; fully-cached pools dispatch nothing
            pending = []
            with tracer.span("encode.compat_dispatch"):
                for e, compats, missing in zip(
                    pool_entries, sig_compats, missing_per_pool
                ):
                    if not missing:
                        pending.append(None)
                        continue
                    enc = e.enc
                    sub = [compats[gi] for gi in missing]
                    sig_arrays = build_compat_inputs(sub, enc, e.vocab)
                    keys = tuple(sorted(enc.key_masks.keys()))
                    zone_ok, ct_ok = zone_ct_masks(sub, enc)
                    S_, T_ = len(sub), len(enc.instance_types)
                    if mesh is not None:
                        # multi-chip: cached catalog T-shards live on the
                        # mesh, signatures replicate, XLA all-gathers the
                        # result
                        from .sharding import allowed_sharded, record_shard_padding

                        prepared = _entry_sharded(e, mesh)
                        # the ACTIVE catalog's type padding, re-recorded
                        # per solve (the transfer-time record inside
                        # prepare_sharded_catalog only fires on cache
                        # misses — padding must never go silent on hits)
                        record_shard_padding(
                            "types",
                            int(prepared[4]),
                            int(prepared[3].shape[0]),
                            accumulate=False,
                            n_devices=int(mesh.devices.size),
                        )
                        with devicetime.track():
                            fut = allowed_sharded(
                                prepared, sig_arrays, zone_ok, ct_ok, keys
                            )
                    elif (
                        backend == "tpu"
                        and S_ * T_ < compat_threshold
                        and S_ < _pallas_min_s()
                    ):
                        # small-S regime: the tunneled chip's dispatch floor
                        # (~65 ms, BENCH_r03) dwarfs this host matmul — keep
                        # the round trip for workloads that earn it. Capture
                        # the mask arrays under the lock (extend_encoded_masks
                        # replaces entries, never mutates arrays) and defer
                        # the compute to the sync point so the shared catalog
                        # lock is not held for the matmul.
                        fut = _DeferredHostCompat(
                            sig_arrays,
                            dict(enc.key_masks),
                            dict(enc.key_has),
                            dict(enc.key_neg),
                            zone_ok,
                            ct_ok,
                            enc.offering_avail,
                            keys,
                        )
                    elif (
                        len(compats) >= _pallas_min_s()
                        and keys
                        and (backend == "tpu" or _pallas_interpret_ok())
                    ):
                        # large-S regime: fused pallas kernel against the
                        # device-resident packed catalog (sig side is the only
                        # per-solve transfer)
                        from .pallas_kernels import allowed_pallas, pack_masks

                        p_keys, tp, th, tn, offsets, widths, avail_dev = _entry_device_packed(e)
                        sp, sh, sn, s_offsets, s_widths = pack_masks(
                            {k: sig_arrays[f"mask:{k}"] for k in p_keys},
                            {k: sig_arrays[f"has:{k}"] for k in p_keys},
                            {k: sig_arrays[f"neg:{k}"] for k in p_keys},
                            p_keys,
                        )
                        assert s_offsets == offsets and s_widths == widths, (
                            "sig/type chunk layouts diverged — vocab grew between "
                            "snapshot and pack"
                        )
                        with devicetime.track():
                            fut = allowed_pallas(
                                sp,
                                sh,
                                sn,
                                sig_arrays["valid"],
                                tp,
                                th,
                                tn,
                                zone_ok,
                                ct_ok,
                                avail_dev,
                                offsets,
                                widths,
                                interpret=backend != "tpu",
                            )
                    else:
                        with devicetime.track():
                            fut = allowed_kernel(
                                {k: np.asarray(v) for k, v in sig_arrays.items()},
                                enc.key_masks,
                                enc.key_has,
                                enc.key_neg,
                                zone_ok,
                                ct_ok,
                                enc.offering_avail,
                                keys,
                            )
                    pending.append((fut, zone_ok, ct_ok, missing))

        # --- per-pod encoding (overlapped with the device dispatch) -----
        from ..scheduling.requirements import pod_requirements as _pod_reqs

        # per unique catalog: extended axis + quantized request matrix
        # (quantized once per unique request shape, gathered per pod)
        with tracer.span("encode.pod_tensorize"):
            uniq_reqs = unique_requests(self._req_ids, self._req_map)
            matrices: Dict[int, tuple] = {}
            for e in {id(e): e for e in pool_entries}.values():
                axis_ext = extend_axis(e.axis, uniq_reqs)
                matrices[id(e)] = (
                    axis_ext,
                    build_requests_matrix_ids(self._req_ids, axis_ext, self._req_map),
                )

        # daemonset overhead per pool, added to every planned node's load
        daemon_requests = {}
        with tracer.span("encode.daemon_overhead"):
            for pool, e in zip(pools, pool_entries):
                axis_ext = matrices[id(e)][0]
                daemons = [
                    p
                    for p in daemonset_pods
                    if pool.taints.tolerates(p) is None
                    and pool.template_requirements.compatible(
                        _pod_reqs(p), frozenset(wk.WELL_KNOWN_LABELS), hint=False
                    )
                    is None
                ]
                daemon_requests[pool.nodepool.name] = quantize_requests(
                    resources.requests_for_pods(*daemons) if daemons else {}, axis_ext
                )

        allowed_per_pool = []
        S = len(groups)
        with tracer.span("encode.compat_wait"):
            for pi, item in enumerate(pending):
                e = pool_entries[pi]
                enc = e.enc
                rows = cached_rows[pi]
                if item is not None:
                    fut, sub_zone, sub_ct, missing = item
                    if isinstance(fut, _DeferredHostCompat):
                        sub_allowed = fut()
                    else:
                        with devicetime.track():  # blocks on the device result
                            sub_allowed = np.asarray(fut)
                    if len(missing) == S:
                        # nothing cached for this pool: the sub arrays ARE
                        # the full arrays (the pure cold path, zero copies)
                        allowed_per_pool.append((sub_allowed, sub_zone, sub_ct))
                        if ws is not None:
                            # analysis: allow-config-provenance(KARPENTER_TPU_SHARDED — compat masks are engine-exact (the pallas/shard parity gates assert bitwise equality), so the mode only selects the compute route, never the cached content)
                            self._cache_compat_rows(
                                e, pool_fps[pi], groups, missing,
                                sig_compats[pi], sub_allowed, sub_zone, sub_ct,
                            )
                        continue
                else:
                    sub_allowed = sub_zone = sub_ct = None
                    missing = []
                allowed = np.zeros((S, len(enc.instance_types)), dtype=bool)
                zone_ok = np.zeros((S, len(enc.zones)), dtype=bool)
                ct_ok = np.zeros((S, len(enc.capacity_types)), dtype=bool)
                for gi, row in enumerate(rows):
                    if row is not None:
                        allowed[gi] = row.allowed
                        zone_ok[gi] = row.zone_ok
                        ct_ok[gi] = row.ct_ok
                for k, gi in enumerate(missing):
                    allowed[gi] = sub_allowed[k]
                    zone_ok[gi] = sub_zone[k]
                    ct_ok[gi] = sub_ct[k]
                if missing and ws is not None:
                    # the shard padding telemetry in the dispatch region
                    # (record_shard_padding's `extra` kwargs) never flows
                    # into the cached compat rows
                    # analysis: allow-cache-key(extra)
                    self._cache_compat_rows(
                        e, pool_fps[pi], groups, missing,
                        sig_compats[pi], sub_allowed, sub_zone, sub_ct,
                    )
                allowed_per_pool.append((allowed, zone_ok, ct_ok))
        return dict(
            encoded=encoded,
            sig_compats=sig_compats,
            allowed_per_pool=allowed_per_pool,
            matrices=matrices,
            pool_entries=pool_entries,
            daemon_requests=daemon_requests,
            mesh=mesh,
        )

    def _cache_compat_rows(
        self, entry, pool_fp, groups, missing, compats, allowed, zone_ok, ct_ok
    ) -> None:
        """Persist freshly computed (signature, pool) compat rows onto
        the catalog entry's LRU (under _CATALOG_LOCK — the entry is
        shared across solvers). Rows copy out of the batch arrays so the
        cache never pins a full (S, T) matrix."""
        with _CATALOG_LOCK:
            for k, gi in enumerate(missing):
                sid = groups[gi].sig_id
                if sid is None:
                    continue
                _sig_rows_put(
                    entry,
                    (pool_fp, sid),
                    incremental.SigRow(
                        compat=compats[gi],
                        allowed=np.array(allowed[k], dtype=bool),
                        zone_ok=np.array(zone_ok[k], dtype=bool),
                        ct_ok=np.array(ct_ok[k], dtype=bool),
                    ),
                    self._cstats,
                )

    def _pack_phase(
        self,
        pods: List[Pod],
        groups: List[SignatureGroup],
        parked_from: int,
        pools: List[PoolEncoding],
        leftover: Dict[int, List[int]],
        state_nodes: Optional[list],
        result: SolverResult,
        ctx: dict,
    ) -> None:
        """Pack half of the tensor pass: bounded limit-aware pack rounds
        (ONE batched device dispatch each), cross-group merge, limit
        enforcement, then the parked pod-affinity post-pass."""
        encoded: List[EncodedInstanceTypes] = ctx["encoded"]
        sig_compats = ctx["sig_compats"]
        allowed_per_pool = ctx["allowed_per_pool"]
        matrices = ctx["matrices"]
        pool_entries = ctx["pool_entries"]
        daemon_requests = ctx["daemon_requests"]
        mesh = ctx["mesh"]
        # --- pack rounds: prepare every group/zone job, ONE batched device
        # call, finalize, then enforce NodePool limits with a running
        # reduction over the emitted plans (scheduler.go:347-383). Plans
        # that no longer fit a limited pool are stripped and their pods
        # retried against the surviving pools/types next round; bounded
        # rounds guarantee termination.
        remaining = self._initial_remaining(
            pools, state_nodes or [], result.node_plans
        )
        # only _enforce_limits reads this; skip on the unlimited hot path
        gi_of = (
            {i: gi for gi, g in enumerate(groups) for i in g.pod_indices}
            if remaining
            else {}
        )
        last_chosen: Dict[int, str] = {}
        pending_idx: Dict[int, List[int]] = {
            gi: idx for gi, idx in leftover.items() if idx
        }
        max_rounds = max(len(pools) + 1, 4) if remaining else 1
        for _round in range(max_rounds):
            if not pending_idx:
                break
            with tracer.span("pack.limit_masks"):
                limit_masks = self._limit_masks(pools, encoded, remaining)
            jobs: List[tuple] = []
            metas: List[dict] = []
            # pass 1: pool choice per signature group (scheduler.go:256-283)
            infos: List[dict] = []
            with tracer.span("pack.choose_pool"):
                for gi in sorted(pending_idx):
                    info = self._choose_pool(
                        gi, groups[gi], pods, pools, encoded, sig_compats,
                        allowed_per_pool, result, pending_idx[gi], limit_masks,
                    )
                    if info is not None:
                        infos.append(info)
            # pass 2: class-merged jobs — groups with identical pool/mask
            # fingerprints pack TOGETHER, and unpinned pods ride along into
            # zone-spread buckets (the oracle mixes compatible pods onto
            # shared nodes; per-group packing alone makes strictly more
            # nodes whenever a batch must fan out across zones anyway)
            with tracer.span("pack.prepare_jobs"):
                self._prepare_class_jobs(
                    infos,
                    pods,
                    matrices,
                    pool_entries,
                    pools,
                    encoded,
                    daemon_requests,
                    result,
                    jobs,
                    metas,
                )
            records: List[dict] = []
            plans_start = len(result.node_plans)
            # pack + finalize through the cross-tick job memo: unchanged
            # jobs skip the dispatch and the finalize recompute entirely
            self._pack_and_finalize(jobs, metas, pods, result, records, mesh)
            # cross-group consolidation: merge underfull tail nodes whose
            # requirement/offering intersections still admit a shared type
            # (the oracle mixes compatible pods freely — scheduler.go:143-147's
            # alternating-A,B canary; per-group packing alone can't)
            with tracer.span("pack.merge"):
                self._merge_and_emit(records, pods, result)
            if not remaining:
                pending_idx = {}
                break
            last_chosen.update(
                {info["gi"]: pools[info["chosen"]].nodepool.name for info in infos}
            )
            with tracer.span("pack.enforce_limits"):
                pending_idx = self._enforce_limits(result, plans_start, remaining, gi_of)
        # pods still pending after the bounded rounds: limits starved them
        for gi, idx in pending_idx.items():
            pool_name = last_chosen.get(gi, pools[0].nodepool.name if pools else "")
            for i in idx:
                result.pod_errors.setdefault(
                    pods[i].uid,
                    f'all available instance types exceed limits for nodepool: "{pool_name}"',
                )
        if parked_from < len(groups):
            with tracer.span("affinity_postpass"):
                self._affinity_postpass(
                    pods,
                    groups,
                    list(range(parked_from, len(groups))),
                    pools,
                    encoded,
                    sig_compats,
                    allowed_per_pool,
                    matrices,
                    pool_entries,
                    daemon_requests,
                    result,
                    remaining,
                    mesh,
                )

    # ------------------------------------------------------------------
    # NodePool limits (scheduler.go:76-80, 287-321, 347-383)

    @staticmethod
    def _initial_remaining(
        pools: List[PoolEncoding], state_nodes: list, prior_plans: List["NodePlan"] = ()
    ) -> Dict[str, dict]:
        """Per limited pool: spec limits minus the capacity of its
        existing nodes (scheduler.go:76-80 + :287-321) AND of NodePlans
        already emitted earlier in this solve — relaxation retries
        re-enter the pipeline and must not see the limits reset, or a
        limited pool gets pushed past spec.limits (the reference
        re-checks limits against every launched claim each loop,
        scheduler.go:347-383)."""
        remaining: Dict[str, dict] = {}
        for pool in pools:
            limits = pool.nodepool.spec.limits
            if limits:
                remaining[pool.nodepool.name] = dict(limits)
        if remaining:
            for n in state_nodes:
                name = n.labels().get(wk.NODEPOOL_LABEL_KEY, "")
                if name in remaining:
                    remaining[name] = resources.subtract(remaining[name], n.capacity())
            for plan in prior_plans:
                if plan.nodepool_name in remaining:
                    remaining[plan.nodepool_name] = resources.subtract(
                        remaining[plan.nodepool_name], plan.instance_type.capacity
                    )
        return remaining

    def _limit_masks(
        self,
        pools: List[PoolEncoding],
        encoded: List[EncodedInstanceTypes],
        remaining: Dict[str, dict],
    ) -> Optional[List[Optional[np.ndarray]]]:
        """Per pool, the (T,) mask of instance types whose capacity still
        fits under the pool's remaining limits (filterByRemainingResources,
        scheduler.go:367-383); None for unlimited pools."""
        if not remaining:
            return None
        masks: List[Optional[np.ndarray]] = []
        for pool, enc in zip(pools, encoded):
            rem = remaining.get(pool.nodepool.name)
            if rem is None:
                masks.append(None)
                continue
            mask = np.ones(len(enc.instance_types), dtype=bool)
            for t, it in enumerate(enc.instance_types):
                for name, r in rem.items():
                    if it.capacity.get(name, 0) > r:
                        mask[t] = False
                        break
            masks.append(mask)
        return masks

    def _enforce_limits(
        self,
        result: SolverResult,
        plans_start: int,
        remaining: Dict[str, dict],
        gi_of: Dict[int, int],
    ) -> Dict[int, List[int]]:
        """Running reduction over this round's emitted plans in order:
        subtract each plan's pinned instance-type capacity from its
        pool's remaining limits; plans that no longer fit are stripped
        and their pods returned for the next round (the reference's
        subtractMax is pessimistic over ALL surviving type options
        because its claims launch an unknown type — our plans pin the
        type, so exact subtraction is faithful to what actually
        launches)."""
        kept: List[NodePlan] = []
        spilled: Dict[int, List[int]] = {}
        for plan in result.node_plans[plans_start:]:
            rem = remaining.get(plan.nodepool_name)
            if rem is None or getattr(plan, "_limits_accounted", False):
                kept.append(plan)
                continue
            cap = plan.instance_type.capacity
            if any(cap.get(name, 0) > r for name, r in rem.items()):
                for i in plan.pod_indices:
                    spilled.setdefault(gi_of[i], []).append(i)
                continue
            remaining[plan.nodepool_name] = resources.subtract(rem, cap)
            kept.append(plan)
        if len(kept) != len(result.node_plans) - plans_start:
            # plans were stripped: the incremental fold counters assumed
            # an append/grow-only plan list — rebuild from scratch
            self._fold_cache = {}
            self._plan_match_cache = {}
        result.node_plans[plans_start:] = kept
        return spilled

    # ------------------------------------------------------------------

    def _choose_pool(
        self,
        gi: int,
        group: SignatureGroup,
        pods: List[Pod],
        pools: List[PoolEncoding],
        encoded: List[EncodedInstanceTypes],
        sig_compats,
        allowed_per_pool,
        result: SolverResult,
        indices: List[int],
        limit_masks: Optional[List[Optional[np.ndarray]]] = None,
    ) -> Optional[dict]:
        """First pool (weight order) whose template accepts the signature
        and offers at least one viable type within its remaining limits
        (scheduler.go:256-283 + filterByRemainingResources :367).
        ``indices`` is the group's still-unplaced subset (pods already on
        existing nodes never consult nodepools)."""
        chosen = None
        chosen_viable = None
        chosen_zone_ok = None
        limit_starved: List[str] = []
        # ISSUE 12: non-self required anti-affinity on zone — fold the
        # seeded domain-exclusion mask into the pool's zone_ok/viable
        # rows BEFORE the frontier (a copy: the cached compat rows are
        # per-signature content, the exclusion is per-solve cluster
        # state). A pool whose admissible zones empty out is skipped
        # like an incompatible one (the oracle tries its next template).
        excl_zones = (
            self._anti_excluded_zones(group)
            if group.anti_exclusion_terms()
            else frozenset()
        )
        for pi, pool in enumerate(pools):
            if not sig_compats[pi][gi].compatible:
                continue
            compat_row = allowed_per_pool[pi][0][gi]
            zone_row = allowed_per_pool[pi][1][gi]
            if excl_zones:
                enc = encoded[pi]
                zmask = np.array([z in excl_zones for z in enc.zones], dtype=bool)
                if zmask.any():
                    zone_row = zone_row & ~zmask
                    # re-derive the offering leg of the allowed mask on
                    # the narrowed zones (compat leg is zone-independent)
                    compat_row = compat_row & enc.offering_avail[:, zone_row, :][
                        :, :, allowed_per_pool[pi][2][gi]
                    ].any(axis=(1, 2))
            if limit_masks is not None and limit_masks[pi] is not None:
                viable_row = compat_row & limit_masks[pi]
                if compat_row.any() and not viable_row.any():
                    limit_starved.append(pool.nodepool.name)
                    continue
            else:
                viable_row = compat_row
            if viable_row.any():
                chosen = pi
                chosen_viable = viable_row
                chosen_zone_ok = zone_row
                break
        if chosen is None:
            parts = []
            for pi, p in enumerate(pools):
                if p.nodepool.name in limit_starved:
                    parts.append(
                        f'all available instance types exceed limits for nodepool: "{p.nodepool.name}"'
                    )
                else:
                    parts.append(
                        f'incompatible with nodepool "{p.nodepool.name}", {sig_compats[pi][gi].error or "no viable instance type"}'
                    )
            err = "; ".join(parts)
            for i in indices:
                result.pod_errors[pods[i].uid] = err
            return None

        # per-pod max-pods-per-node from hostname spread / self anti-affinity
        max_per_node = np.int32(2**31 - 1)
        solo_cross_hostname = False
        hs = group.hostname_spread()
        if hs is not None:
            sel = hs.label_selector
            if sel is None or sel.matches(group.exemplar.metadata.labels):
                max_per_node = np.int32(hs.max_skew)
            else:
                # non-self-selecting hostname spread: the reference adds
                # no +1 for non-matching pods (topologygroup.go:166-175)
                # and hostname min is always 0, so fresh nodes are always
                # admissible and the group's own pods stack freely — but
                # the group must not share nodes with pods its selector
                # counts, so it packs solo on new nodes only (a strict
                # subset of the oracle's admissible placements)
                solo_cross_hostname = True
        if group.hostname_isolated:
            max_per_node = np.int32(1)

        return dict(
            group=group,
            gi=gi,
            indices=indices,
            chosen=chosen,
            viable=chosen_viable,  # (T,) bool, limit- and exclusion-filtered
            zone_ok=chosen_zone_ok,  # (Z,) — anti-exclusion narrowed
            ct_ok=allowed_per_pool[chosen][2][gi],  # (C,)
            max_per_node=max_per_node,
            solo_cross_hostname=solo_cross_hostname,
            merged=sig_compats[chosen][gi].merged,  # template ∩ pod reqs
        )

    def _prepare_class_jobs(
        self,
        infos: List[dict],
        pods: List[Pod],
        matrices: Dict[int, tuple],
        pool_entries: List["_CatalogEntry"],
        pools: List[PoolEncoding],
        encoded: List[EncodedInstanceTypes],
        daemon_requests,
        result: SolverResult,
        jobs: List[tuple],
        metas: List[dict],
    ) -> None:
        # groups are interchangeable for packing only when their FULL
        # merged requirement sets agree — the (viable, zone, ct) masks
        # alone miss requirement keys that don't project onto catalog
        # dimensions (e.g. custom node labels: team=a vs team=b yield
        # identical masks but can never share a node). Hostname-capped
        # groups stay solo (their cap is enforced per job).
        classes: Dict[tuple, List[dict]] = {}
        for info in infos:
            g_ = info["group"]
            if (
                int(info["max_per_node"]) < 2**31 - 1
                or info.get("solo_cross_hostname")
                or g_.zone_anti_isolated
            ):
                key = ("solo", id(info["group"]))
            else:
                key = (
                    info["chosen"],
                    info["viable"].tobytes(),
                    info["zone_ok"].tobytes(),
                    info["ct_ok"].tobytes(),
                    _requirements_fingerprint(info["merged"]),
                )
            classes.setdefault(key, []).append(info)

        for members in classes.values():
            chosen = members[0]["chosen"]
            pool, enc = pools[chosen], encoded[chosen]
            viable = members[0]["viable"]
            zone_ok, ct_ok = members[0]["zone_ok"], members[0]["ct_ok"]
            max_per_node = members[0]["max_per_node"]
            merged = members[0]["merged"]
            # hostname-level per-node constraints of this class's group
            # (solo classes only — shared classes carry no hostname caps):
            # the merge pass enforces them on any combined membership
            node_limits = _group_node_limits(members[0]["group"])
            daemon = daemon_requests[pool.nodepool.name]
            requests_matrix = matrices[id(pool_entries[chosen])][1]
            # host-port feature loads per pod (ISSUE 12): a class can mix
            # port-bearing and portless groups — the job's appended port
            # columns let the pack scan enforce every conflict natively
            ports_of: Optional[Dict[int, tuple]] = None
            if any(m["group"].has_stateful_node_constraints for m in members):
                ports_of = {}
                for m in members:
                    p = m["group"].host_ports()
                    if p:
                        for i in m["indices"]:
                            ports_of[int(i)] = p
                if not ports_of:
                    ports_of = None

            spread = [m for m in members if m["group"].zone_spread() is not None]
            plain = [m for m in members if m["group"].zone_spread() is None]

            def sorted_idx(groups_pods: List[int]) -> Tuple[np.ndarray, np.ndarray]:
                idx = np.asarray(groups_pods, dtype=np.int64)
                reqs = requests_matrix[idx]
                # descending by primary then memory (queue.go:76 ordering)
                order = np.lexsort((-reqs[:, 1], -reqs[:, 0]))
                return idx[order], reqs[order]

            g0 = members[0]["group"]
            if len(members) == 1 and g0.zone_anti_isolated:
                idx0, reqs0 = sorted_idx(members[0]["indices"])
                self._affinity_assign(
                    members[0], idx0, reqs0, enc, pool, daemon, pods, result,
                    jobs, metas,
                )
                continue
            if (
                len(members) == 1
                and int(max_per_node) < 2**31 - 1
                and self._existing_ctx is not None
                and g0.zone_spread() is None
            ):
                # hostname-capped group with existing capacity: fill the
                # per-node quota (max_skew minus the node's existing
                # matching count) before opening capped new nodes.
                # Groups that ALSO zone-spread skip this (their pods
                # must be zone-assigned first; they take new zone-pinned
                # nodes where max_per_node still applies).
                idx0, _ = sorted_idx(members[0]["indices"])
                left = self._pack_hostname_existing(
                    members[0], idx0, int(max_per_node), pods, result
                )
                if not left:
                    continue
                members[0] = dict(members[0], indices=left)
                spread, plain = [], [members[0]]

            if not spread:
                idx, reqs = sorted_idx([i for m in members for i in m["indices"]])
                self._prepare_job(
                    idx, reqs, enc, viable, zone_ok, ct_ok, daemon, max_per_node,
                    pool, pods, result, jobs, metas, merged=merged,
                    per_node_limits=node_limits, pod_ports=ports_of,
                )
                continue

            # zone buckets: every spread GROUP water-fills its own pods
            # (per-group min-skew, topologygroup.go:93); plain pods of
            # the class ride along round-robin — they must land
            # somewhere, and these nodes already exist
            zones, zone_types = _viable_zones(enc, viable, zone_ok, ct_ok)
            if not zones:
                for m in spread:
                    for i in m["indices"]:
                        result.pod_errors[pods[i].uid] = (
                            "no zone with viable offering for topology spread"
                        )
                if plain:
                    idx, reqs = sorted_idx([i for m in plain for i in m["indices"]])
                    self._prepare_job(
                        idx, reqs, enc, viable, zone_ok, ct_ok, daemon, max_per_node,
                        pool, pods, result, jobs, metas, merged=merged,
                        per_node_limits=node_limits, pod_ports=ports_of,
                    )
                continue

            # per-group min-skew zone assignment from seeded domain
            # counters (topology.go:125-148 Record + topologygroup.go:
            # 93-104 min-skew selection, in closed form —
            # topology_tensor.py); zone-assigned pods then try existing
            # nodes in their zone before opening new ones
            buckets: Dict[str, list] = {z: [] for z in zones}
            Z = len(zones)
            for m in spread:
                g_idx, _ = sorted_idx(m["indices"])
                self._spread_assign(
                    m, g_idx, zones, enc, pods, result, buckets
                )
            # plain pods ride along only when zone choice doesn't shrink
            # the viable set — otherwise a pod needing a type offered in
            # one zone could be round-robined into a bucket without it
            ride_along = plain and all(
                bool(np.array_equal(zone_types[z], viable)) for z in zones
            )
            if ride_along:
                p_idx, _ = sorted_idx([i for m in plain for i in m["indices"]])
                for zi, z in enumerate(zones):
                    part = p_idx[zi::Z]
                    if part.size:
                        buckets[z].append(part)
                        self._ledger_add(pods, part, z)
            elif plain:
                idx, reqs = sorted_idx([i for m in plain for i in m["indices"]])
                self._prepare_job(
                    idx, reqs, enc, viable, zone_ok, ct_ok, daemon, max_per_node,
                    pool, pods, result, jobs, metas, merged=merged,
                    per_node_limits=node_limits, pod_ports=ports_of,
                )
            for z in zones:
                if buckets[z]:
                    idx, reqs = sorted_idx(np.concatenate(buckets[z]))
                    self._prepare_job(
                        idx, reqs, enc, zone_types[z], zone_ok, ct_ok, daemon,
                        max_per_node, pool, pods, result, jobs, metas, zone=z,
                        merged=merged, per_node_limits=node_limits,
                        pod_ports=ports_of,
                    )

    # ------------------------------------------------------------------
    # tensor-path topology spread (topology_tensor.py; VERDICT r3 #2/#5)

    def _spread_seeds(self, group: SignatureGroup, constraint) -> Dict[str, int]:
        """Existing matching-pod counts per zone for one constraint,
        cached per solve (the oracle seeds identically via
        Topology._count_domains; batch pods are excluded)."""
        from ..scheduler.topology import TopologyNodeFilter
        from .encode import _selector_key
        from .topology_tensor import seed_counts_for_constraint

        key = (
            constraint.topology_key,
            _selector_key(constraint.label_selector),
            group.exemplar.namespace,
            # counting drops pods on nodes failing the exemplar's node
            # filter — groups with different nodeSelector/affinity must
            # not share counts
            TopologyNodeFilter.for_pod(group.exemplar).key(),
        )
        seeds = self._seed_cache.get(key)
        if seeds is None:
            # cross-tick reuse scoped to the cluster's generation counter
            # (state/cluster.py): any pod/node/claim event bumps it, so an
            # unchanged generation proves the kube-derived counts are too
            ws = self._warm
            gen = getattr(self, "_cluster_gen", None)
            skey = None
            if ws is not None and gen is not None:
                # the drained-node delta keeps a disruption simulation's
                # seed counts from aliasing the undrained solve's (and
                # different drain subsets from aliasing each other); the
                # tenant scope keeps one tenant's counts from aliasing
                # another's — the generation guard below is a PER-CLUSTER
                # counter, so equal generations from different tenants'
                # clusters witness nothing about each other
                skey = key + (
                    self._seed_exclusion_key(), self._sim_drained, self._tenant_scope
                )
                seeds = ws.seeds_get(skey, gen, self._cstats)
            if seeds is None:
                with tracer.span("pack.spread_seeds"):
                    seeds = seed_counts_for_constraint(
                        self.kube_client, group.exemplar, constraint, self._batch_uids
                    )
                if skey is not None:
                    # the kube-visible pod/node state the counts derive
                    # from is witnessed by the cluster-generation guard
                    # (state/cluster.py bumps on every informer event)
                    # analysis: allow-cache-key(self.kube_client)
                    ws.seeds_put(skey, gen, seeds, self._cstats)
            self._seed_cache[key] = seeds
        return seeds

    # ------------------------------------------------------------------
    # ISSUE 12: residual constraint algebra on the tensor path

    def _inject_volume_zones(self, pods: List[Pod]) -> None:
        """Tensor-path twin of build_scheduler's VolumeTopology.inject:
        PVC-pinned zone requirements join the pod's node affinity so the
        compat algebra sees them. Pods whose computed pin is already
        injected are skipped (no memo churn on steady ticks); pods with
        volumes but no pin never mutate at all."""
        from ..scheduler.volumetopology import VolumeTopology

        vt = None
        for pod in pods:
            if not pod.spec.volumes:
                continue
            if vt is None:
                vt = VolumeTopology(self.kube_client)
            reqs = []
            for volume in pod.spec.volumes:
                reqs.extend(vt._requirements_for_volume(pod, volume))
            if not reqs:
                continue
            key = tuple(sorted((r.key, r.operator, tuple(r.values)) for r in reqs))
            if pod.__dict__.get("_karp_volzone_key") == key:
                continue  # pin already injected and unchanged
            vt.inject(pod)
            pod.__dict__["_karp_volzone_key"] = key

    def _anti_seeds(self, group: SignatureGroup, term, topology_key: str) -> Dict[str, int]:
        """Seeded matching-pod counts per domain for one anti-affinity
        term (count_matching_pods_by_domain through the oracle's
        TopologyGroup — no node filter, topologygroup.go:70-76), cached
        per solve and cross-tick under the cluster-generation guard
        (the _spread_seeds discipline)."""
        from .encode import _selector_key
        from .topology_tensor import seed_counts_for_selector

        key = (
            "anti",
            topology_key,
            _selector_key(term.label_selector),
            group.exemplar.namespace,
        )
        seeds = self._seed_cache.get(key)
        if seeds is None:
            ws = self._warm
            gen = getattr(self, "_cluster_gen", None)
            skey = None
            if ws is not None and gen is not None:
                skey = key + (
                    self._seed_exclusion_key(), self._sim_drained, self._tenant_scope
                )
                seeds = ws.seeds_get(skey, gen, self._cstats)
            if seeds is None:
                seeds = seed_counts_for_selector(
                    self.kube_client, group.exemplar, topology_key,
                    term.label_selector, self._batch_uids,
                )
                if skey is not None:
                    # kube-visible pod/node state is witnessed by the
                    # cluster-generation guard (state/cluster.py)
                    # analysis: allow-cache-key(self.kube_client)
                    ws.seeds_put(skey, gen, seeds, self._cstats)
            self._seed_cache[key] = seeds
        return seeds

    def _anti_excluded_zones(self, group: SignatureGroup) -> frozenset:
        """Zones a non-self required anti-affinity term forbids: any
        zone already holding a selector-matching pod (counts are static
        — routing guarantees no batch group matches the selector, so no
        committed-placement fold is needed). Folded into the group's
        zone_ok before the viable mask / frontier (ISSUE 12)."""
        gid = id(group)
        excl = self._anti_zone_excl_cache.get(gid)
        if excl is None:
            zones: set = set()
            for term in group.anti_exclusion_terms():
                if term.topology_key != wk.LABEL_TOPOLOGY_ZONE:
                    continue
                seeds = self._anti_seeds(group, term, wk.LABEL_TOPOLOGY_ZONE)
                zones.update(z for z, n in seeds.items() if n > 0)
            excl = frozenset(zones)
            self._anti_zone_excl_cache[gid] = excl
        return excl

    def _anti_excluded_hosts(self, group: SignatureGroup) -> frozenset:
        """Hostnames a non-self required anti-affinity term forbids
        (existing nodes already holding a matching pod); fresh nodes are
        always admissible — a new node is an empty hostname domain."""
        hosts: set = set()
        for term in group.anti_exclusion_terms():
            if term.topology_key != wk.LABEL_HOSTNAME:
                continue
            seeds = self._anti_seeds(group, term, wk.LABEL_HOSTNAME)
            hosts.update(h for h, n in seeds.items() if n > 0)
        return frozenset(hosts)

    def _anti_exclusion_row(self, group: SignatureGroup, ctx: dict) -> Optional[np.ndarray]:
        """(M,) bool exclusion mask over existing nodes from the
        group's non-self anti terms (zone- and hostname-level), or None
        when the group carries none."""
        if not group.anti_exclusion_terms():
            return None
        nodes = ctx["nodes"]
        excl = np.zeros(len(nodes), dtype=bool)
        zones = self._anti_excluded_zones(group)
        if zones:
            excl |= np.isin(ctx["node_zones"], sorted(zones))
        hosts = self._anti_excluded_hosts(group)
        if hosts:
            excl |= np.array(
                [(n.hostname() in hosts or n.name() in hosts) for n in nodes]
            )
        return excl

    def _group_volumes(self, group: SignatureGroup):
        """Per-solve memo of resolve_group_volumes (the PVC → SC →
        driver chain reads the kube store; one resolution per
        signature)."""
        from .constraint_tensors import resolve_group_volumes

        gid = id(group)
        gv = self._group_vols_cache.get(gid)
        if gv is None:
            gv = resolve_group_volumes(self.kube_client, group)
            self._group_vols_cache[gid] = gv
        return gv

    @staticmethod
    def _sel_fp(sel) -> tuple:
        # cached on the selector object itself (selectors are immutable
        # once built): the hot paths call this hundreds of thousands of
        # times per solve and the id-keyed dict lookup was measurable
        fp = getattr(sel, "_solver_fp", None)
        if fp is None:
            fp = (
                tuple(sorted(sel.match_labels.items())),
                tuple(
                    (e.key, e.operator, tuple(e.values))
                    for e in sel.match_expressions
                ),
            )
            sel._solver_fp = fp
        return fp

    def _sel_matches(self, sel, i: int, pods: List[Pod]) -> bool:
        if sel is None:
            return True
        key = (self._sel_fp(sel), i)
        hit = self._match_cache.get(key)
        if hit is None:
            hit = sel.matches(pods[i].metadata.labels)
            self._match_cache[key] = hit
        return hit

    def _plan_has_match(self, plan, sel, ns: str, pods: List[Pod]) -> bool:
        """Does any plan member match (sel, ns)? Cached per selector
        content and plan; rescans only members added since the last
        check (plans only ever grow within a solve)."""
        members = plan.pod_indices
        if sel is None:
            return any(pods[i].namespace == ns for i in members)
        key = (self._sel_fp(sel), id(plan))
        seen, matched = self._plan_match_cache.get(key, (0, False))
        if matched:
            return True
        if seen < len(members):
            for i in members[seen:]:
                if pods[i].namespace == ns and self._sel_matches(sel, i, pods):
                    matched = True
                    break
            self._plan_match_cache[key] = (len(members), matched)
        return matched

    def _fold_committed(
        self,
        seeds: Dict[str, int],
        selector,
        namespace: str,
        pods: List[Pod],
        result: SolverResult,
    ) -> Dict[str, int]:
        """Per-zone counts of THIS solve's committed placements matching
        a selector, folded into the seeds — later passes (limit-spill
        rounds, relaxation retries) must see them: the oracle records
        every landing immediately (topology.go:125). Free when no plans
        exist yet (the common single-pass solve)."""
        if not (result.node_plans or result.existing_plans):
            return seeds
        # incremental: per selector-content, a cursor state counts each
        # plan member exactly once — the affinity post-pass queries this
        # hundreds of times against an ever-growing plan list
        key = (
            self._sel_fp(selector) if selector is not None else None,
            namespace,
        )
        st = self._fold_cache.get(key)
        if st is None:
            st = {"sizes": {}, "ec": 0, "counts": {}}
            self._fold_cache[key] = st
        counts = st["counts"]

        def _count(members, start, zone):
            n = 0
            for i in members[start:]:
                if pods[i].namespace == namespace and self._sel_matches(
                    selector, i, pods
                ):
                    n += 1
            if n and zone:
                counts[zone] = counts.get(zone, 0) + n

        sizes = st["sizes"]
        for plan in result.node_plans:
            pid = id(plan)
            seen = sizes.get(pid, 0)
            members = plan.pod_indices
            if len(members) > seen:  # new plan, or grown by a join
                _count(members, seen, plan.zone)
                sizes[pid] = len(members)
        eplans = result.existing_plans
        for eplan in eplans[st["ec"] :]:
            _count(
                eplan.pod_indices,
                0,
                eplan.state_node.labels().get(wk.LABEL_TOPOLOGY_ZONE),
            )
        st["ec"] = len(eplans)
        if not counts:
            return seeds
        seeds = dict(seeds)
        for z, n in counts.items():
            seeds[z] = seeds.get(z, 0) + n
        return seeds

    def _ledger_add(self, pods: List[Pod], part, zone: str) -> None:
        if not self._ledger_selectors:
            return
        for i in part.tolist():
            p = pods[int(i)]
            for sel, ns in self._ledger_selectors:
                if ns == p.namespace and self._sel_matches(sel, int(i), pods):
                    self._prep_zone_ledger.append((int(i), zone))
                    break

    def _fold_ledger(
        self,
        seeds: Dict[str, int],
        selector,
        namespace: str,
        pods: List[Pod],
    ) -> Dict[str, int]:
        """Fold this solve's prep-time zone-pinned assignments into the
        seeds — the in-batch analogue of the oracle recording each
        placement before counting the next (topology.go:125). Unpinned
        jobs (no zone until post-pack) are deliberately absent: they
        correspond to pods placed after every counting group."""
        if not self._prep_zone_ledger:
            return seeds
        seeds = dict(seeds)
        for i, z in self._prep_zone_ledger:
            if pods[i].namespace == namespace and self._sel_matches(
                selector, i, pods
            ):
                seeds[z] = seeds.get(z, 0) + 1
        return seeds

    def _existing_compat_row(self, group: SignatureGroup, ctx: dict) -> np.ndarray:
        row = ctx["compat_rows"].get(id(group))
        if row is None:
            row = existing_node_compat([group], ctx["nodes"])[0]
            excl = self._anti_exclusion_row(group, ctx)
            if excl is not None:
                row = (row.astype(bool) & ~excl).astype(row.dtype)
            ctx["compat_rows"][id(group)] = row
        return row

    def _spread_assign(
        self,
        m: dict,
        g_idx: np.ndarray,
        zones: List[str],
        enc: EncodedInstanceTypes,
        pods: List[Pod],
        result: SolverResult,
        buckets: Dict[str, list],
    ) -> None:
        """Assign one spread group's pods to zones by seeded min-skew
        quotas, route each zone's pods through existing capacity first,
        and append the remainder to the new-node buckets."""
        from ..kube.objects import SCHEDULE_ANYWAY
        from .topology_tensor import interleave_by_quota, spread_quotas

        group: SignatureGroup = m["group"]
        c = group.zone_spread()
        P = len(g_idx)
        if P == 0:
            return
        seeds = self._fold_ledger(
            self._fold_committed(
                self._spread_seeds(group, c),
                c.label_selector,
                group.exemplar.namespace,
                pods,
                result,
            ),
            c.label_selector,
            group.exemplar.namespace,
            pods,
        )
        ctx = self._existing_ctx
        merged = m["merged"]
        zone_req = (
            merged.get_req(wk.LABEL_TOPOLOGY_ZONE) if merged is not None else None
        )

        def allowed(z: str) -> bool:
            return zone_req is None or zone_req.has(z)

        # placement domains A: new-node-eligible zones, plus zones whose
        # existing nodes admit the group (a pod can land there with no
        # new claim — scheduler.go:241-246 order). Hostname-capped
        # groups can't use the existing-node first-fit (it has no
        # per-node matching-count quota), so for them existing-only
        # zones are NOT placement domains — adding them would assign
        # quotas that respill and break the zone skew.
        can_use_existing = (
            ctx is not None
            and int(m["max_per_node"]) >= 2**31 - 1
            and not m.get("solo_cross_hostname")
        )
        place = list(zones)
        existing_zones: set = set()
        if can_use_existing:
            row = self._existing_compat_row(group, ctx).astype(bool)
            for z in sorted(set(ctx["node_zones"][row].tolist())):
                if z and allowed(z):
                    existing_zones.add(z)
                    if z not in place:
                        place.append(z)
        # pod-supported domains D: the full universe filtered by the
        # merged requirements — supported-but-unplaceable domains pin the
        # global min at their seed count (topologygroup.go:177,193-212)
        universe = set(enc.zones) | set(seeds) | existing_zones
        supported = {d for d in universe if allowed(d)}
        ext = supported - set(place)
        ext_min = min((seeds.get(d, 0) for d in ext)) if ext else None
        min_domains = (
            c.min_domains if c.when_unsatisfiable != SCHEDULE_ANYWAY else None
        )
        counts = np.array([seeds.get(z, 0) for z in place], dtype=np.int64)
        sel = c.label_selector
        self_selecting = sel is None or sel.matches(group.exemplar.metadata.labels)
        if self_selecting:
            quotas, unplaced = spread_quotas(
                counts, ext_min, c.max_skew, min_domains, len(supported), P
            )
        else:
            # cross-selector spread: the group's own placements never move
            # the counts (topologygroup.go:166-175 adds the +1 only when
            # the pod matches its own selector), so the min-count domain
            # is static and EVERY pod takes it — no water-fill
            if min_domains is not None and len(supported) < min_domains:
                global_min = 0  # topologygroup.go:205-210
            else:
                global_min = min(
                    (seeds.get(d, 0) for d in supported), default=0
                )
            admissible = [
                zi
                for zi in range(len(place))
                if counts[zi] - global_min <= c.max_skew
            ]
            quotas = np.zeros(len(place), dtype=np.int64)
            if admissible:
                target = min(admissible, key=lambda zi: counts[zi])
                quotas[target] = P
                unplaced = 0
            else:
                unplaced = P
        parts = interleave_by_quota(g_idx, quotas)
        if unplaced:
            # DoNotSchedule overflow fails like the oracle's DoesNotExist
            # next-domain; ScheduleAnyway groups get the constraint
            # stripped by the relaxation ladder and retry as plain
            for i in g_idx[P - unplaced :]:
                result.pod_errors[pods[i].uid] = (
                    f"would violate max-skew for topology spread on "
                    f"{c.topology_key}"
                )
        respill: List[np.ndarray] = []
        for zi, z in enumerate(place):
            part = parts[zi]
            if part.size and can_use_existing and z in existing_zones:
                # pods landing on existing nodes become existing_plans at
                # prep — _fold_committed counts those; no ledger entry
                part = self._pack_spread_existing(part, z, group, ctx, result)
            if part.size == 0:
                continue
            if z in buckets:  # new-node-eligible zone
                buckets[z].append(part)
                self._ledger_add(pods, part, z)
            else:
                respill.append(part)
        if respill:
            # existing-only zones out of free capacity: retarget the
            # least-loaded new-node zone (bounded skew divergence — the
            # oracle would interleave these per pod)
            spill = np.concatenate(respill)
            tgt = min(
                zones,
                key=lambda z: seeds.get(z, 0)
                + sum(int(p.size) for p in buckets[z]),
            )
            buckets[tgt].append(spill)
            self._ledger_add(pods, spill, tgt)

    def _affinity_assign(
        self,
        m: dict,
        idx: np.ndarray,  # group's pod indices, descending by size
        reqs: np.ndarray,
        enc: EncodedInstanceTypes,
        pool: PoolEncoding,
        daemon: np.ndarray,
        pods: List[Pod],
        result: SolverResult,
        jobs: List[tuple],
        metas: List[dict],
    ) -> None:
        """Tensor-path self ZONE ANTI-affinity: at most one pod per zone;
        zones with a matching pod are full, extras fail (mirrors
        nextDomainAntiAffinity, topologygroup.go:249-257). Pod AFFINITY
        groups no longer pass through here — they resolve post-pack in
        _affinity_postpass."""
        from .topology_tensor import seed_counts_for_selector, water_fill

        group: SignatureGroup = m["group"]
        zone_ok, ct_ok = m["zone_ok"], m["ct_ok"]
        viable = m["viable"]
        P = len(idx)
        ctx = self._existing_ctx
        zones, zone_types = _viable_zones(enc, viable, zone_ok, ct_ok)
        a = group.exemplar.spec.affinity

        # zone anti-affinity: one pod per zone with no matching pod yet
        term = next(
            t
            for t in a.pod_anti_affinity.required
            if t.topology_key == wk.LABEL_TOPOLOGY_ZONE
        )
        seeds = self._fold_committed(
            seed_counts_for_selector(
                self.kube_client, group.exemplar, wk.LABEL_TOPOLOGY_ZONE,
                term.label_selector, self._batch_uids,
            ),
            term.label_selector,
            group.exemplar.namespace,
            pods,
            result,
        )
        counts = np.array(
            [min(seeds.get(z, 0), 1) for z in zones], dtype=np.int64
        )
        quotas, unplaced = water_fill(counts, P, ceiling=1)
        pos = 0
        for zi, z in enumerate(zones):
            if quotas[zi] <= 0:
                continue
            i = idx[pos : pos + 1]
            r = reqs[pos : pos + 1]
            pos += 1
            part = i
            if ctx is not None:
                part = self._pack_spread_existing(part, z, group, ctx, result)
            if part.size:
                self._prepare_job(
                    part, r, enc, zone_types[z], zone_ok, ct_ok, daemon,
                    np.int32(1), pool, pods, result, jobs, metas, zone=z,
                    merged=m["merged"], no_merge=True,
                )
        for i in idx[pos:]:
            result.pod_errors[pods[i].uid] = (
                "pod anti-affinity on zone: no zone without a matching pod"
            )

    # ------------------------------------------------------------------
    # post-pack pod-affinity resolution (r5: cross-selector terms
    # tensorized; VERDICT r4 next #2)

    def _topo_order_parked(
        self, groups: List[SignatureGroup], parked_idx: List[int]
    ) -> List[int]:
        """Anchor-dependency order: if any of A's affinity selectors
        matches B's labels, B resolves first (its placements are A's
        admissible domains). Kahn's algorithm; cycles fall back to input
        order — whichever cycle member goes first legitimately sees no
        in-batch anchors (the reference fails the same way under that
        pod order)."""
        sels_of = {
            gi: [
                t.label_selector
                for t in groups[gi].affinity_terms()
                if t.label_selector is not None
            ]
            for gi in parked_idx
        }
        deps: Dict[int, set] = {gi: set() for gi in parked_idx}
        for gi in parked_idx:
            sels = sels_of[gi]
            if not sels:
                continue
            for gj in parked_idx:
                if gj != gi and any(
                    sel.matches(groups[gj].exemplar.metadata.labels) for sel in sels
                ):
                    deps[gi].add(gj)
        order: List[int] = []
        placed: set = set()
        pending = list(parked_idx)
        while pending:
            ready = [gi for gi in pending if deps[gi] <= placed]
            if not ready:
                ready = [pending[0]]  # cycle: break in input order
            for gi in ready:
                order.append(gi)
                placed.add(gi)
            pending = [gi for gi in pending if gi not in placed]
        return order

    def _affinity_postpass(
        self,
        pods: List[Pod],
        groups: List[SignatureGroup],
        parked_idx: List[int],
        pools: List[PoolEncoding],
        encoded: List[EncodedInstanceTypes],
        sig_compats,
        allowed_per_pool,
        matrices: Dict[int, tuple],
        pool_entries: List["_CatalogEntry"],
        daemon_requests,
        result: SolverResult,
        remaining: Dict[str, dict],
        mesh,
    ) -> None:
        """Resolve single-term required pod-affinity groups AFTER the
        main pack, one group at a time in anchor-dependency order. At
        this point every committed placement has a final zone (and node),
        so each group's admissible domains are exactly the reference's
        Get-over-recorded-counts (topologygroup.go:215-247) under the
        valid pod ordering that schedules counted groups first."""
        order = self._topo_order_parked(groups, parked_idx)
        gi_of = (
            {
                i: gi
                for gi in parked_idx
                for i in groups[gi].pod_indices
            }
            if remaining
            else {}
        )
        # fixpoint over the parked groups — the tensor analogue of the
        # oracle's progress-detecting retry queue (scheduler/queue.py:25):
        # a group failing for lack of anchors re-tries after later groups
        # commit placements its selector matches; rounds stop when one
        # makes no progress (a genuinely dead anchor cycle fails in both
        # worlds)
        pending: Dict[int, List[int]] = {
            gi: list(groups[gi].pod_indices) for gi in order
        }
        for _ in range(len(order) + 1):
            progress = False
            for gi in order:
                idxs = pending.get(gi)
                if not idxs:
                    continue
                group = groups[gi]
                # prior round's failures were provisional — clear before retry
                for i in idxs:
                    result.pod_errors.pop(pods[i].uid, None)
                # limits move as plans emit — recompute the masks per attempt
                limit_masks = self._limit_masks(pools, encoded, remaining)
                info = self._choose_pool(
                    gi, group, pods, pools, encoded, sig_compats,
                    allowed_per_pool, result, idxs, limit_masks,
                )
                if info is None:
                    # incompatibility is terminal, not an anchor problem
                    pending[gi] = []
                    continue
                chosen = info["chosen"]
                pool, enc = pools[chosen], encoded[chosen]
                entry = pool_entries[chosen]
                requests_matrix = matrices[id(entry)][1]
                idx = np.asarray(info["indices"], dtype=np.int64)
                reqs = requests_matrix[idx]
                sort = np.lexsort((-reqs[:, 1], -reqs[:, 0]))
                idx, reqs = idx[sort], reqs[sort]
                daemon = daemon_requests[pool.nodepool.name]
                self._postpass_matrix = requests_matrix
                self._postpass_remaining = remaining
                jobs: List[tuple] = []
                metas: List[dict] = []
                plans_start = len(result.node_plans)
                if group.tensor_pod_affinity() == wk.LABEL_TOPOLOGY_ZONE:
                    self._postpass_zone_affinity(
                        info, group, idx, reqs, enc, pool, daemon, pods, result,
                        jobs, metas,
                    )
                else:
                    self._postpass_hostname_affinity(
                        info, group, idx, reqs, enc, pool, daemon, pods, result,
                        requests_matrix, remaining,
                    )
                if jobs:
                    records: List[dict] = []
                    self._pack_and_finalize(
                        jobs, metas, pods, result, records, mesh, merge_all=False
                    )
                    self._merge_and_emit(records, pods, result)
                if remaining:
                    # limited pools: strip plans that bust the remaining
                    # budget; their pods fail terminally (the pool is
                    # starved — retrying cannot help, scheduler.go:347-383)
                    spilled = self._enforce_limits(
                        result, plans_start, remaining, gi_of
                    )
                    pool_name = pools[info["chosen"]].nodepool.name
                    for sgi, sidx in spilled.items():
                        for i in sidx:
                            result.pod_errors[pods[i].uid] = (
                                "all available instance types exceed limits "
                                f'for nodepool: "{pool_name}"'
                            )
                failed = [i for i in idxs if pods[i].uid in result.pod_errors]
                if len(failed) < len(idxs):
                    progress = True
                pending[gi] = failed
            if not progress:
                break

    def _postpass_zone_affinity(
        self,
        info: dict,
        group: SignatureGroup,
        idx: np.ndarray,
        reqs: np.ndarray,
        enc: EncodedInstanceTypes,
        pool: PoolEncoding,
        daemon: np.ndarray,
        pods: List[Pod],
        result: SolverResult,
        jobs: List[tuple],
        metas: List[dict],
    ) -> None:
        """Zone pod-affinity against committed placements: pods may go
        to any viable zone where EVERY required term already counts a
        matching pod (per-term anchor masks intersected — ISSUE 12
        multi-term); terms with no anchors anywhere must be
        self-selecting and bootstrap — all of a bootstrapping group's
        pods land in ONE zone, since the first placement re-anchors the
        empty terms there (topologygroup.go:215-232)."""
        from .topology_tensor import seed_counts_for_selector

        terms = group.affinity_terms()
        zone_ok, ct_ok, viable = info["zone_ok"], info["ct_ok"], info["viable"]
        ctx = self._existing_ctx
        zones, zone_types = _viable_zones(enc, viable, zone_ok, ct_ok)

        def zone_price(z: str) -> float:
            zi = enc.zones.index(z)
            p = enc.offering_price[zone_types[z], zi, :][:, ct_ok]
            p = np.where(np.isfinite(p), p, np.inf)
            return float(p.min()) if p.size else np.inf

        own_labels = group.exemplar.metadata.labels
        anchored_sets: List[set] = []
        bootstrap_ok = True
        for term in terms:
            seeds = self._fold_committed(
                seed_counts_for_selector(
                    self.kube_client,
                    group.exemplar,
                    wk.LABEL_TOPOLOGY_ZONE,
                    term.label_selector,
                    self._batch_uids,
                ),
                term.label_selector,
                group.exemplar.namespace,
                pods,
                result,
            )
            anchors_t = {z for z, v in seeds.items() if v > 0}
            if anchors_t:
                anchored_sets.append(anchors_t)
            elif term.label_selector is not None and not term.label_selector.matches(
                own_labels
            ):
                bootstrap_ok = False  # empty term, not self-seedable
        if not bootstrap_ok:
            # some term has no matching pod anywhere and the group
            # cannot seed its own domain (nextDomainAffinity bootstraps
            # only when the pod matches its own selector)
            for i in idx:
                result.pod_errors[pods[i].uid] = (
                    "pod affinity: no pod matches the affinity selector"
                )
            return
        has_bootstrap_terms = len(anchored_sets) < len(terms)
        anchors: List[str] = []
        if anchored_sets:
            inter = set.intersection(*anchored_sets)
            anchors = [z for z in zones if z in inter]
            if not anchors:
                # matching pods exist, but no viable zone satisfies every
                # term jointly — the affinity pins the pods elsewhere
                for i in idx:
                    result.pod_errors[pods[i].uid] = (
                        "pod affinity anchors are outside viable zones"
                    )
                return
            if has_bootstrap_terms:
                # the first placement seeds the empty terms in its zone;
                # later pods must then co-locate — one zone for the group
                anchors = [min(anchors, key=zone_price)]
        if anchors:
            part = idx
            if ctx is not None:
                for z in anchors:
                    if not part.size:
                        break
                    part = self._pack_spread_existing(part, z, group, ctx, result)
            if part.size:
                # this solve's planned nodes in anchor zones admit the
                # pods too (the oracle back-fills in-flight claims
                # before opening nodes, scheduler.go:241-246)
                anchor_plans = [
                    p for p in result.node_plans if p.zone in anchors
                ]
                if anchor_plans:
                    entry_matrix = self._postpass_matrix
                    part = self._join_planned_nodes(
                        part, anchor_plans, info, enc, pool, daemon, pods,
                        result, entry_matrix, self._postpass_remaining,
                    )
            if part.size:
                sub = np.isin(idx, part)
                zmask = zone_ok & np.array(
                    [z in anchors for z in enc.zones], dtype=bool
                )
                v = viable & enc.offering_avail[:, zmask, :][:, :, ct_ok].any(
                    axis=(1, 2)
                )
                self._prepare_job(
                    idx[sub], reqs[sub], enc, v, zmask, ct_ok, daemon,
                    info["max_per_node"], pool, pods, result, jobs, metas,
                    merged=info["merged"],
                )
            return
        if not group.affinity_self_selecting():
            # no matching pod anywhere and the group cannot seed its own
            # domain (nextDomainAffinity bootstraps only when the pod
            # matches its own selector)
            for i in idx:
                result.pod_errors[pods[i].uid] = (
                    "pod affinity: no pod matches the affinity selector"
                )
            return
        if zones:
            # bootstrap exactly one zone — cheapest viable offering (the
            # oracle picks an arbitrary viable domain; a refinement)
            z_star = min(zones, key=zone_price)
            part = idx
            if ctx is not None:
                part = self._pack_spread_existing(part, z_star, group, ctx, result)
            if part.size:
                star_plans = [p for p in result.node_plans if p.zone == z_star]
                if star_plans:
                    part = self._join_planned_nodes(
                        part, star_plans, info, enc, pool, daemon, pods,
                        result, self._postpass_matrix, self._postpass_remaining,
                    )
            if part.size:
                sub = np.isin(idx, part)
                self._prepare_job(
                    idx[sub], reqs[sub], enc, zone_types[z_star],
                    zone_ok, ct_ok, daemon, info["max_per_node"], pool,
                    pods, result, jobs, metas, zone=z_star,
                    merged=info["merged"],
                )
        else:
            for i in idx:
                result.pod_errors[pods[i].uid] = (
                    "no zone with viable offering for pod affinity"
                )

    def _postpass_hostname_affinity(
        self,
        info: dict,
        group: SignatureGroup,
        idx: np.ndarray,
        reqs: np.ndarray,
        enc: EncodedInstanceTypes,
        pool: PoolEncoding,
        daemon: np.ndarray,
        pods: List[Pod],
        result: SolverResult,
        requests_matrix: np.ndarray,
        remaining: Optional[Dict[str, dict]] = None,
    ) -> None:
        """Hostname pod-affinity against committed placements: anchors
        are existing nodes holding matching pods AND this solve's planned
        nodes holding matching members (joinable with instance-type
        growth, as the oracle's in-flight claims re-size). With no
        anchors, a self-selecting group bootstraps one co-located node;
        anyone else fails (topologygroup.go:215-232). With additional
        ZONE terms (ISSUE 12 multi-term), anchor nodes/plans must also
        sit in the zones every zone term admits."""
        from .topology_tensor import seed_counts_for_selector

        terms = group.affinity_terms()
        host_term = next(
            t for t in terms if t.topology_key == wk.LABEL_HOSTNAME
        )
        zone_terms = [t for t in terms if t.topology_key == wk.LABEL_TOPOLOGY_ZONE]
        ns = group.exemplar.namespace
        sel = host_term.label_selector
        ctx = self._existing_ctx
        own_labels = group.exemplar.metadata.labels
        zone_filter: Optional[set] = None
        zone_bootstrap = False
        for zt in zone_terms:
            zseeds = self._fold_committed(
                seed_counts_for_selector(
                    self.kube_client, group.exemplar, wk.LABEL_TOPOLOGY_ZONE,
                    zt.label_selector, self._batch_uids,
                ),
                zt.label_selector, ns, pods, result,
            )
            anchors_t = {z for z, v in zseeds.items() if v > 0}
            if anchors_t:
                zone_filter = anchors_t if zone_filter is None else (zone_filter & anchors_t)
            elif zt.label_selector is not None and not zt.label_selector.matches(own_labels):
                for i in idx:
                    result.pod_errors[pods[i].uid] = (
                        "pod affinity: no pod matches the affinity selector"
                    )
                return
            else:
                zone_bootstrap = True  # self-seedable empty zone term
        if zone_filter is not None and not zone_filter:
            for i in idx:
                result.pod_errors[pods[i].uid] = (
                    "pod affinity anchors are outside viable zones"
                )
            return
        seeds = seed_counts_for_selector(
            self.kube_client,
            group.exemplar,
            wk.LABEL_HOSTNAME,
            sel,
            self._batch_uids,
        )
        # existing nodes that GAINED matching members this solve anchor too
        for eplan in result.existing_plans:
            if any(
                pods[i].namespace == ns and self._sel_matches(sel, i, pods)
                for i in eplan.pod_indices
            ):
                name = eplan.state_node.hostname() or eplan.state_node.name()
                seeds[name] = seeds.get(name, 0) + 1

        planned_anchors = [
            p
            for p in result.node_plans
            if self._plan_has_match(p, sel, ns, pods)
            and (zone_filter is None or p.zone in zone_filter)
        ]
        if zone_bootstrap and (seeds or planned_anchors):
            # an empty self-seedable zone term pins the whole group to
            # ONE zone once the first pod lands: take the first anchor's
            # zone (node order, then plan order — the oracle's first-fit
            # p1 choice) and narrow the filter to it
            z_star = None
            if ctx is not None and seeds:
                for n, z in zip(ctx["nodes"], ctx["node_zones"]):
                    if (n.hostname() in seeds or n.name() in seeds) and (
                        zone_filter is None or z in zone_filter
                    ):
                        z_star = str(z)
                        break
            if z_star is None and planned_anchors:
                z_star = planned_anchors[0].zone
            if z_star is not None:
                zone_filter = {z_star}
                planned_anchors = [p for p in planned_anchors if p.zone == z_star]
        left = idx
        if seeds and ctx is not None and left.size:
            left = self._pack_affinity_hostname_existing(
                left, group, seeds, ctx, result, zone_filter=zone_filter
            )
        if planned_anchors and left.size:
            left = self._join_planned_nodes(
                left, planned_anchors, info, enc, pool, daemon, pods, result,
                requests_matrix, remaining,
            )
        if left.size and planned_anchors:
            # anchors at max capacity: the oracle never reaches this state
            # because its anchors absorb joiners while growing across MANY
            # claims — reproduce the outcome by re-seeding: move one
            # matching pod from an over-full anchor plan onto a fresh
            # node (same zone, so its own zone-level constraints and all
            # committed counts stay intact) and co-locate joiners there
            left = self._reseed_anchor_nodes(
                left, planned_anchors, info, enc, pool, daemon, pods, result,
                requests_matrix, sel, ns,
            )
        if not left.size:
            return
        if not seeds and not planned_anchors:
            if group.affinity_self_selecting():
                binfo = info
                if zone_filter is not None:
                    zmask = info["zone_ok"] & np.array(
                        [z in zone_filter for z in enc.zones], dtype=bool
                    )
                    v = info["viable"] & enc.offering_avail[:, zmask, :][
                        :, :, info["ct_ok"]
                    ].any(axis=(1, 2))
                    if not v.any():
                        for i in left:
                            result.pod_errors[pods[i].uid] = (
                                "pod affinity anchors are outside viable zones"
                            )
                        return
                    binfo = dict(info, zone_ok=zmask, viable=v)
                sub = np.isin(idx, left)
                self._pack_affinity_hostname_new(
                    idx[sub], reqs[sub], enc, pool, daemon, binfo, pods, result
                )
                return
            for i in left:
                result.pod_errors[pods[i].uid] = (
                    "pod affinity: no pod matches the affinity selector"
                )
            return
        # anchors exist but are full: a fresh claim is a zero-count domain
        for i in left:
            result.pod_errors[pods[i].uid] = (
                "pod affinity on hostname: anchor nodes are full"
            )

    def _reseed_anchor_nodes(
        self,
        left: np.ndarray,
        plans: List["NodePlan"],
        info: dict,
        enc: EncodedInstanceTypes,
        pool: PoolEncoding,
        daemon: np.ndarray,
        pods: List[Pod],
        result: SolverResult,
        requests_matrix: np.ndarray,
        sel,
        ns: str,
    ) -> np.ndarray:
        """Seed fresh anchor nodes for hostname-affinity leftovers: take
        one selector-matching pod from a full anchor plan that holds more
        than one, open a new node in the SAME zone with it, and first-fit
        leftovers there. Zone-invariant by construction, so every
        committed zone count and zone-level constraint is untouched."""
        from ..scheduling.requirements import ALLOW_UNDEFINED_WELL_KNOWN_LABELS

        merged = info["merged"]
        viable = info["viable"]
        alloc = self._alloc_full(enc, daemon)
        # worklist: a freshly seeded node whose joiners also match the
        # selector (self-selecting groups) becomes a donor itself
        worklist = sorted(plans, key=lambda p: -len(p.pod_indices))
        wi = 0
        while wi < len(worklist):
            donor_plan = worklist[wi]
            wi += 1
            if not left.size:
                break
            if donor_plan.max_pods_per_node < 2**31 - 1 or donor_plan.node_limits:
                continue
            if donor_plan.nodepool_name != pool.nodepool.name:
                continue
            if donor_plan.requirements is None or merged is None:
                continue
            if donor_plan.requirements.intersects(merged) is not None:
                continue
            if donor_plan.zone not in enc.zones:
                continue
            zi = enc.zones.index(donor_plan.zone)
            if donor_plan.capacity_type not in enc.capacity_types:
                continue
            ci = enc.capacity_types.index(donor_plan.capacity_type)
            if not (info["zone_ok"][zi] and info["ct_ok"][ci]):
                continue
            matching = [
                i
                for i in donor_plan.pod_indices
                if pods[i].namespace == ns and self._sel_matches(sel, i, pods)
            ]
            if len(matching) < 2:
                continue  # the donor plan must keep an anchor of its own
            while left.size and len(matching) > 1:
                donor = matching.pop()
                # the new node carries the donor too: admissible types
                # must satisfy BOTH sides' requirement sets (the same
                # combined filter — and cache — the join path uses)
                cache_key = (
                    donor_plan.requirements.fingerprint(),
                    merged.fingerprint(),
                    zi,
                    ci,
                    viable.tobytes(),
                )
                cached = self._join_types_cache.get(cache_key)
                if cached is None:
                    combined = Requirements(*donor_plan.requirements.values_list())
                    combined.add(*merged.values_list())
                    tmask = viable & enc.offering_avail[:, zi, ci]
                    cached = tuple(
                        int(t)
                        for t in np.flatnonzero(tmask)
                        if combined.compatible(
                            enc.instance_types[t].requirements,
                            ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
                            hint=False,
                        )
                        is None
                    )
                    self._join_types_cache[cache_key] = cached
                t_idx = np.array(cached, dtype=np.int64)
                if t_idx.size == 0:
                    return left
                usage = requests_matrix[[donor]].astype(np.int64).sum(axis=0)
                jreqs = requests_matrix[left].astype(np.int64)
                cum = usage[None, :] + np.cumsum(jreqs, axis=0)
                fits_any = (cum[:, None, :] <= alloc[t_idx][None, :, :]).all(-1).any(1)
                n_fit = (
                    int(fits_any.sum()) if fits_any.all() else int(np.argmin(fits_any))
                )
                if n_fit == 0:
                    matching.append(donor)
                    break
                load = cum[n_fit - 1]
                fits = (load[None, :] <= alloc[t_idx]).all(axis=1)
                prices = enc.offering_price[t_idx, zi, ci]
                prices = np.where(fits & np.isfinite(prices), prices, np.inf)
                t_local = int(np.argmin(prices))
                if not np.isfinite(prices[t_local]):
                    matching.append(donor)
                    break
                t = int(t_idx[t_local])
                rem = (
                    self._postpass_remaining.get(pool.nodepool.name)
                    if self._postpass_remaining
                    else None
                )
                if rem is not None:
                    cap = enc.instance_types[t].capacity
                    if any(v > rem.get(name, 0) for name, v in cap.items() if v > 0):
                        matching.append(donor)
                        break  # no limit headroom for another node
                    self._postpass_remaining[pool.nodepool.name] = resources.subtract(
                        rem, cap
                    )
                # detach the donor from its plan (zone unchanged, so the
                # incremental committed counts stay correct — but the
                # plan-level anchor cache must forget the shrunk plan)
                pos = donor_plan.pod_indices.index(donor)
                donor_plan.pod_indices.pop(pos)
                if donor_plan._pod_requests is not None:
                    donor_plan._pod_requests.pop(pos)
                donor_plan._requests = None
                pid = id(donor_plan)
                self._plan_match_cache = {
                    k: v for k, v in self._plan_match_cache.items() if k[1] != pid
                }
                members = [int(donor)] + [int(i) for i in left[:n_fit]]
                combined = Requirements(*donor_plan.requirements.values_list())
                combined.add(*merged.values_list())
                new_plan = NodePlan(
                    nodepool_name=pool.nodepool.name,
                    instance_type=enc.instance_types[t],
                    zone=donor_plan.zone,
                    capacity_type=donor_plan.capacity_type,
                    price=float(enc.offering_price[t, zi, ci]),
                    pod_indices=members,
                    requirements=combined,
                    _pod_requests=[self._all_requests[i] for i in members],
                )
                # limits were consumed above; the post-pass enforcement
                # must not subtract (or strip) this plan a second time
                new_plan._limits_accounted = True
                result.node_plans.append(new_plan)
                worklist.append(new_plan)
                # the donor was already counted in this zone; only the
                # joiners are new to the committed counters
                for st in self._fold_cache.values():
                    st["sizes"][id(new_plan)] = 1
                left = left[n_fit:]
        return left

    def _join_planned_nodes(
        self,
        left: np.ndarray,
        plans: List["NodePlan"],
        info: dict,
        enc: EncodedInstanceTypes,
        pool: PoolEncoding,
        daemon: np.ndarray,
        pods: List[Pod],
        result: SolverResult,
        requests_matrix: np.ndarray,
        remaining: Optional[Dict[str, dict]] = None,
    ) -> np.ndarray:
        """First-fit ``left`` (descending by size) onto this solve's
        planned anchor nodes, growing each node's instance type within
        the commonly-viable set — the tensor analogue of pods joining an
        in-flight NodeClaim whose instance options re-narrow
        (scheduler.go:241-246 + nodeclaim.go add semantics). Returns the
        indices that found no anchor capacity."""
        from ..kube.objects import OP_IN
        from ..scheduling import Requirement
        from ..scheduling.requirements import ALLOW_UNDEFINED_WELL_KNOWN_LABELS

        merged = info["merged"]
        viable = info["viable"]
        alloc = self._alloc_full(enc, daemon)
        for plan in plans:
            if not left.size:
                break
            if plan.max_pods_per_node < 2**31 - 1 or plan.node_limits:
                continue  # capped/limited (spread/anti) nodes never absorb joiners
            if plan.nodepool_name != pool.nodepool.name:
                continue
            if plan.requirements is None or merged is None:
                continue
            if plan.requirements.intersects(merged) is not None:
                continue
            if plan.zone not in enc.zones or plan.capacity_type not in enc.capacity_types:
                continue
            zi = enc.zones.index(plan.zone)
            ci = enc.capacity_types.index(plan.capacity_type)
            # the joiner's own zone/capacity-type admissibility must hold
            # at the plan's pinned offering (a zone-restricted pod can't
            # join a node in a forbidden zone)
            if not (info["zone_ok"][zi] and info["ct_ok"][ci]):
                continue
            cache_key = (
                plan.requirements.fingerprint(),
                merged.fingerprint(),
                zi,
                ci,
                viable.tobytes(),
            )
            cached = self._join_types_cache.get(cache_key)
            if cached is None:
                combined = Requirements(*plan.requirements.values_list())
                combined.add(*merged.values_list())
                combined.add(
                    Requirement(wk.LABEL_TOPOLOGY_ZONE, OP_IN, [plan.zone]),
                    Requirement(wk.CAPACITY_TYPE_LABEL_KEY, OP_IN, [plan.capacity_type]),
                )
                if merged.compatible(
                    combined, ALLOW_UNDEFINED_WELL_KNOWN_LABELS, hint=False
                ) is not None:
                    cached = ()
                else:
                    tmask = viable & enc.offering_avail[:, zi, ci]
                    cached = tuple(
                        int(t)
                        for t in np.flatnonzero(tmask)
                        if combined.compatible(
                            enc.instance_types[t].requirements,
                            ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
                            hint=False,
                        )
                        is None
                    )
                self._join_types_cache[cache_key] = cached
            if not cached:
                continue
            t_idx = np.array(cached, dtype=np.int64)
            usage = requests_matrix[plan.pod_indices].astype(np.int64).sum(axis=0)
            jreqs = requests_matrix[left].astype(np.int64)
            cum = usage[None, :] + np.cumsum(jreqs, axis=0)
            fits_any = (cum[:, None, :] <= alloc[t_idx][None, :, :]).all(-1).any(1)
            n_fit = int(fits_any.sum()) if fits_any.all() else int(np.argmin(fits_any))
            if n_fit == 0:
                continue
            load = cum[n_fit - 1]
            fits = (load[None, :] <= alloc[t_idx]).all(axis=1)
            prices = enc.offering_price[t_idx, zi, ci]
            prices = np.where(fits & np.isfinite(prices), prices, np.inf)
            t_local = int(np.argmin(prices))
            if not np.isfinite(prices[t_local]):
                continue
            t = int(t_idx[t_local])
            it_new = enc.instance_types[t]
            rem = remaining.get(plan.nodepool_name) if remaining else None
            if rem is not None and it_new is not plan.instance_type:
                # growing the node consumes limit headroom: the delta
                # between the new and old type's capacity must fit
                delta = resources.subtract(
                    it_new.capacity, plan.instance_type.capacity
                )
                if any(v > rem.get(name, 0) for name, v in delta.items() if v > 0):
                    continue
                remaining[plan.nodepool_name] = resources.subtract(rem, delta)
            members = left[:n_fit].tolist()
            plan.pod_indices.extend(int(i) for i in members)
            plan.instance_type = it_new
            plan.price = float(enc.offering_price[t, zi, ci])
            # rebuild the merged requirement set only on an actual join
            # (the admissible-type cache skips it on the probe path)
            combined = Requirements(*plan.requirements.values_list())
            combined.add(*merged.values_list())
            plan.requirements = combined
            if plan._pod_requests is not None:
                plan._pod_requests.extend(self._all_requests[int(i)] for i in members)
            plan._requests = None
            left = left[n_fit:]
        return left

    def _pack_affinity_hostname_existing(
        self,
        idx: np.ndarray,
        group: SignatureGroup,
        seeds: Dict[str, int],
        ctx: dict,
        result: SolverResult,
        zone_filter: Optional[set] = None,
    ) -> np.ndarray:
        """First-fit the group onto existing nodes already holding a
        matching pod (the only admissible domains once anchors exist);
        ``zone_filter`` narrows anchors to the zones the group's zone
        terms admit (ISSUE 12 multi-term)."""
        row = self._existing_compat_row(group, ctx).astype(bool)
        anchor = np.array(
            [n.hostname() in seeds or n.name() in seeds for n in ctx["nodes"]]
        )
        mask = row & anchor
        if zone_filter is not None:
            mask &= np.isin(ctx["node_zones"], sorted(zone_filter))
        if not mask.any():
            return idx
        reqs = build_requests_matrix_ids(
            self._req_ids[idx], ctx["axis"], self._req_map
        )
        assign, free_out = run_pack_existing(
            reqs,
            np.zeros(len(idx), dtype=np.int32),
            mask[None, :].astype(np.uint8),
            ctx["free"],
        )
        ctx["free"] = np.ascontiguousarray(free_out, dtype=np.int32)
        placed = assign >= 0
        by_node: Dict[int, List[int]] = {}
        for j in np.flatnonzero(placed):
            by_node.setdefault(int(assign[j]), []).append(int(idx[j]))
        for mnode in sorted(by_node):
            result.existing_plans.append(
                ExistingNodePlan(
                    state_node=ctx["nodes"][mnode], pod_indices=by_node[mnode]
                )
            )
        return idx[~placed]

    def _pack_affinity_hostname_new(
        self,
        idx: np.ndarray,
        reqs: np.ndarray,
        enc: EncodedInstanceTypes,
        pool: PoolEncoding,
        daemon: np.ndarray,
        m: dict,
        pods: List[Pod],
        result: SolverResult,
    ) -> None:
        """Bootstrap ONE co-located node: the largest size-descending
        prefix some viable type holds becomes a single NodePlan; the
        rest fail (a second claim would be a zero-count hostname domain
        the pods cannot join — oracle behavior)."""
        viable_idx = np.flatnonzero(m["viable"])
        if len(viable_idx) == 0:
            for i in idx:
                result.pod_errors[pods[i].uid] = "no viable instance type"
            return
        alloc = self._alloc_full(enc, daemon)[viable_idx]
        cum = np.cumsum(reqs.astype(np.int64), axis=0)  # (P, R)
        fits_any = (cum[:, None, :] <= alloc[None, :, :]).all(axis=-1).any(axis=1)
        n_fit = int(fits_any.sum()) if fits_any.all() else int(np.argmin(fits_any))
        if n_fit == 0:
            for i in idx:
                result.pod_errors[pods[i].uid] = (
                    "no instance type fits the first co-located pod"
                )
            return
        load = cum[n_fit - 1]
        fits = (load[None, :] <= alloc).all(axis=1)
        zone_ok, ct_ok = m["zone_ok"], m["ct_ok"]
        prices = enc.offering_price[viable_idx][:, zone_ok, :][:, :, ct_ok].reshape(
            len(viable_idx), -1
        )
        p = (
            np.where(np.isfinite(prices), prices, np.inf).min(axis=1)
            if prices.size
            else np.full(len(viable_idx), np.inf)
        )
        p = np.where(fits, p, np.inf)
        t_local = int(np.argmin(p))
        if not np.isfinite(p[t_local]):
            for i in idx:
                result.pod_errors[pods[i].uid] = (
                    "packed node has no fitting instance type"
                )
            return
        t = int(viable_idx[t_local])
        offering_zone, offering_ct, offering_price = self._cheapest_offering(
            enc, t, zone_ok, ct_ok, None
        )
        members = idx[:n_fit].tolist()
        result.node_plans.append(
            NodePlan(
                nodepool_name=pool.nodepool.name,
                instance_type=enc.instance_types[t],
                zone=offering_zone,
                capacity_type=offering_ct,
                price=offering_price,
                pod_indices=members,
                requirements=m["merged"],
                _pod_requests=[self._all_requests[i] for i in members],
            )
        )
        for i in idx[n_fit:]:
            result.pod_errors[pods[i].uid] = (
                "pod affinity on hostname: co-located node is full"
            )

    def _pack_hostname_existing(
        self,
        m: dict,
        idx: np.ndarray,  # group's pod indices, descending by size
        cap: int,
        pods: List[Pod],
        result: SolverResult,
    ) -> List[int]:
        """Fill existing nodes up to each node's hostname-topology quota
        (cap minus its existing matching-pod count — hostname domains
        always see a global min of 0, topologygroup.go:193-196).
        Host-side first-fit: group sizes here are small relative to the
        batch (the capped shapes), and the oracle this replaces was
        O(P·M) anyway. Returns the indices still needing new nodes."""
        from .encode import _selector_key
        from .topology_tensor import seed_counts_for_selector

        group: SignatureGroup = m["group"]
        ctx = self._existing_ctx
        nodes = ctx["nodes"]
        if not nodes:
            return list(idx)
        ns = group.exemplar.namespace
        # EVERY hostname constraint the group carries contributes its own
        # (cap, selector, seeds) triple — a group can have both a
        # hostname spread (cap=max_skew, spread selector) and self
        # anti-affinity (cap=1, anti selector); a node's quota is the
        # minimum over all of them
        constraints: List[tuple] = []
        hs = group.hostname_spread()
        if hs is not None:
            constraints.append(
                (int(hs.max_skew), hs.label_selector, self._spread_seeds(group, hs))
            )
        if group.hostname_isolated:
            term = next(
                t
                for t in group.exemplar.spec.affinity.pod_anti_affinity.required
                if t.topology_key == wk.LABEL_HOSTNAME
            )
            skey = ("anti-host", _selector_key(term.label_selector), ns)
            seeds = self._seed_cache.get(skey)
            if seeds is None:
                seeds = seed_counts_for_selector(
                    self.kube_client,
                    group.exemplar,
                    wk.LABEL_HOSTNAME,
                    term.label_selector,
                    self._batch_uids,
                )
                self._seed_cache[skey] = seeds
            constraints.append((1, term.label_selector, seeds))
        if not constraints:
            return list(idx)

        # fold THIS solve's committed existing-node placements (matching
        # pods this batch already put on a node — e.g. earlier rounds or
        # retries — count against that node's quota, like the oracle's
        # immediate Record)
        def _committed(selector) -> Dict[str, int]:
            out: Dict[str, int] = {}
            for eplan in result.existing_plans:
                n = sum(
                    1
                    for i in eplan.pod_indices
                    if pods[i].namespace == ns
                    and (selector is None or selector.matches(pods[i].metadata.labels))
                )
                if n:
                    name = eplan.state_node.hostname() or eplan.state_node.name()
                    out[name] = out.get(name, 0) + n
            return out

        row = self._existing_compat_row(group, ctx).astype(bool)
        quota = np.where(row, np.int64(cap), np.int64(0)).astype(np.int64)
        for c_cap, selector, seeds in constraints:
            committed = _committed(selector)
            q_c = np.array(
                [
                    max(
                        0,
                        c_cap
                        - max(seeds.get(n.hostname(), 0), seeds.get(n.name(), 0))
                        - max(
                            committed.get(n.hostname(), 0),
                            committed.get(n.name(), 0),
                        ),
                    )
                    for n in nodes
                ],
                dtype=np.int64,
            )
            quota = np.minimum(quota, q_c)
        if not quota.any():
            return list(idx)
        reqs = build_requests_matrix_ids(
            self._req_ids[idx], ctx["axis"], self._req_map
        )
        free = ctx["free"]
        by_node: Dict[int, List[int]] = {}
        leftover: List[int] = []
        eligible = np.flatnonzero(quota > 0)
        for j, i in enumerate(idx):
            placed = False
            for mi in eligible:
                if quota[mi] > 0 and (free[mi] >= reqs[j]).all():
                    free[mi] -= reqs[j]
                    quota[mi] -= 1
                    by_node.setdefault(int(mi), []).append(int(i))
                    placed = True
                    break
            if not placed:
                leftover.append(int(i))
        for mi in sorted(by_node):
            result.existing_plans.append(
                ExistingNodePlan(state_node=nodes[mi], pod_indices=by_node[mi])
            )
        return leftover

    def _pack_spread_existing(
        self,
        part: np.ndarray,
        zone: str,
        group: SignatureGroup,
        ctx: dict,
        result: SolverResult,
    ) -> np.ndarray:
        """First-fit one zone bucket onto that zone's admitting existing
        nodes (zone-pinned so committed domain counts stay exact);
        returns the indices that still need a new node."""
        row = self._existing_compat_row(group, ctx).astype(bool)
        mask = row & (ctx["node_zones"] == zone)
        if not mask.any():
            return part
        reqs = build_requests_matrix_ids(
            self._req_ids[part], ctx["axis"], self._req_map
        )
        assign, free_out = run_pack_existing(
            reqs,
            np.zeros(len(part), dtype=np.int32),
            mask[None, :].astype(np.uint8),
            ctx["free"],
        )
        ctx["free"] = np.ascontiguousarray(free_out, dtype=np.int32)
        placed = assign >= 0
        if placed.any():
            by_node: Dict[int, List[int]] = {}
            for j in np.flatnonzero(placed):
                by_node.setdefault(int(assign[j]), []).append(int(part[j]))
            for mnode in sorted(by_node):
                result.existing_plans.append(
                    ExistingNodePlan(
                        state_node=ctx["nodes"][mnode], pod_indices=by_node[mnode]
                    )
                )
        return part[~placed]

    # ------------------------------------------------------------------

    def _prepare_job(
        self,
        idx: np.ndarray,
        reqs: np.ndarray,
        enc: EncodedInstanceTypes,
        viable: np.ndarray,
        zone_ok: np.ndarray,
        ct_ok: np.ndarray,
        daemon: np.ndarray,
        max_per_node,
        pool: PoolEncoding,
        pods: List[Pod],
        result: SolverResult,
        jobs: List[tuple],
        metas: List[dict],
        zone: Optional[str] = None,
        merged=None,
        per_node_limits: Optional[list] = None,
        no_merge: bool = False,
        pod_ports: Optional[Dict[int, tuple]] = None,
    ) -> None:
        viable_idx = np.flatnonzero(viable)
        if len(viable_idx) == 0:
            for i in idx:
                result.pod_errors[pods[i].uid] = "no viable instance type"
            return
        # stable name-rank order on the viable axis: every downstream
        # price argmin over this axis (assign_cheapest_types, both
        # engines) then resolves exact price ties to the same instance
        # type regardless of catalog list order (see _offering_rank)
        viable_idx = _rank_order(viable_idx, _type_rank(enc))
        # daemon-adjusted allocatable (shared with the merge pass so the
        # pack-time and merge-time capacity views can't diverge)
        alloc = self._alloc_full(enc, daemon)[viable_idx].astype(np.int32)
        # zone buckets of one group share viable sets — cache the frontier
        # on the encoding (warm across solves for cached catalogs)
        cache_key = ("frontier", viable_idx.tobytes(), daemon.tobytes())
        frontier = enc.runtime_caches.get(cache_key)
        if frontier is None:
            frontier = pareto_frontier(alloc)
            _cache_put(enc, cache_key, frontier)
        # host-port feature columns (ISSUE 12): appended to the job's
        # request matrix and frontier so the pack kernel enforces port
        # conflicts natively — every frontier point carries the fresh-
        # node port capacities (constant columns preserve dominance).
        # meta["reqs"]/["alloc"] stay resource-only: finalize prices and
        # usage never see the pseudo axes.
        job_reqs, job_frontier = reqs, frontier
        port_features: tuple = ()
        port_sets = None
        if pod_ports:
            sets = [pod_ports.get(int(i), ()) for i in idx]
            if any(sets):
                from .constraint_tensors import PortFeatures

                feats = PortFeatures(sets)
                if feats.count:
                    port_sets = sets
                    port_features = tuple(feats.features)
                    job_reqs = np.ascontiguousarray(
                        np.hstack([reqs, feats.load_matrix(sets)]), dtype=np.int32
                    )
                    job_frontier = np.ascontiguousarray(
                        np.hstack(
                            [frontier, np.tile(feats.caps, (frontier.shape[0], 1))]
                        ),
                        dtype=np.int32,
                    )
        jobs.append((job_reqs, job_frontier, np.int32(max_per_node)))
        metas.append(
            dict(
                idx=idx,
                reqs=reqs,
                enc=enc,
                viable_idx=viable_idx,
                alloc=alloc,
                zone_ok=zone_ok,
                ct_ok=ct_ok,
                pool=pool,
                zone=zone,
                daemon=daemon,
                max_per_node=int(max_per_node),
                merged=merged,
                per_node_limits=per_node_limits or [],
                no_merge=no_merge,
                port_features=port_features,
                pod_port_sets=port_sets,
            )
        )

    def _pack_and_finalize(
        self,
        jobs: List[tuple],
        metas: List[dict],
        pods: List[Pod],
        result: SolverResult,
        records: List[dict],
        mesh,
        merge_all: Optional[bool] = None,
    ) -> None:
        """Pack + finalize one job batch through the cross-tick job memo
        (solver/incremental.py): a job whose content-addressed key hits
        reuses last tick's pack result and finalize skeleton — no device
        dispatch (zero H2D for that job), no usage/type/offering
        recompute — and only rebinds node memberships to this tick's
        batch indices. Misses run exactly the cold pipeline and populate
        the memo. Emission order is the metas order either way, so warm
        and cold solves build identical plan/record streams."""
        from . import backends as backends_mod

        # pack-backend seam (solver/backends/): ffd | lp | auto per the
        # KARPENTER_TPU_PACK_BACKEND switch. The backend only decides the
        # pod→node partition; pricing/finalize below is backend-agnostic,
        # and each job's memo key carries the backend token so a switch
        # between ticks can never alias cached skeletons.
        backend = backends_mod.active_backend()
        ws = self._warm
        plane = self.fleet_plane
        keys: List[Optional[tuple]] = [None] * len(jobs)
        skels: List[Optional[incremental.JobSkeleton]] = [None] * len(jobs)
        if ws is not None and jobs:
            with tracer.span("pack.cache.lookup", jobs=len(jobs)):
                for i, (job, meta) in enumerate(zip(jobs, metas)):
                    key = self._job_key(job, meta, mesh, backend)
                    keys[i] = key
                    if key is not None:
                        skels[i] = ws.jobs.get(key, self._cstats)
                        if skels[i] is None and plane is not None:
                            # fleet content plane: the key minus its
                            # trailing tenant scope is pure content
                            # (catalog entry identity+fingerprint, pool
                            # fingerprint, request digest, every mask,
                            # engine+backend tokens), so a skeleton
                            # another tenant computed for the identical
                            # content IS this job's skeleton
                            skels[i] = plane.skeleton_get(key[:-1], self._cstats)
        miss = [i for i in range(len(jobs)) if skels[i] is None]
        # the backends' meta contract, enumerated field by field: this
        # is every meta input a backend may read (backends/__init__.py),
        # and listing them explicitly keeps the job-memo read-set check
        # field-precise (passing the whole metas list would root the
        # value slice at `metas` and mask the per-field key witnesses)
        miss_metas = [
            dict(
                alloc=metas[i]["alloc"],
                enc=metas[i]["enc"],
                viable_idx=metas[i]["viable_idx"],
                zone_ok=metas[i]["zone_ok"],
                ct_ok=metas[i]["ct_ok"],
                zone=metas[i]["zone"],
            )
            for i in miss
        ]
        # backend.lock spans the call AND the per-call output reads: a
        # concurrent solve (shadow parity) on the shared singleton must
        # not overwrite last_stats/last_job_flags between them
        with backend.lock:
            packed = (
                # analysis: allow-wait-under-lock(device — backend.lock exists to serialize this dispatch and its output reads; the solver holds no other lock here, so the edge cannot deadlock)
                backend.pack_jobs(
                    [jobs[i] for i in miss],
                    miss_metas,
                    mesh=mesh,
                    stats=self._cstats,
                )
                if miss
                else []
            )
            self._observe_pack_backend(backend, bool(miss))
            # per-job guard flags: True where the LP partition won —
            # those jobs' merge records become cost-guarded (a merge may
            # not raise the price back above what the guard just saved)
            miss_flags = list(getattr(backend, "last_job_flags", ()) or ())
        if len(miss_flags) != len(miss):
            miss_flags = [False] * len(miss)
        if merge_all is None:
            # small plans: every (uncapped) node joins the merge pass —
            # the oracle also back-fills leftover space on full nodes.
            # Large plans: only underfull tails (bounds the merge cost).
            total_nodes = 0
            mi = 0
            for i in range(len(jobs)):
                if skels[i] is not None:
                    total_nodes += skels[i].node_count
                else:
                    total_nodes += int(packed[mi][1])
                    mi += 1
            merge_all = total_nodes <= 256
        with tracer.span("pack.finalize"):
            mi = 0
            for i, meta in enumerate(metas):
                skel = skels[i]
                if skel is None:
                    node_ids, node_count = packed[mi]
                    cost_guard = miss_flags[mi]
                    mi += 1
                    skel = self._job_skeleton(
                        meta, node_ids, int(node_count), cost_guard=cost_guard
                    )
                    if keys[i] is not None:
                        # meta["reqs"] is the job's request matrix (keyed
                        # by its blake2b digest via the job tuple) and
                        # meta["alloc"] is _alloc_full(enc, daemon)[viable]
                        # — every constituent is in the key
                        # analysis: allow-cache-key(metas.reqs, metas.alloc)
                        ws.jobs.put(keys[i], skel, self._cstats)
                        if plane is not None:
                            # content-plane publish under the tenant-free
                            # content prefix (same witness argument as
                            # the put above; the dropped tenant scope is
                            # not in the computation's read-set — the
                            # skeleton is a pure function of the keyed
                            # content, which is what makes cross-tenant
                            # sharing memoization, not approximation)
                            # analysis: allow-cache-key(metas.reqs, metas.alloc)
                            plane.skeleton_put(keys[i][:-1], skel, self._cstats)
                self._emit_skeleton(
                    meta, skel, keys[i], pods, result, records, merge_all
                )

    def _observe_pack_backend(self, backend, dispatched: bool) -> None:
        """Surface the pack backend's per-call outcome (LP guard wins,
        relaxation bound sum) in per-solve stats, the solve trace, and
        the lp-jobs metric."""
        stats = getattr(backend, "last_stats", None) if dispatched else None
        acc = self._pack_backend_stats
        acc["backend"] = backend.name
        if not stats:
            return
        for k in (
            "jobs",
            "lp_won",
            "ffd_kept",
            "ffd_kept_cold",
            "ffd_kept_refined",
            "refine_rounds",
            "refine_accepted",
            "branches_considered",
            "branches_pruned",
            "branches_explored",
            "branches_won",
            "ascent_iters",
        ):
            if k in stats:
                acc[k] = acc.get(k, 0) + int(stats[k])
        for k in ("lp_bound_sum", "lp_saved_per_hr"):
            if k in stats:
                acc[k] = round(acc.get(k, 0.0) + float(stats[k]), 6)
        if self.metrics is not None and hasattr(self.metrics, "solver_lp_jobs"):
            if stats.get("lp_won"):
                self.metrics.solver_lp_jobs.inc(stats["lp_won"], outcome="lp_won")
            # the ISSUE-19 outcome split: a job FFD kept because the
            # optimality tier never ran (cold) is a different signal
            # from one it kept AFTER refinement/branching spent their
            # budgets (refined). Legacy backends report only the total.
            cold = int(stats.get("ffd_kept_cold", 0))
            refined = int(stats.get("ffd_kept_refined", 0))
            if cold:
                self.metrics.solver_lp_jobs.inc(cold, outcome="ffd_kept_cold")
            if refined:
                self.metrics.solver_lp_jobs.inc(refined, outcome="ffd_kept_refined")
            if stats.get("ffd_kept") and not (cold or refined):
                self.metrics.solver_lp_jobs.inc(stats["ffd_kept"], outcome="ffd_kept")
        if self.metrics is not None and hasattr(self.metrics, "solver_lp_branches"):
            for outcome in ("pruned", "explored", "won"):
                v = int(stats.get(f"branches_{outcome}", 0))
                if v:
                    self.metrics.solver_lp_branches.inc(v, outcome=outcome)

    def _job_key(self, job: tuple, meta: dict, mesh, backend=None) -> Optional[tuple]:
        """Content address of one pack job: every input the pack AND the
        finalize read. Two ticks producing equal keys provably produce
        identical skeletons (the computation is deterministic), which is
        what keeps warm solves plan-identical to cold ones."""
        # identity lookup, revalidated: _enc_keys maps id(enc) to
        # (id(entry), entry.fingerprint) captured under _CATALOG_LOCK, and
        # the fingerprint rides in the key — a recycled id cannot alias
        # analysis: allow-cache-determinism(id)
        enc_key = self._enc_keys.get(id(meta["enc"])) if hasattr(self, "_enc_keys") else None
        if enc_key is None or self._warm is None:
            return None
        pool_fp = self._pool_fp_by_name.get(meta["pool"].nodepool.name)
        if pool_fp is None:
            return None
        reqs, _frontier, mpn = job
        merged = meta["merged"]
        limits_key = tuple(
            (self._sel_fp(sel) if sel is not None else None, ns, int(cap))
            for sel, ns, cap in meta["per_node_limits"] or ()
        )
        return (
            enc_key,
            pool_fp,
            meta["zone"],
            incremental.job_digest(reqs),
            meta["viable_idx"].tobytes(),
            np.asarray(meta["zone_ok"]).tobytes(),
            np.asarray(meta["ct_ok"]).tobytes(),
            meta["daemon"].tobytes(),
            int(mpn),
            merged.fingerprint() if merged is not None else None,
            limits_key,
            bool(meta["no_merge"]),
            # host-port content (ISSUE 12): the appended feature COLUMNS
            # ride the reqs digest, but two different port universes can
            # produce byte-identical matrices (TCP:80 vs TCP:81 wildcard
            # columns) — the feature labels disambiguate, and the merge
            # pass's conflict guard reads them through the emitted
            # records, so skeleton streams must never alias across them
            # (a field subscript, not .get(): a dict-rooted read would
            # widen the cachesound witness over every meta field)
            tuple(meta["port_features"] or ()),
            incremental.pack_engine_token(mesh),
            # pack-backend identity: which engine partitioned this job
            # (plus its configuration, e.g. the LP iteration budget) —
            # two backends may produce different partitions for equal
            # inputs, so their skeletons must never alias
            backend.job_token() if backend is not None else ("ffd",),
            # tenant scope LAST, by contract: everything before it is
            # pure content (the fleet content plane shares skeletons
            # across tenants under key[:-1]); the scope itself is
            # isolation defense-in-depth on top of the per-tenant warm
            # state (incremental.warm_state_for)
            self._tenant_scope,
        )

    def _job_skeleton(
        self, meta: dict, node_ids: np.ndarray, node_count: int,
        cost_guard: bool = False,
    ) -> incremental.JobSkeleton:
        """The pure finalize computation for one packed job, positional
        over the job's size-sorted pod order (no batch indices — those
        rebind at emit time). Offerings are resolved for EVERY ok node
        so the skeleton serves both merge_all regimes."""
        reqs, enc = meta["reqs"], meta["enc"]
        viable_idx, alloc = meta["viable_idx"], meta["alloc"]
        zone_ok, ct_ok, zone = meta["zone_ok"], meta["ct_ok"], meta["zone"]
        node_ids = np.asarray(node_ids)
        unsched = np.flatnonzero(node_ids < 0)
        R = reqs.shape[1]
        if node_count == 0:
            z = np.zeros(0, dtype=np.int64)
            return incremental.JobSkeleton(
                0, z, np.zeros(1, dtype=np.int64), unsched,
                np.zeros(0, dtype=bool), np.zeros(0, dtype=bool),
                np.zeros((0, R), dtype=np.int64), alloc.max(axis=0) if alloc.size else np.zeros(R, np.int32),
                z, z, [], [], np.zeros(0), cost_guard,
            )
        usage = node_usage_from_assignment(reqs, node_ids, node_count)

        # price per viable type: cheapest offering allowed by the
        # signature's zone/capacity-type requirements (zone-pinned if
        # set) — shared with the pack backends (backends.job_prices) so
        # a backend's cost reasoning cannot drift from this pricing
        prices = _job_prices(meta)

        chosen_types = assign_cheapest_types(usage, alloc, prices)
        # underfull ⇔ half the elementwise-max viable allocatable still
        # holds the load — those tail nodes go to the merge pass
        alloc_cap = alloc.max(axis=0)
        # group pod positions by node in one argsort pass (not O(N·P) masks)
        valid = node_ids >= 0
        vpos = np.flatnonzero(valid)
        order = np.argsort(node_ids[valid], kind="stable")
        positions = vpos[order]
        sorted_ids = node_ids[valid][order]
        bounds = np.searchsorted(sorted_ids, np.arange(node_count + 1))
        usage64 = usage.astype(np.int64)
        ok = chosen_types >= 0
        underfull = np.all(
            usage64 * 2 <= alloc_cap.astype(np.int64)[None, :], axis=1
        )
        if cost_guard:
            # LP-chosen partitions: a node is only a merge candidate when
            # it is underfull for its CHOSEN type — the LP deliberately
            # sizes nodes for cheap small types, and measuring fullness
            # against the biggest viable type would send every such node
            # through a merge pass whose cost guard then rejects it
            # pair by pair (pure overhead at plan-identical output)
            chosen_alloc = alloc[np.maximum(chosen_types, 0)].astype(np.int64)
            underfull = ok & np.all(usage64 * 2 <= chosen_alloc, axis=1)
        ok_nodes = np.flatnonzero(ok)
        ok_ord = np.full(node_count, -1, dtype=np.int64)
        ok_ord[ok_nodes] = np.arange(ok_nodes.size)
        if ok_nodes.size:
            # one masked argmin over (N, Z, C) replaces a
            # _cheapest_offering call per emitted node
            t_global = viable_idx[chosen_types[ok_nodes]]
            off_zone, off_ct, off_price = self._cheapest_offering_batch(
                enc, t_global, zone_ok, ct_ok, zone
            )
        else:
            t_global = np.zeros(0, dtype=np.int64)
            off_zone, off_ct, off_price = [], [], np.zeros(0)
        return incremental.JobSkeleton(
            node_count=int(node_count),
            positions=positions,
            bounds=bounds,
            unsched=unsched,
            ok=ok,
            underfull=underfull,
            usage64=usage64,
            alloc_cap=alloc_cap,
            ok_ord=ok_ord,
            t_global=t_global,
            off_zone=off_zone,
            off_ct=off_ct,
            off_price=off_price,
            cost_guard=bool(cost_guard),
        )

    def _emit_skeleton(
        self,
        meta: dict,
        skel: incremental.JobSkeleton,
        key: Optional[tuple],
        pods: List[Pod],
        result: SolverResult,
        records: List[dict],
        merge_all: bool,
    ) -> None:
        """Rebind one job skeleton to this tick's batch: positional node
        memberships become pod indices, plan nodes emit NodePlans, and
        underfull tails become merge records (carrying their record
        identity ``_rkey`` when the job is memoized)."""
        idx, enc = meta["idx"], meta["enc"]
        for i in idx[skel.unsched]:
            result.pod_errors[pods[i].uid] = (
                "no instance type satisfied resources and requirements (tensor path)"
            )
        if skel.node_count == 0:
            return
        viable_bool = np.zeros(len(enc.instance_types), dtype=bool)
        viable_bool[meta["viable_idx"]] = True
        # per-node routing: capped / limited groups merge too (r5) — the
        # merge check enforces each side's per-node limits on the
        # combined membership; only no_merge jobs (zone anti-affinity)
        # stay out
        if meta["no_merge"]:
            to_record = np.zeros(skel.node_count, dtype=bool)
        elif merge_all and not skel.cost_guard:
            to_record = skel.ok.copy()
        else:
            to_record = skel.ok & skel.underfull
        # records of one job share every per-job array and list (the
        # merge engines replace, never mutate, record entries)
        job_limits = list(meta["per_node_limits"])
        max_per_node = meta["max_per_node"]
        pool, zone = meta["pool"], meta["zone"]
        port_sets = meta.get("pod_port_sets")
        positions, bounds = skel.positions, skel.bounds
        for n in range(skel.node_count):
            pos_slice = positions[bounds[n] : bounds[n + 1]]
            members = idx[pos_slice].tolist()
            if not skel.ok[n]:
                for i in members:
                    result.pod_errors[pods[i].uid] = "packed node has no fitting instance type"
                continue
            if to_record[n]:
                rec = dict(
                    enc=enc,
                    pool=pool,
                    zone=zone,
                    zone_ok=meta["zone_ok"],
                    ct_ok=meta["ct_ok"],
                    viable=viable_bool,
                    usage=skel.usage64[n],
                    members=members,
                    daemon=meta["daemon"],
                    alloc_cap=skel.alloc_cap,
                    merged=meta["merged"],
                    max_per_node=max_per_node,
                    limits=job_limits,
                )
                if port_sets is not None:
                    # the node's reserved ports ride the record so the
                    # merge pass can reject conflicting combinations
                    # (constraint_tensors.ports_conflict)
                    node_ports = sorted(
                        {t for p in pos_slice for t in port_sets[int(p)]}
                    )
                    if node_ports:
                        rec["ports"] = tuple(node_ports)
                if skel.cost_guard:
                    rec["_cost_guard"] = True
                if key is not None:
                    rec["_rkey"] = (key, n)
                records.append(rec)
                continue
            o = int(skel.ok_ord[n])
            result.node_plans.append(
                NodePlan(
                    nodepool_name=pool.nodepool.name,
                    instance_type=enc.instance_types[int(skel.t_global[o])],
                    zone=skel.off_zone[o],
                    capacity_type=skel.off_ct[o],
                    price=float(skel.off_price[o]),
                    pod_indices=members,
                    requirements=meta["merged"],
                    max_pods_per_node=int(max_per_node),
                    node_limits=list(job_limits),
                    _pod_requests=[self._all_requests[i] for i in members],
                )
            )

    def _finalize_job(
        self,
        meta: dict,
        node_ids: np.ndarray,
        node_count: int,
        pods: List[Pod],
        result: SolverResult,
        records: List[dict],
        merge_all: bool = False,
    ) -> None:
        """Uncached finalize (skeleton + emit in one step) — the shape
        tests drive directly; the solve pipeline goes through
        _pack_and_finalize for the memoized path."""
        skel = self._job_skeleton(meta, np.asarray(node_ids), int(node_count))
        self._emit_skeleton(meta, skel, None, pods, result, records, merge_all)

    # ------------------------------------------------------------------

    _MERGE_SCAN_CAP = 64  # K-open bound on the first-fit merge scan

    def _alloc_full(self, enc: EncodedInstanceTypes, daemon: np.ndarray) -> np.ndarray:
        """(T, R_ext) daemon-adjusted allocatable over the whole catalog
        (cached on the encoding, warm across solves)."""
        key = ("alloc", daemon.tobytes())
        cached = enc.runtime_caches.get(key)
        if cached is not None:
            return cached
        alloc = enc.allocatable.astype(np.int64)
        if alloc.shape[1] < daemon.shape[0]:
            alloc = np.concatenate(
                [alloc, np.zeros((alloc.shape[0], daemon.shape[0] - alloc.shape[1]), np.int64)],
                axis=1,
            )
        alloc = np.maximum(alloc - daemon[None, :].astype(np.int64), 0)
        _cache_put(enc, key, alloc)
        return alloc

    def _merge_and_emit(self, records: List[dict], pods: List[Pod], result: SolverResult) -> None:
        """Greedy first-fit merge of underfull planned nodes across
        signature groups. A merge is legal when the nodes share a pool,
        their zone pins agree (pods never change zones, so topology-
        spread counts are untouched), the intersected zone/capacity-type
        masks stay nonempty, and some commonly-viable instance type
        holds the combined load with an available offering.

        Dispatches to the bucketed vector engine (merge.py) unless
        KARPENTER_TPU_MERGE_ENGINE=scalar; both engines share
        ``_merge_pair_exact`` and produce identical merged clusters."""
        if not records:
            return
        import time as _time

        from . import merge as merge_mod

        t0 = _time.perf_counter()
        st = self._merge_stats
        engine = merge_mod.merge_engine()
        # cross-tick merge memo: when every record carries a content
        # identity (its job key + node ordinal), the whole pass is a
        # deterministic function of the identified stream — a hit
        # replays the recorded absorption trails and emitted offerings
        ws = self._warm
        mkey = None
        if ws is not None and all("_rkey" in r for r in records):
            mkey = (
                engine,
                int(self._MERGE_SCAN_CAP),
                tuple(r["_rkey"] for r in records),
            )
            skel = ws.merges.get(mkey, self._cstats)
            if skel is not None:
                with tracer.span("pack.cache.merge_replay", plans=len(skel.clusters)):
                    self._replay_merge(skel, records, pods, result)
                st["merge_engine"] = engine
                st["merge_records"] = st.get("merge_records", 0) + len(records)
                st["merge_pairs_applied"] = (
                    st.get("merge_pairs_applied", 0) + skel.applied
                )
                st["merge_ms"] = (
                    st.get("merge_ms", 0.0) + (_time.perf_counter() - t0) * 1000.0
                )
                return
        applied_before = st.get("merge_pairs_applied", 0)
        records.sort(key=lambda r: -int(r["usage"][0]))
        if engine == "vector":
            merged = merge_mod.merge_records_vector(
                self, records, pods, self._MERGE_SCAN_CAP
            )
        else:
            merged = self._merge_scalar(records, pods)
        trails = self._merge_trails(merged, records) if ws is not None else None
        with tracer.span("pack.merge.emit", plans=len(merged)):
            clusters: Optional[list] = [] if mkey is not None and trails is not None else None
            for ci, m in enumerate(merged):
                trail = trails[ci] if trails is not None else None
                # per-cluster emit memo: the absorption trail is a content
                # address of the folded cluster, so the emitted offering
                # replays even when the surrounding stream changed
                emitted = ws.emits.get(trail, self._cstats) if trail is not None else None
                if emitted is not None:
                    self._emit_from_choice(m, emitted, pods, result)
                else:
                    before = len(result.node_plans)
                    self._emit_record(m, pods, result)
                    if len(result.node_plans) > before:
                        plan = result.node_plans[-1]
                        emitted = (
                            self._type_ordinal(m["enc"], plan.instance_type),
                            plan.zone,
                            plan.capacity_type,
                            plan.price,
                            False,
                        )
                    else:
                        emitted = (-1, None, None, 0.0, True)
                    if trail is not None:
                        # the emitted tuple reads back the plan just
                        # appended to result — an output echo of the
                        # trail-identified fold, not an independent input
                        # analysis: allow-cache-key(result)
                        ws.emits.put(trail, emitted, self._cstats)
                if clusters is not None:
                    if trail is None:
                        clusters = None  # unrecoverable trail: don't memoize
                    else:
                        clusters.append((trail,) + emitted)
        if mkey is not None and clusters is not None:
            # the skeleton stores (a) emitted choices read back from
            # result (output echo, see the emit memo above) and (b) the
            # absorb count from _merge_stats telemetry — both are
            # products of the keyed record stream, not inputs to it
            # analysis: allow-cache-key(result, self._merge_stats)
            ws.merges.put(
                mkey,
                incremental.MergeSkeleton(
                    clusters,
                    st.get("merge_pairs_applied", 0) - applied_before,
                ),
                self._cstats,
            )
        st["merge_engine"] = engine
        st["merge_records"] = st.get("merge_records", 0) + len(records)
        st["merge_ms"] = st.get("merge_ms", 0.0) + (_time.perf_counter() - t0) * 1000.0

    @staticmethod
    def _merge_trails(merged: List[dict], records: List[dict]) -> list:
        """Recover each merged cluster's absorption trail (the record
        identities whose memberships concatenated into it, in first-fit
        order) from the membership runs — no engine instrumentation, so
        the scalar and vector engines both stay capture-free. Clusters
        touching an unidentified record get a None trail (not cached)."""
        by_first = {r["members"][0]: r for r in records if r["members"]}
        trails = []
        for m in merged:
            mem = m["members"]
            trail: list = []
            i = 0
            ok = bool(mem)
            while i < len(mem):
                r = by_first.get(mem[i])
                rkey = r.get("_rkey") if r is not None else None
                if rkey is None:
                    ok = False
                    break
                rl = len(r["members"])
                if mem[i : i + rl] != r["members"]:
                    ok = False
                    break
                trail.append(rkey)
                i += rl
            trails.append(tuple(trail) if ok and trail else None)
        return trails

    def _emit_from_choice(
        self, m: dict, emitted: tuple, pods: List[Pod], result: SolverResult
    ) -> None:
        """Emit one merged cluster from a memoized offering choice —
        exactly the NodePlan (or error set) _emit_record would build for
        this (content-identical) cluster."""
        t, zone, ct, price, failed = emitted
        if failed:
            for i in m["members"]:
                result.pod_errors[pods[i].uid] = (
                    "packed node has no fitting instance type"
                )
            return
        enc = m["enc"]
        result.node_plans.append(
            NodePlan(
                nodepool_name=m["pool"].nodepool.name,
                instance_type=enc.instance_types[t],
                zone=zone,
                capacity_type=ct,
                price=price,
                pod_indices=m["members"],
                requirements=m["merged"],
                max_pods_per_node=int(m.get("max_per_node", 2**31 - 1)),
                node_limits=list(m.get("limits", [])),
                _pod_requests=[self._all_requests[i] for i in m["members"]],
            )
        )

    def _replay_merge(
        self, skel: "incremental.MergeSkeleton", records: List[dict], pods, result
    ) -> None:
        """Re-apply a recorded merge outcome to this tick's (content-
        identical) records: fold memberships/requirements/limits in the
        recorded absorption order and emit the recorded offerings —
        exactly what the engine + _emit_record would recompute."""
        maxint = 2**31 - 1
        by_key = {r["_rkey"]: r for r in records}
        for cluster in skel.clusters:
            trail, emitted = cluster[0], cluster[1:]
            recs = [by_key[k] for k in trail]
            base = recs[0]
            members = list(base["members"])
            merged_req = base["merged"]
            limits = base["limits"]
            mpn = base.get("max_per_node", maxint)
            for r in recs[1:]:
                combined = Requirements(*merged_req.values_list())
                combined.add(*r["merged"].values_list())
                merged_req = combined
                limits = limits + r["limits"]
                mpn = min(mpn, r.get("max_per_node", maxint))
                members.extend(r["members"])
            self._emit_from_choice(
                dict(
                    base,
                    members=members,
                    merged=merged_req,
                    limits=limits,
                    max_per_node=mpn,
                ),
                emitted,
                pods,
                result,
            )

    @staticmethod
    def _type_ordinal(enc: EncodedInstanceTypes, it: InstanceType) -> int:
        table = enc.runtime_caches.get(("type_ord",))
        if table is None:
            table = {id(t): i for i, t in enumerate(enc.instance_types)}
            _cache_put(enc, ("type_ord",), table)
        return table[id(it)]

    def _merge_scalar(self, records: List[dict], pods: List[Pod]) -> List[dict]:
        """Reference merge engine: the pure-Python pairwise first-fit
        loop over pre-sorted records. Kept as the escape hatch and the
        parity oracle for the vector engine (merge.py)."""
        st = self._merge_stats
        screened = 0
        applied = 0
        merged: List[dict] = []
        for r in records:
            placed = False
            for m in merged[: self._MERGE_SCAN_CAP]:
                screened += 1
                if m["enc"] is not r["enc"] or m["pool"] is not r["pool"]:
                    continue
                if m["zone"] is not None and r["zone"] is not None and m["zone"] != r["zone"]:
                    continue
                enc = r["enc"]
                zone = m["zone"] if m["zone"] is not None else r["zone"]
                zone_ok = m["zone_ok"] & r["zone_ok"]
                ct_ok = m["ct_ok"] & r["ct_ok"]
                if not zone_ok.any() or not ct_ok.any():
                    continue
                if zone is not None and not zone_ok[enc.zones.index(zone)]:
                    continue
                viable = m["viable"] & r["viable"]
                if not viable.any():
                    continue
                # the full requirement sets must intersect per key — the
                # mask projections miss custom node-label keys (team=a
                # vs team=b pods can never share a node)
                if m["merged"] is None or r["merged"] is None:
                    continue
                # cheap reject: combined load exceeds even the elementwise
                # max of both sides' viable capacities
                if np.any(
                    m["usage"] + r["usage"] > np.minimum(m["alloc_cap"], r["alloc_cap"])
                ):
                    continue
                if self._merge_pair_exact(
                    m, r, pods, zone=zone, zone_ok=zone_ok, ct_ok=ct_ok, viable=viable
                ):
                    applied += 1
                    placed = True
                    break
            if not placed:
                merged.append(dict(r, members=list(r["members"])))
        st["merge_candidates_screened"] = st.get("merge_candidates_screened", 0) + screened
        st["merge_pairs_applied"] = st.get("merge_pairs_applied", 0) + applied
        return merged

    def _merge_pair_exact(
        self,
        m: dict,
        r: dict,
        pods: List[Pod],
        skip_intersects: bool = False,
        zone=None,
        zone_ok=None,
        ct_ok=None,
        viable=None,
    ) -> bool:
        """Exact tail of one merge-pair check — requirement-set
        intersection, combined-load fits, offering availability on the
        intersected masks, hostname-level limits — then the apply
        (Requirements union, cache carry-over, membership join).
        Shared by the scalar and vector engines so their accept/apply
        semantics cannot drift. Mutates ``m`` and returns True when
        ``r`` was absorbed. Callers have already verified: same
        enc/pool, zone pins agree, intersected zone/ct/viable masks
        nonempty, both merged sets present, and the alloc_cap cheap
        reject. The vector engine's screen resolves intersects exactly
        (interned fingerprint matrix) and passes skip_intersects."""
        enc = r["enc"]
        if zone is None:
            zone = m["zone"] if m["zone"] is not None else r["zone"]
        if zone_ok is None:
            zone_ok = m["zone_ok"] & r["zone_ok"]
        if ct_ok is None:
            ct_ok = m["ct_ok"] & r["ct_ok"]
        if viable is None:
            viable = m["viable"] & r["viable"]
        # host-port guard (ISSUE 12): two nodes whose reserved ports
        # conflict can never fold — exactly the oracle's per-claim
        # HostPortUsage.conflicts check on the combined membership
        m_ports, r_ports = m.get("ports"), r.get("ports")
        if m_ports and r_ports:
            from .constraint_tensors import ports_conflict

            if ports_conflict(m_ports, r_ports):
                return False
        if not skip_intersects:
            ikey = (m["merged"].fingerprint(), r["merged"].fingerprint())
            compat_ok = self._intersects_cache.get(ikey)
            if compat_ok is None:
                compat_ok = m["merged"].intersects(r["merged"]) is None
                self._intersects_cache[ikey] = compat_ok
            if not compat_ok:
                return False
        limits = m.get("limits", []) + r.get("limits", [])
        if limits:
            # every hostname-level constraint of either side must
            # hold on the merged membership (the oracle's per-node
            # count check at placement time); per-side counts are
            # cached so mega-memberships aren't rescanned per pair.
            # Checked FIRST: on cap-dense workloads (ISSUE 12's
            # anti-affinity-dense mix) limit rejects dominate, and this
            # check is pure cached-dict work while the fits/offering
            # checks below reduce over the type axis
            for sel, ns, cap in limits:
                count = self._record_limit_count(
                    m, sel, ns, pods
                ) + self._record_limit_count(r, sel, ns, pods)
                if count > cap:
                    return False
        usage = m["usage"] + r["usage"]
        alloc = self._alloc_full(enc, r["daemon"])
        fits = viable & np.all(usage[None, :] <= alloc, axis=1)
        if not fits.any():
            return False
        zmask = zone_ok
        if zone is not None:
            zmask = np.zeros(len(enc.zones), dtype=bool)
            zmask[enc.zones.index(zone)] = True
        off_ok = enc.offering_avail[:, zmask][:, :, ct_ok].any(axis=(1, 2))
        if not (fits & off_ok).any():
            return False
        # cost guard (solver/backends/lp.py): when either side's
        # partition was chosen by the LP backend for its price, the
        # merged node may not cost more than the two nodes it replaces
        # — the node-count-driven consolidation must not undo the LP's
        # dollar win. FFD-origin records never carry the flag, so the
        # default merge semantics are byte-identical to before.
        merged_price: Optional[float] = None
        if m.get("_cost_guard") or r.get("_cost_guard"):
            pm = np.where(fits & off_ok, _offering_pmin(enc, zmask, ct_ok), np.inf)
            merged_price = float(pm.min())
            if merged_price > self._record_price(m) + self._record_price(r) + 1e-9:
                return False
        combined = Requirements(*m["merged"].values_list())
        combined.add(*r["merged"].values_list())
        # merge the per-selector count caches additively BEFORE the
        # memberships join: keys cached on BOTH sides stay exact (counts
        # are disjoint membership sums); one-sided keys are completed by
        # computing the missing side now — while the sides are still
        # separate — so mega-merges never rescan O(members) later (the
        # sel objects needed ride in _limit_sels)
        m_cache = m.get("_limit_counts") or {}
        r_cache = r.get("_limit_counts") or {}
        shared = m_cache.keys() & r_cache.keys()
        counts = {k: m_cache[k] + r_cache[k] for k in shared}
        if limits:
            sels = {**(r.get("_limit_sels") or {}), **(m.get("_limit_sels") or {})}
            for k in (m_cache.keys() | r_cache.keys()) - shared:
                if k not in sels:
                    continue
                counts[k] = self._record_limit_count(
                    m, sels[k], k[1], pods
                ) + self._record_limit_count(r, sels[k], k[1], pods)
            m["_limit_sels"] = sels
        m.update(
            usage=usage,
            zone=zone,
            zone_ok=zone_ok,
            ct_ok=ct_ok,
            viable=viable,
            merged=combined,
            limits=limits,
            max_per_node=min(
                m.get("max_per_node", 2**31 - 1),
                r.get("max_per_node", 2**31 - 1),
            ),
        )
        m["members"].extend(r["members"])
        m["_limit_counts"] = counts
        if m_ports or r_ports:
            m["ports"] = tuple(sorted(set(m_ports or ()) | set(r_ports or ())))
        if m.get("_cost_guard") or r.get("_cost_guard"):
            m["_cost_guard"] = True
            m["_price"] = merged_price
        else:
            m.pop("_price", None)
        return True

    def _record_price(self, rec: dict) -> float:
        """The price this record would emit at today: cheapest offering
        of the cheapest viable type that holds its usage, under its own
        zone/capacity-type masks (the _emit_record choice). Cached on
        the record dict — the merge guard prices each side once."""
        p = rec.get("_price")
        if p is not None:
            return p
        enc = rec["enc"]
        zmask = rec["zone_ok"]
        if rec["zone"] is not None:
            zmask = np.zeros(len(enc.zones), dtype=bool)
            zmask[enc.zones.index(rec["zone"])] = True
        alloc = self._alloc_full(enc, rec["daemon"])
        fits = rec["viable"] & np.all(rec["usage"][None, :] <= alloc, axis=1)
        prices = np.where(fits, _offering_pmin(enc, zmask, rec["ct_ok"]), np.inf)
        p = float(prices.min()) if prices.size else float("inf")
        rec["_price"] = p
        return p

    def _record_limit_count(self, record: dict, sel, ns: str, pods: List[Pod]) -> int:
        cache = record.setdefault("_limit_counts", {})
        key = (self._sel_fp(sel) if sel is not None else None, ns)
        count = cache.get(key)
        if count is None:
            count = sum(
                1
                for i in record["members"]
                if pods[i].namespace == ns and self._sel_matches(sel, i, pods)
            )
            cache[key] = count
            # the sel object rides along so a future merge can complete
            # a one-sided cache entry without the caller re-supplying it
            record.setdefault("_limit_sels", {})[key] = sel
        return count

    def _emit_record(self, m: dict, pods: List[Pod], result: SolverResult) -> None:
        enc, zone_ok, ct_ok, zone = m["enc"], m["zone_ok"], m["ct_ok"], m["zone"]
        usage = m["usage"]
        alloc = self._alloc_full(enc, m["daemon"])
        fits = m["viable"] & np.all(usage[None, :] <= alloc, axis=1)
        zmask = zone_ok
        if zone is not None:
            zmask = np.zeros(len(enc.zones), dtype=bool)
            zmask[enc.zones.index(zone)] = True
        # per-type cheapest price within the (zone, ct) mask comes from a
        # table cached on the encoding — merged records share few
        # distinct masks, so emit stops re-reducing (T, Z, C) per record.
        # Price ties break on the stable type name rank, not catalog
        # position (see _offering_rank)
        p = np.where(fits, _offering_pmin(enc, zmask, ct_ok), np.inf)
        t = _stable_argmin(p, _type_rank(enc))
        if not np.isfinite(p[t]):
            for i in m["members"]:
                result.pod_errors[pods[i].uid] = "packed node has no fitting instance type"
            return
        offering_zone, offering_ct, offering_price = self._cheapest_offering(
            enc, t, zone_ok, ct_ok, zone
        )
        result.node_plans.append(
            NodePlan(
                nodepool_name=m["pool"].nodepool.name,
                instance_type=enc.instance_types[t],
                zone=offering_zone,
                capacity_type=offering_ct,
                price=offering_price,
                pod_indices=m["members"],
                requirements=m["merged"],
                max_pods_per_node=int(m.get("max_per_node", 2**31 - 1)),
                node_limits=list(m.get("limits", [])),
                _pod_requests=[self._all_requests[i] for i in m["members"]],
            )
        )

    @staticmethod
    def _cheapest_offering(
        enc: EncodedInstanceTypes,
        t: int,
        zone_ok: np.ndarray,
        ct_ok: np.ndarray,
        zone: Optional[str],
    ) -> Tuple[str, str, float]:
        prices = enc.offering_price[t]  # (Z, C)
        mask = np.isfinite(prices) & ct_ok[None, :] & zone_ok[:, None]
        if zone is not None:
            zmask = np.zeros(len(enc.zones), dtype=bool)
            zmask[enc.zones.index(zone)] = True
            mask = mask & zmask[:, None]
        masked = np.where(mask, prices, np.inf)
        flat = _stable_argmin(masked.ravel(), _offering_rank(enc).ravel())
        zi, ci = np.unravel_index(flat, masked.shape)
        return enc.zones[zi], enc.capacity_types[ci], float(masked[zi, ci])

    @staticmethod
    def _cheapest_offering_batch(
        enc: EncodedInstanceTypes,
        types: np.ndarray,
        zone_ok: np.ndarray,
        ct_ok: np.ndarray,
        zone: Optional[str],
    ) -> Tuple[List[str], List[str], np.ndarray]:
        """_cheapest_offering over many nodes' chosen types at once: one
        masked argmin over (N, Z, C). Price ties break on the stable
        lexicographic (zone, capacity-type) rank — the same rule as the
        scalar — never on array position (see _offering_rank)."""
        prices = enc.offering_price[types]  # (N, Z, C)
        mask = np.isfinite(prices) & ct_ok[None, None, :] & zone_ok[None, :, None]
        if zone is not None:
            zmask = np.zeros(len(enc.zones), dtype=bool)
            zmask[enc.zones.index(zone)] = True
            mask = mask & zmask[None, :, None]
        masked = np.where(mask, prices, np.inf).reshape(len(types), -1)
        pmin = masked.min(axis=1)
        rank = _offering_rank(enc).reshape(1, -1)
        tied = masked == pmin[:, None]
        flat = np.where(tied, rank, np.iinfo(np.int64).max).argmin(axis=1)
        zi, ci = np.unravel_index(flat, prices.shape[1:])
        return (
            [enc.zones[z] for z in zi],
            [enc.capacity_types[c] for c in ci],
            masked[np.arange(len(types)), flat],
        )
