"""LP-relaxation pack backend (ISSUE 8 tentpole; optimality tier ISSUE 19).

The pod-signature × instance-offering assignment LP, relaxed to
continuous variables — per pack job, with S the job's distinct request
rows (signatures), T its viable types priced by their cheapest admitted
offering (backends.job_prices):

    min  Σ_t price_t · x_t                       x_t  = nodes of type t
    s.t. Σ_s y_st · count_s · req_sr ≤ x_t · alloc_tr   ∀ t, r
         Σ_t y_st = 1                            ∀ s  (y_st = 0 where a
         x, y ≥ 0                                      signature can't fit t)

Solved on-device as a batched projected ascent on the LP DUAL — resource
shadow prices μ_tr ≥ 0 constrained to each type's price budget
(μ_t · alloc_t ≤ price_t), objective Σ_s count_s · min_t μ_t · req_s.
EVERY dual-feasible μ certifies a lower bound on the cost of ANY
integral plan for the job (weak duality), and the iteration keeps every
iterate feasible by projection, so the bound we report is sound
regardless of convergence; the final bound is re-evaluated on the host
in float64 with a 1−1e−9 safety factor so float32 device arithmetic can
never round it above the true optimum.

The primal decision reuses μ: each signature routes to the type where
its resource bundle is cheapest under the shadow prices (the dual's own
ν-chooser), and the per-type pod sets are then packed by the exact FFD
kernels restricted to that one type's capacity row — the
feasibility-repair pass — so every emitted assignment is feasible by
construction and flows through the unchanged finalize/merge pipeline.
A final cost guard prices BOTH candidates (the LP rounding and the
plain FFD pack) with the same cheapest-fitting-type model the finalize
step uses and keeps the strictly cheaper one: the LP backend can never
emit a plan that prices above FFD's on the same job, never strands a
pod FFD would have scheduled, and on price-flat catalogs it degrades
to FFD exactly (greedy-oracle parity preserved).

The optimality tier (ISSUE 19) closes the gap between that guard and
the certified bound with three mechanisms, all preserving the
invariants above by construction:

- **Primal-dual refinement** (``KARPENTER_TPU_LP_REFINE_ROUNDS``):
  after the repair pass, the dual re-ascends WARM-STARTED against the
  repaired primal's residuals (per-type routed demand over the capacity
  the repair actually opened), re-routes, re-repairs — one batched
  repair dispatch per round. Every re-ascent iterate is projected
  feasible, so each round's host-recertified bound can only TIGHTEN
  (``max`` over rounds), and a round's candidate replaces the incumbent
  only on a strict price improvement with the same scheduled set.
- **Restricted branch-and-bound** (``KARPENTER_TPU_LP_BRANCH_K``):
  the top-k most-fractional signature→type assignments (smallest
  relative μ-cost margin between best and runner-up type) each spawn a
  depth-1 branch forcing the signature onto its runner-up; a branch is
  just another repair pack job, so the surviving frontier coalesces
  into ONE batched dispatch. A branch whose dual bound
  (parent ν-objective + count·Δμ-cost, valid by weak duality for the
  restricted LP) cannot beat the incumbent is pruned without packing —
  counted, spanned (``lp.branch``), never silent.
- **Warm-started duals as a cache plane**: converged dual weights ride
  the relax memo value, the memo is a process-shared plane (every
  LPBackend instance adopts it), and the warmstore persists/restores it
  as the re-witnessed ``lprelax`` snapshot plane — a restored or
  steady-state tick hits the memo and starts at ZERO ascent iterations
  instead of from ``w0 = 1/alloc``. Reuse is memoization, never
  approximation: warm values are exact-key hits, so cache state can
  never change a plan.

Relaxation results ride a content-addressed cross-tick memo
(``lprelax`` LRU, PR-4 discipline): keyed by the request matrix digest,
the capacity table, the price-table fingerprint, the iteration budget,
and (for refinement re-ascents) the stage tag carrying the warm-start
digest — the full read-set of the dual solve, held to the cachesound
rules like every other memo layer.
"""

from __future__ import annotations

import os
import time
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import devicetime, incremental
from ...tracing import deviceplane, tracer
from . import PackBackend, job_prices

_BIG = np.float32(1e12)  # padded/unavailable-type price: finite, never argmin


def _pow2(n: int, floor: int = 8) -> int:
    return max(floor, 1 << max(0, (n - 1)).bit_length())


@deviceplane.observe_jit("lp.dual_ascent", static_names=("iters",))
@partial(jax.jit, static_argnames=("iters",))
def _dual_ascent_kernel(reqs, counts, alloc, prices, valid, w0, iters: int):
    """Batched dual ascent, pure JAX (padded to size classes so compiles
    are reused across jobs).

    reqs (S, R) f32 signature request rows (0 on padding); counts (S,)
    f32 pod multiplicities (0 on padding); alloc (T, R) f32 true
    capacities (0 where the type has none — padding rows are all-0);
    prices (T,) f32 finite (_BIG on padding); valid (T,) bool; w0 (T, R)
    f32 positive starting weights (cold: 1/alloc_safe; warm: a prior
    converged w, optionally residual-scaled — feasibility never depends
    on the start, only convergence speed does).
    → (w (T, R) dual weights, t_star (S,) int32, has_fit (S,) bool).

    μ is parametrized as a per-type weight row scaled onto the price
    budget (μ_t = price_t · w_t / (w_t · alloc_t)) — feasible by
    construction at every step — and the weights move multiplicatively
    toward each type's congested resources (routed demand per unit
    capacity): a multiplicative-weights ascent on the piecewise-linear
    dual."""
    T = alloc.shape[0]
    fit = jnp.all(reqs[:, None, :] <= alloc[None, :, :], axis=-1) & valid[None, :]
    has_fit = jnp.any(fit, axis=1)
    alloc_safe = jnp.maximum(alloc, 1.0)

    def project(w):
        denom = jnp.sum(w * alloc, axis=1, keepdims=True)
        return prices[:, None] * w / jnp.maximum(denom, 1e-6)

    def route_of(mu):
        cost_st = reqs @ mu.T  # (S, T) — $ per pod of signature s on type t
        cost_st = jnp.where(fit, cost_st, _BIG * 1e6)
        return jnp.argmin(cost_st, axis=1).astype(jnp.int32)

    def step(w, k):
        t_star = route_of(project(w))
        route = jax.nn.one_hot(t_star, T, dtype=reqs.dtype) * (
            counts * has_fit.astype(reqs.dtype)
        )[:, None]
        demand = route.T @ reqs  # (T, R) pods routed to t, per resource
        util = demand / alloc_safe
        norm = util / jnp.maximum(util.max(axis=1, keepdims=True), 1e-30)
        lr = 0.5 / jnp.sqrt(k + 1.0)
        return w * (1.0 + lr * norm), None

    w, _ = jax.lax.scan(step, w0, jnp.arange(iters, dtype=reqs.dtype))
    return w, route_of(project(w)), has_fit


def _host_bound(
    w: np.ndarray,
    reqs: np.ndarray,
    counts: np.ndarray,
    alloc: np.ndarray,
    prices: np.ndarray,
) -> float:
    """Re-certify the bound from the returned dual weights in float64:
    project μ onto the price budget with a 1−1e−9 margin (so float
    rounding can never push μ infeasible) and evaluate Σ count·ν — a
    valid lower bound for any feasible μ, independent of the device's
    float32 arithmetic."""
    w64 = np.asarray(w, dtype=np.float64)
    denom = np.maximum((w64 * alloc).sum(axis=1, keepdims=True), 1e-300)
    mu = (prices[:, None] * w64 / denom) * (1.0 - 1e-9)
    cost_st = reqs @ mu.T  # (S, T)
    fit = np.all(reqs[:, None, :] <= alloc[None, :, :], axis=-1)
    cost_st = np.where(fit, cost_st, np.inf)
    nu = cost_st.min(axis=1, initial=np.inf)
    nu = np.where(np.isfinite(nu), nu, 0.0)
    return float((nu * counts).sum())


def _dual_prices(w: np.ndarray, alloc: np.ndarray, prices: np.ndarray) -> np.ndarray:
    """The float64 μ table (T, R) behind ``_host_bound``'s projection —
    the branch stage prices signatures with exactly the certified dual."""
    w64 = np.asarray(w, dtype=np.float64)
    denom = np.maximum((w64 * alloc).sum(axis=1, keepdims=True), 1e-300)
    return (np.asarray(prices, dtype=np.float64)[:, None] * w64 / denom) * (1.0 - 1e-9)


def relax(
    reqs: np.ndarray,  # (S, R) signature rows
    counts: np.ndarray,  # (S,) pod multiplicities
    alloc: np.ndarray,  # (T, R) capacities
    prices: np.ndarray,  # (T,) finite prices (mask infeasible types to _BIG)
    iters: int,
    w0: Optional[np.ndarray] = None,  # (T, R) warm-start weights
) -> Tuple[np.ndarray, np.ndarray, float, np.ndarray]:
    """One padded relaxation solve → (t_star (S,), has_fit (S,), bound,
    w (T, R) converged dual weights). ``bound`` is a certified lower
    bound ($/hr) on any integral plan that serves these pods from these
    types at these prices; ``w`` seeds warm re-ascents."""
    from ..backend import default_backend

    default_backend()  # device boundary: pin/probe before the first jnp op
    S, R = reqs.shape
    T = alloc.shape[0]
    S_pad, T_pad = _pow2(S), _pow2(T)
    reqs_p = np.zeros((S_pad, R), dtype=np.float32)
    reqs_p[:S] = reqs
    counts_p = np.zeros(S_pad, dtype=np.float32)
    counts_p[:S] = counts
    alloc_p = np.zeros((T_pad, R), dtype=np.float32)
    alloc_p[:T] = alloc
    prices_p = np.full(T_pad, _BIG, dtype=np.float32)
    prices_p[:T] = np.minimum(prices, _BIG)
    valid_p = np.zeros(T_pad, dtype=bool)
    valid_p[:T] = np.asarray(prices) < _BIG
    # scale-invariant cold start: w0 = 1/alloc makes every resource axis
    # contribute equally to the price budget (μ0_r = price/(R·alloc_r)),
    # so convergence does not depend on quantization scale (memory is
    # quantized ~1e9 units, pods ~1e3 — uniform weights would park all
    # the initial dual mass on the largest axis). Warm starts override
    # the real rows only; padding rows stay neutral.
    w0_p = 1.0 / np.maximum(alloc_p, 1.0).astype(np.float32)
    if w0 is not None:
        w0_p[:T] = np.maximum(np.asarray(w0, dtype=np.float32), 1e-12)
    deviceplane.record_footprint(
        deviceplane.nbytes_of(reqs_p, counts_p, alloc_p, prices_p, valid_p, w0_p)
    )
    with devicetime.track(phase="lp"):
        devicetime.transfer(
            "h2d", reqs_p, counts_p, alloc_p, prices_p, valid_p, w0_p, phase="lp"
        )
        w, t_star, has_fit = _dual_ascent_kernel(
            jnp.asarray(reqs_p),
            jnp.asarray(counts_p),
            jnp.asarray(alloc_p),
            jnp.asarray(prices_p),
            jnp.asarray(valid_p),
            jnp.asarray(w0_p),
            int(iters),
        )
        # the ONE intended sync of the relax dispatch
        w = np.asarray(w)  # analysis: allow-host-sync
        t_star = np.asarray(t_star)[:S]  # analysis: allow-host-sync
        has_fit = np.asarray(has_fit)[:S]  # analysis: allow-host-sync
    devicetime.transfer("d2h", w, t_star, has_fit, phase="lp")
    real = valid_p[:T]
    bound = _host_bound(
        w[:T][real].astype(np.float64),
        reqs_p[:S].astype(np.float64),
        counts_p[:S].astype(np.float64),
        alloc_p[:T][real].astype(np.float64),
        prices_p[:T][real].astype(np.float64),
    )
    return t_star, has_fit, bound, w[:T]


def dual_bound(
    reqs: np.ndarray, alloc: np.ndarray, prices: np.ndarray, iters: int = 256
) -> float:
    """Standalone relaxation lower bound over raw per-pod request rows
    (deduped to signatures internally) — what plancost uses to report
    the optimality gap for ANY backend's emitted plan."""
    if reqs.shape[0] == 0 or alloc.shape[0] == 0:
        return 0.0
    finite = np.isfinite(np.asarray(prices, dtype=np.float64))
    if not finite.any():
        return 0.0
    uniq, inv = np.unique(np.asarray(reqs), axis=0, return_inverse=True)
    counts = np.bincount(inv, minlength=len(uniq)).astype(np.float64)
    _, _, bound, _ = relax(
        uniq.astype(np.float64),
        counts,
        np.asarray(alloc, dtype=np.float64)[finite],
        np.asarray(prices, dtype=np.float64)[finite],
        iters,
    )
    return bound


def _candidate_cost(
    reqs: np.ndarray,
    node_ids: np.ndarray,
    node_count: int,
    alloc: np.ndarray,
    prices: np.ndarray,
) -> float:
    """Price a candidate partition exactly as the finalize step will:
    per node, the cheapest viable type that holds its load."""
    from ..pack import assign_cheapest_types, node_usage_from_assignment

    if node_count == 0:
        return 0.0
    usage = node_usage_from_assignment(reqs, np.asarray(node_ids), int(node_count))
    chosen = assign_cheapest_types(usage, alloc, prices)
    if np.any(chosen < 0):
        return float("inf")
    return float(prices[chosen].sum())


def _candidate_headroom(
    reqs: np.ndarray,
    node_ids: np.ndarray,
    node_count: int,
    alloc: np.ndarray,
    prices: np.ndarray,
) -> float:
    """Mean free capacity fraction across a candidate's opened nodes —
    the consolidation-headroom term of the Pareto tie-break (plancost
    ``cost_weights``): when two partitions price identically, the one
    leaving more slack consolidates cheaper later."""
    from ..pack import assign_cheapest_types, node_usage_from_assignment

    if node_count == 0:
        return 0.0
    usage = node_usage_from_assignment(reqs, np.asarray(node_ids), int(node_count))
    chosen = assign_cheapest_types(usage, alloc, prices)
    if np.any(chosen < 0):
        return 0.0
    cap = np.maximum(alloc[chosen].astype(np.float64), 1.0)
    frac = 1.0 - usage.astype(np.float64) / cap
    return float(np.clip(frac, 0.0, 1.0).mean())


# the process-shared relax memo (the warm-dual plane, ISSUE 19): every
# LPBackend instance — the `lp` singleton, AutoBackend's inner lane,
# test-local constructions — adopts the first-constructed LRU, so the
# warmstore has exactly one canonical plane to snapshot/restore and a
# warm hit is a warm hit regardless of which facade dispatched the job
_RELAX_PLANE: List[incremental.LRU] = []


def shared_relax_cache() -> Optional[incremental.LRU]:
    """The canonical ``lprelax`` memo (None before any LPBackend)."""
    return _RELAX_PLANE[0] if _RELAX_PLANE else None


def export_relax_plane() -> List[tuple]:
    """Persistable (key, value) rows of the warm-dual plane for the
    warmstore writer. Keys are pure content — reqs digest, capacity
    bytes, price-table bytes, iteration budget, refine-stage tag — and
    values are numpy/float tuples: nothing process-private crosses."""
    cache = shared_relax_cache()
    return [] if cache is None else list(cache.items())


def reset_for_tests() -> None:
    _RELAX_PLANE.clear()


class LPBackend(PackBackend):
    """The LP-relaxation backend behind the ``lp`` switch value."""

    name = "lp"

    def __init__(self) -> None:
        super().__init__()
        self._relax_cache = incremental.LRU("lprelax")
        # adopt the shared plane (see _RELAX_PLANE): the constructor call
        # above stays inline so the cachesound registry sees the plane
        # name; all instances after the first alias the same memo
        if _RELAX_PLANE:
            self._relax_cache = _RELAX_PLANE[0]
        else:
            _RELAX_PLANE.append(self._relax_cache)
        self.last_stats: dict = {}
        # per-job guard outcome of the last pack_jobs call (True where
        # the LP partition won): the solver marks those jobs' merge
        # records cost-guarded
        self.last_job_flags: List[bool] = []
        #: per-round refinement trajectory of the last pack_jobs call:
        #: [{round, bound, cost, improved, ms}] summed over the call's
        #: routed jobs — bound is monotone nondecreasing, cost monotone
        #: nonincreasing by construction (profile_solve prints this)
        self.last_refine_trajectory: List[dict] = []
        #: branch table of the last pack_jobs call: one row per
        #: considered branch {job, sig, count, from_t, to_t, bound,
        #: cost, outcome(pruned|explored|won)}
        self.last_branch_table: List[dict] = []
        #: dual-ascent iterations actually executed by the last
        #: pack_jobs call (0 on a fully warm tick — memo hits re-ascend
        #: nothing; the warm-dual restore tests measure this)
        self.last_ascent_iters: int = 0

    @property
    def iterations(self) -> int:
        """Dual-ascent iteration budget (env-tunable; a component of
        every relax memo key AND of the job token — a budget change is
        a different computation)."""
        try:
            return max(8, int(os.environ.get("KARPENTER_TPU_LP_ITERS", "160")))
        except ValueError:
            return 160

    @property
    def refine_rounds(self) -> int:
        """Primal-dual refinement rounds after the repair pass (0 ⇒ the
        pre-ISSUE-19 single-shot behavior)."""
        try:
            return min(
                8, max(0, int(os.environ.get("KARPENTER_TPU_LP_REFINE_ROUNDS", "2")))
            )
        except ValueError:
            return 2

    @property
    def branch_k(self) -> int:
        """Branch width: the k most-fractional signature→type choices
        branched per job (0 disables branching)."""
        try:
            return min(
                16, max(0, int(os.environ.get("KARPENTER_TPU_LP_BRANCH_K", "2")))
            )
        except ValueError:
            return 2

    @property
    def refine_iters(self) -> int:
        """Re-ascent budget per refinement round: warm-started ascents
        converge from a near-optimal w, so a quarter budget suffices."""
        return max(8, self.iterations // 4)

    def job_token(self) -> tuple:
        # every knob that can change this backend's partition for fixed
        # job inputs — including the Pareto weights, whose tie-break
        # participates in the guard (two weight settings must never
        # alias one skeleton stream)
        from .. import plancost

        return (
            "lp",
            int(self.iterations),
            int(self.refine_rounds),
            int(self.branch_k),
            plancost.weights_token(),
        )

    # -- relaxation memo (cross-tick, content-addressed) ----------------

    def _relax_job(
        self,
        reqs: np.ndarray,
        alloc: np.ndarray,
        prices: np.ndarray,
        iters: int,
        stats=None,
        stage: tuple = (),
        w0: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray, float, np.ndarray]:
        """Signature-level relaxation through the ``lprelax`` memo.
        The key witnesses the dual solve's full read-set: the job's
        sorted request matrix (digest), the viable capacity table, the
        price-table fingerprint, the iteration budget, and — for
        refinement re-ascents — the stage tag carrying the warm-start
        weight digest (w0 is itself a deterministic function of keyed
        inputs, but the digest keeps the witness explicit)."""
        key = (
            incremental.job_digest(reqs),
            alloc.tobytes(),
            prices.tobytes(),
            int(iters),
        ) + tuple(stage)
        hit = self._relax_cache.get(key, stats)
        if hit is not None:
            return hit
        uniq, inv = np.unique(reqs, axis=0, return_inverse=True)
        counts = np.bincount(inv, minlength=len(uniq)).astype(np.float64)
        t_star_u, has_fit_u, bound, w = relax(
            uniq.astype(np.float64),
            counts,
            alloc.astype(np.float64),
            prices.astype(np.float64),
            iters,
            w0=w0,
        )
        self.last_ascent_iters += int(iters)
        value = (t_star_u[inv], has_fit_u[inv], bound, w)
        # reqs IS witnessed — by the collision-safe blake2b job_digest
        # in the key (the read-set rule cannot see through the digest
        # helper); `step` is the dual kernel's scan body, closed over
        # padded views of the same keyed inputs, not an independent one;
        # w0 rides the stage tag as a digest for the same reason
        # analysis: allow-cache-key(reqs,step,w0)
        self._relax_cache.put(key, value, stats)
        return value

    # -- pack ------------------------------------------------------------

    def _repair_groups(self, ji: int, jobs, metas, t_star, has_fit):
        """Per-type repair pack jobs for one routing: [(t, pos)], [job]."""
        reqs, _frontier, mpn = jobs[ji]
        alloc = metas[ji]["alloc"]
        groups, rjobs = [], []
        for t in np.unique(t_star[has_fit]):
            pos = np.flatnonzero(has_fit & (t_star == t))
            groups.append((int(t), pos))
            rjobs.append((reqs[pos], alloc[int(t)][None, :].astype(np.int32), mpn))
        return groups, rjobs

    @staticmethod
    def _assemble(n_pods: int, parts: List[tuple]) -> Tuple[np.ndarray, int]:
        """Stitch per-type repair results into one job-wide partition;
        type-ordinal order keeps node numbering deterministic."""
        node_ids = np.full(n_pods, -1, dtype=np.int32)
        offset = 0
        for t, pos, ids, count in sorted(parts, key=lambda e: e[0]):
            assigned = ids >= 0
            node_ids[pos[assigned]] = ids[assigned] + offset
            offset += count
        return node_ids, offset

    def pack_jobs(
        self, jobs: List[tuple], metas: List[dict], mesh=None, stats=None
    ) -> List[Tuple[np.ndarray, int]]:
        from .. import plancost
        from ..pack import batch_pack

        n = len(jobs)
        refine_rounds = self.refine_rounds
        branch_k = self.branch_k
        st = {
            "jobs": n,
            "lp_won": 0,
            "ffd_kept": 0,
            "ffd_kept_cold": 0,
            "ffd_kept_refined": 0,
            "lp_bound_sum": 0.0,
            "lp_saved_per_hr": 0.0,
            "refine_rounds": 0,
            "refine_accepted": 0,
            "branches_considered": 0,
            "branches_pruned": 0,
            "branches_explored": 0,
            "branches_won": 0,
            "ascent_iters": 0,
        }
        flags = [False] * n
        self.last_refine_trajectory = []
        self.last_branch_table = []
        self.last_ascent_iters = 0
        if not n:
            self.last_stats = st
            self.last_job_flags = flags
            return []
        # the FFD candidate for every job in one batched dispatch — the
        # cost guard needs it anyway, and it is the fallback partition
        ffd_packed = batch_pack(jobs, mesh=mesh)
        t0 = time.perf_counter()
        routes: List[Optional[dict]] = []
        with tracer.span("lp.relax", jobs=n):
            for job, meta in zip(jobs, metas):
                reqs = job[0]
                if reqs.shape[1] != meta["alloc"].shape[1]:
                    # stateful job (appended host-port feature columns,
                    # ISSUE 12): the assignment LP prices the RESOURCE
                    # axes only — keep FFD's partition, whose kernel
                    # enforces the port columns natively
                    routes.append(None)
                    continue
                prices = np.asarray(job_prices(meta), dtype=np.float64)
                finite = np.isfinite(prices)
                if not finite.any() or reqs.shape[0] == 0:
                    routes.append(None)
                    continue
                mpn = int(job[2])
                r_alloc = metas_alloc = meta["alloc"]
                r_reqs = reqs
                if mpn < 2**31 - 1:
                    # job-level pod cap → one synthetic capacity column
                    r_alloc = np.concatenate(
                        [metas_alloc, np.full((metas_alloc.shape[0], 1), mpn, metas_alloc.dtype)],
                        axis=1,
                    )
                    r_reqs = np.concatenate(
                        [reqs, np.ones((reqs.shape[0], 1), reqs.dtype)], axis=1
                    )
                safe_prices = np.where(finite, prices, float(_BIG))
                t_star, has_fit, bound, w = self._relax_job(
                    r_reqs, r_alloc, safe_prices, self.iterations, stats
                )
                routes.append(dict(
                    t_star=t_star, has_fit=has_fit, prices=prices, bound=bound,
                    w=w, r_reqs=r_reqs, r_alloc=r_alloc, safe_prices=safe_prices,
                ))
        # round 0: repair the routed primal per (job, type) group
        repair_meta: List[tuple] = []  # (job index, type ordinal, positions)
        repair_jobs: List[tuple] = []
        with tracer.span("lp.round"):
            for ji, route in enumerate(routes):
                if route is None:
                    continue
                groups, rjobs = self._repair_groups(
                    ji, jobs, metas, route["t_star"], route["has_fit"]
                )
                for (t, pos), rj in zip(groups, rjobs):
                    repair_meta.append((ji, t, pos))
                    repair_jobs.append(rj)
        with tracer.span("lp.repair", jobs=len(repair_jobs)):
            repaired = batch_pack(repair_jobs, mesh=mesh) if repair_jobs else []
        lp_parts: List[list] = [[] for _ in range(n)]
        for (ji, t, pos), (ids, count) in zip(repair_meta, repaired):
            lp_parts[ji].append((t, pos, np.asarray(ids), int(count)))

        # price round 0: per job, the LP candidate vs the FFD fallback.
        # A candidate is admissible only when it schedules exactly FFD's
        # pod set (never strands a pod FFD would have scheduled); the
        # incumbent below is what refinement/branching must strictly beat
        ffd_cost: List[float] = [0.0] * n
        best: List[Optional[dict]] = [None] * n
        for ji in range(n):
            if routes[ji] is None:
                continue
            reqs = jobs[ji][0]
            alloc = metas[ji]["alloc"]
            prices = routes[ji]["prices"]
            ffd_ids = np.asarray(ffd_packed[ji][0])
            ffd_cost[ji] = _candidate_cost(
                reqs, ffd_ids, int(ffd_packed[ji][1]), alloc, prices
            )
            node_ids, count = self._assemble(reqs.shape[0], lp_parts[ji])
            cost = _candidate_cost(reqs, node_ids, count, alloc, prices)
            if np.isfinite(cost) and bool(np.array_equal(node_ids < 0, ffd_ids < 0)):
                best[ji] = {"node_ids": node_ids, "count": count, "cost": cost}
            routes[ji]["parts"] = lp_parts[ji]

        def _incumbent_cost(ji: int) -> float:
            lp_c = best[ji]["cost"] if best[ji] is not None else float("inf")
            return min(lp_c, ffd_cost[ji])

        def _traj_row(rnd: int, improved: int, t_start: float) -> dict:
            routed = [ji for ji in range(n) if routes[ji] is not None]
            return {
                "round": rnd,
                "bound": round(sum(routes[ji]["bound"] for ji in routed), 6),
                "cost": round(sum(_incumbent_cost(ji) for ji in routed), 6),
                "improved": improved,
                "ms": round((time.perf_counter() - t_start) * 1000.0, 3),
            }

        self.last_refine_trajectory.append(_traj_row(0, 0, t0))

        # primal-dual refinement: re-ascend warm-started against the
        # repaired primal's residuals, re-route, re-repair — one batched
        # repair dispatch per round; the bound only tightens (max), the
        # incumbent only improves (strict), so iterating is always safe
        for r in range(1, refine_rounds + 1):
            tr0 = time.perf_counter()
            round_meta: List[tuple] = []
            round_jobs: List[tuple] = []
            with tracer.span("lp.refine", round=r):
                for ji, route in enumerate(routes):
                    if route is None:
                        continue
                    r_reqs, r_alloc = route["r_reqs"], route["r_alloc"]
                    t_star, has_fit = route["t_star"], route["has_fit"]
                    T = r_alloc.shape[0]
                    opened = np.zeros(T, dtype=np.float64)
                    for t, _pos, _ids, count in route["parts"]:
                        opened[t] = count
                    demand = np.zeros(r_alloc.shape, dtype=np.float64)
                    for t in np.unique(t_star[has_fit]):
                        demand[int(t)] = r_reqs[has_fit & (t_star == t)].sum(axis=0)
                    # residual pressure of the REPAIRED primal: routed
                    # demand per unit of the capacity repair actually
                    # opened — types whose integral rounding overshot get
                    # their shadow prices pushed up, re-routing the next
                    # descent away from them
                    util = demand / (
                        np.maximum(opened, 1.0)[:, None]
                        * np.maximum(r_alloc.astype(np.float64), 1.0)
                    )
                    peak = float(util.max())
                    w0 = np.asarray(route["w"], dtype=np.float64) * (
                        1.0 + util / max(peak, 1e-12)
                    )
                    t_star2, has_fit2, bnd, w2 = self._relax_job(
                        r_reqs,
                        r_alloc,
                        route["safe_prices"],
                        self.refine_iters,
                        stats,
                        stage=("refine", r, incremental.job_digest(w0)),
                        w0=w0,
                    )
                    route.update(t_star=t_star2, has_fit=has_fit2, w=w2)
                    # dual-feasible every iterate ⇒ every round certifies;
                    # keep the tightest
                    route["bound"] = max(route["bound"], bnd)
                    groups, rjobs = self._repair_groups(ji, jobs, metas, t_star2, has_fit2)
                    for (t, pos), rj in zip(groups, rjobs):
                        round_meta.append((ji, t, pos))
                        round_jobs.append(rj)
                round_repaired = batch_pack(round_jobs, mesh=mesh) if round_jobs else []
            st["refine_rounds"] = r
            parts_r: List[list] = [[] for _ in range(n)]
            for (ji, t, pos), (ids, count) in zip(round_meta, round_repaired):
                parts_r[ji].append((t, pos, np.asarray(ids), int(count)))
            improved = 0
            for ji in range(n):
                if routes[ji] is None or not parts_r[ji]:
                    continue
                reqs = jobs[ji][0]
                routes[ji]["parts"] = parts_r[ji]
                node_ids, count = self._assemble(reqs.shape[0], parts_r[ji])
                cost = _candidate_cost(
                    reqs, node_ids, count, metas[ji]["alloc"], routes[ji]["prices"]
                )
                ffd_ids = np.asarray(ffd_packed[ji][0])
                admissible = np.isfinite(cost) and bool(
                    np.array_equal(node_ids < 0, ffd_ids < 0)
                )
                lp_c = best[ji]["cost"] if best[ji] is not None else float("inf")
                if admissible and cost < lp_c - 1e-9:
                    best[ji] = {"node_ids": node_ids, "count": count, "cost": cost}
                    improved += 1
            st["refine_accepted"] += improved
            self.last_refine_trajectory.append(_traj_row(r, improved, tr0))

        # restricted branch-and-bound over the top-k most-fractional
        # signature→type choices: each branch forces one signature onto
        # its runner-up type and re-repairs; the surviving frontier packs
        # as ONE batched dispatch, pruned branches never pack at all
        if branch_k > 0:
            frontier_meta: List[tuple] = []  # (branch row index, t, pos)
            frontier_jobs: List[tuple] = []
            branch_rows: List[dict] = []
            branch_state: List[tuple] = []  # (ji,) aligned with branch_rows
            with tracer.span("lp.branch", k=branch_k):
                for ji, route in enumerate(routes):
                    if route is None:
                        continue
                    r_reqs, r_alloc = route["r_reqs"], route["r_alloc"]
                    safe_prices = route["safe_prices"]
                    if r_alloc.shape[0] < 2:
                        continue
                    mu = _dual_prices(route["w"], r_alloc, safe_prices)
                    uniq, inv = np.unique(r_reqs, axis=0, return_inverse=True)
                    counts = np.bincount(inv, minlength=len(uniq)).astype(np.float64)
                    cost_su = uniq @ mu.T
                    fit = np.all(
                        uniq[:, None, :] <= r_alloc[None, :, :].astype(np.float64),
                        axis=-1,
                    ) & (np.asarray(safe_prices) < float(_BIG))[None, :]
                    cost_su = np.where(fit, cost_su, np.inf)
                    order = np.argsort(cost_su, axis=1, kind="stable")
                    rows_idx = np.arange(len(uniq))
                    t1, t2 = order[:, 0], order[:, 1]
                    c1, c2 = cost_su[rows_idx, t1], cost_su[rows_idx, t2]
                    eligible = np.isfinite(c1) & np.isfinite(c2) & (counts > 0)
                    if not eligible.any():
                        continue
                    # fractionality score: the relative margin between
                    # best and runner-up μ-cost — a near-tie is exactly
                    # where continuous routing mass splits and integral
                    # rounding can pick the wrong side
                    margin = np.where(
                        eligible, (c2 - c1) / np.maximum(c1, 1e-12), np.inf
                    )
                    picks = np.argsort(margin, kind="stable")[:branch_k]
                    # the branch bound's parent is the ν-objective of the
                    # SAME μ the branch reprices with (weak duality for
                    # the restricted LP: forcing s→t2 replaces ν_s=c1
                    # with μ_t2·req_s=c2, every other term unchanged)
                    nu = np.where(np.isfinite(c1), c1, 0.0)
                    base = float((nu * counts).sum())
                    for s in picks:
                        s = int(s)
                        if not eligible[s]:
                            continue
                        st["branches_considered"] += 1
                        bbound = float(base + counts[s] * (float(c2[s]) - float(c1[s])))
                        row = {
                            "job": ji,
                            "sig": s,
                            "count": int(counts[s]),
                            "from_t": int(t1[s]),
                            "to_t": int(t2[s]),
                            "bound": round(bbound, 6),
                            "cost": None,
                            "outcome": "pruned",
                        }
                        if bbound >= _incumbent_cost(ji) - 1e-9:
                            st["branches_pruned"] += 1
                            branch_rows.append(row)
                            continue
                        t_star_b = route["t_star"].copy()
                        t_star_b[inv == s] = np.int32(t2[s])
                        bi = len(branch_rows)
                        branch_rows.append(row)
                        branch_state.append(ji)
                        groups, rjobs = self._repair_groups(
                            ji, jobs, metas, t_star_b, route["has_fit"]
                        )
                        for (t, pos), rj in zip(groups, rjobs):
                            frontier_meta.append((bi, t, pos))
                            frontier_jobs.append(rj)
                with tracer.span("lp.branch.pack", jobs=len(frontier_jobs)):
                    frontier_packed = (
                        batch_pack(frontier_jobs, mesh=mesh) if frontier_jobs else []
                    )
                branch_parts: dict = {}
                for (bi, t, pos), (ids, count) in zip(frontier_meta, frontier_packed):
                    branch_parts.setdefault(bi, []).append(
                        (t, pos, np.asarray(ids), int(count))
                    )
                for bi, parts in sorted(branch_parts.items()):
                    row = branch_rows[bi]
                    ji = row["job"]
                    reqs = jobs[ji][0]
                    node_ids, count = self._assemble(reqs.shape[0], parts)
                    cost = _candidate_cost(
                        reqs, node_ids, count, metas[ji]["alloc"], routes[ji]["prices"]
                    )
                    row["cost"] = round(cost, 6) if np.isfinite(cost) else None
                    ffd_ids = np.asarray(ffd_packed[ji][0])
                    admissible = np.isfinite(cost) and bool(
                        np.array_equal(node_ids < 0, ffd_ids < 0)
                    )
                    lp_c = best[ji]["cost"] if best[ji] is not None else float("inf")
                    if admissible and cost < lp_c - 1e-9:
                        best[ji] = {"node_ids": node_ids, "count": count, "cost": cost}
                        row["outcome"] = "won"
                        st["branches_won"] += 1
                    else:
                        row["outcome"] = "explored"
                        st["branches_explored"] += 1
            self.last_branch_table = branch_rows

        # Pareto tie-break (plancost cost_weights): price stays the
        # dominant objective — the guard below is unchanged when the
        # non-price weights are 0 — but when consolidation headroom is
        # weighted and the candidates price IDENTICALLY, prefer the
        # partition with more slack (weights ride job_token, so two
        # settings can never alias one skeleton stream)
        headroom_weight = plancost.cost_weights()["headroom"]

        results: List[Tuple[np.ndarray, int]] = []
        refined_tier = refine_rounds > 0 or branch_k > 0
        with tracer.span("lp.guard"):
            for ji in range(n):
                ffd_ids, ffd_count = ffd_packed[ji]
                ffd_ids = np.asarray(ffd_ids)
                if routes[ji] is None:
                    st["ffd_kept"] += 1
                    st["ffd_kept_cold"] += 1
                    results.append((ffd_ids, int(ffd_count)))
                    continue
                st["lp_bound_sum"] += routes[ji]["bound"]
                reqs = jobs[ji][0]
                alloc = metas[ji]["alloc"]
                prices = routes[ji]["prices"]
                cand = best[ji]
                # strict improvement only, and never at the price of a
                # stranded pod (admissibility above): on price-flat
                # catalogs the LP partition ties and FFD's (parity-
                # gated) plan stands
                win = cand is not None and cand["cost"] < ffd_cost[ji] - 1e-9
                if (
                    not win
                    and cand is not None
                    and headroom_weight > 0.0
                    and abs(cand["cost"] - ffd_cost[ji]) <= 1e-9
                ):
                    lp_head = _candidate_headroom(
                        reqs, cand["node_ids"], cand["count"], alloc, prices
                    )
                    ffd_head = _candidate_headroom(
                        reqs, ffd_ids, int(ffd_count), alloc, prices
                    )
                    win = lp_head > ffd_head + 1e-12
                if win:
                    st["lp_won"] += 1
                    st["lp_saved_per_hr"] += max(0.0, ffd_cost[ji] - cand["cost"])
                    flags[ji] = True
                    results.append((cand["node_ids"], cand["count"]))
                else:
                    st["ffd_kept"] += 1
                    # the satellite split: a cold rejection (no
                    # refinement ran) is a different signal from a plan
                    # FFD still beat AFTER refinement + branching spent
                    # their budgets
                    st["ffd_kept_refined" if refined_tier else "ffd_kept_cold"] += 1
                    results.append((ffd_ids, int(ffd_count)))
        st["ascent_iters"] = int(self.last_ascent_iters)
        st["lp_bound_sum"] = round(st["lp_bound_sum"], 6)
        st["lp_saved_per_hr"] = round(st["lp_saved_per_hr"], 6)
        self.last_stats = st
        self.last_job_flags = flags
        return results
