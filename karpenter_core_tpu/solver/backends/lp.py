"""LP-relaxation pack backend (ISSUE 8 tentpole).

The pod-signature × instance-offering assignment LP, relaxed to
continuous variables — per pack job, with S the job's distinct request
rows (signatures), T its viable types priced by their cheapest admitted
offering (backends.job_prices):

    min  Σ_t price_t · x_t                       x_t  = nodes of type t
    s.t. Σ_s y_st · count_s · req_sr ≤ x_t · alloc_tr   ∀ t, r
         Σ_t y_st = 1                            ∀ s  (y_st = 0 where a
         x, y ≥ 0                                      signature can't fit t)

Solved on-device as a batched projected ascent on the LP DUAL — resource
shadow prices μ_tr ≥ 0 constrained to each type's price budget
(μ_t · alloc_t ≤ price_t), objective Σ_s count_s · min_t μ_t · req_s.
EVERY dual-feasible μ certifies a lower bound on the cost of ANY
integral plan for the job (weak duality), and the iteration keeps every
iterate feasible by projection, so the bound we report is sound
regardless of convergence; the final bound is re-evaluated on the host
in float64 with a 1−1e−9 safety factor so float32 device arithmetic can
never round it above the true optimum.

The primal decision reuses μ: each signature routes to the type where
its resource bundle is cheapest under the shadow prices (the dual's own
ν-chooser), and the per-type pod sets are then packed by the exact FFD
kernels restricted to that one type's capacity row — the
feasibility-repair pass — so every emitted assignment is feasible by
construction and flows through the unchanged finalize/merge pipeline.
A final cost guard prices BOTH candidates (the LP rounding and the
plain FFD pack) with the same cheapest-fitting-type model the finalize
step uses and keeps the strictly cheaper one: the LP backend can never
emit a plan that prices above FFD's on the same job, never strands a
pod FFD would have scheduled, and on price-flat catalogs it degrades
to FFD exactly (greedy-oracle parity preserved).

Relaxation results ride a content-addressed cross-tick memo
(``lprelax`` LRU, PR-4 discipline): keyed by the request matrix digest,
the capacity table, the price-table fingerprint, and the iteration
budget — the full read-set of the dual solve, held to the cachesound
rules like every other memo layer.
"""

from __future__ import annotations

import os
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import devicetime, incremental
from ...tracing import deviceplane, tracer
from . import PackBackend, job_prices

_BIG = np.float32(1e12)  # padded/unavailable-type price: finite, never argmin


def _pow2(n: int, floor: int = 8) -> int:
    return max(floor, 1 << max(0, (n - 1)).bit_length())


@deviceplane.observe_jit("lp.dual_ascent", static_names=("iters",))
@partial(jax.jit, static_argnames=("iters",))
def _dual_ascent_kernel(reqs, counts, alloc, prices, valid, iters: int):
    """Batched dual ascent, pure JAX (padded to size classes so compiles
    are reused across jobs).

    reqs (S, R) f32 signature request rows (0 on padding); counts (S,)
    f32 pod multiplicities (0 on padding); alloc (T, R) f32 true
    capacities (0 where the type has none — padding rows are all-0);
    prices (T,) f32 finite (_BIG on padding); valid (T,) bool.
    → (w (T, R) dual weights, t_star (S,) int32, has_fit (S,) bool).

    μ is parametrized as a per-type weight row scaled onto the price
    budget (μ_t = price_t · w_t / (w_t · alloc_t)) — feasible by
    construction at every step — and the weights move multiplicatively
    toward each type's congested resources (routed demand per unit
    capacity): a multiplicative-weights ascent on the piecewise-linear
    dual."""
    T = alloc.shape[0]
    fit = jnp.all(reqs[:, None, :] <= alloc[None, :, :], axis=-1) & valid[None, :]
    has_fit = jnp.any(fit, axis=1)
    alloc_safe = jnp.maximum(alloc, 1.0)

    def project(w):
        denom = jnp.sum(w * alloc, axis=1, keepdims=True)
        return prices[:, None] * w / jnp.maximum(denom, 1e-6)

    def route_of(mu):
        cost_st = reqs @ mu.T  # (S, T) — $ per pod of signature s on type t
        cost_st = jnp.where(fit, cost_st, _BIG * 1e6)
        return jnp.argmin(cost_st, axis=1).astype(jnp.int32)

    def step(w, k):
        t_star = route_of(project(w))
        route = jax.nn.one_hot(t_star, T, dtype=reqs.dtype) * (
            counts * has_fit.astype(reqs.dtype)
        )[:, None]
        demand = route.T @ reqs  # (T, R) pods routed to t, per resource
        util = demand / alloc_safe
        norm = util / jnp.maximum(util.max(axis=1, keepdims=True), 1e-30)
        lr = 0.5 / jnp.sqrt(k + 1.0)
        return w * (1.0 + lr * norm), None

    # scale-invariant start: w0 = 1/alloc makes every resource axis
    # contribute equally to the price budget (μ0_r = price/(R·alloc_r)),
    # so convergence does not depend on quantization scale (memory is
    # quantized ~1e9 units, pods ~1e3 — uniform weights would park all
    # the initial dual mass on the largest axis)
    w0 = 1.0 / alloc_safe
    w, _ = jax.lax.scan(step, w0, jnp.arange(iters, dtype=reqs.dtype))
    return w, route_of(project(w)), has_fit


def _host_bound(
    w: np.ndarray,
    reqs: np.ndarray,
    counts: np.ndarray,
    alloc: np.ndarray,
    prices: np.ndarray,
) -> float:
    """Re-certify the bound from the returned dual weights in float64:
    project μ onto the price budget with a 1−1e−9 margin (so float
    rounding can never push μ infeasible) and evaluate Σ count·ν — a
    valid lower bound for any feasible μ, independent of the device's
    float32 arithmetic."""
    w64 = np.asarray(w, dtype=np.float64)
    denom = np.maximum((w64 * alloc).sum(axis=1, keepdims=True), 1e-300)
    mu = (prices[:, None] * w64 / denom) * (1.0 - 1e-9)
    cost_st = reqs @ mu.T  # (S, T)
    fit = np.all(reqs[:, None, :] <= alloc[None, :, :], axis=-1)
    cost_st = np.where(fit, cost_st, np.inf)
    nu = cost_st.min(axis=1, initial=np.inf)
    nu = np.where(np.isfinite(nu), nu, 0.0)
    return float((nu * counts).sum())


def relax(
    reqs: np.ndarray,  # (S, R) signature rows
    counts: np.ndarray,  # (S,) pod multiplicities
    alloc: np.ndarray,  # (T, R) capacities
    prices: np.ndarray,  # (T,) finite prices (mask infeasible types to _BIG)
    iters: int,
) -> Tuple[np.ndarray, np.ndarray, float]:
    """One padded relaxation solve → (t_star (S,), has_fit (S,), bound).
    ``bound`` is a certified lower bound ($/hr) on any integral plan
    that serves these pods from these types at these prices."""
    from ..backend import default_backend

    default_backend()  # device boundary: pin/probe before the first jnp op
    S, R = reqs.shape
    T = alloc.shape[0]
    S_pad, T_pad = _pow2(S), _pow2(T)
    reqs_p = np.zeros((S_pad, R), dtype=np.float32)
    reqs_p[:S] = reqs
    counts_p = np.zeros(S_pad, dtype=np.float32)
    counts_p[:S] = counts
    alloc_p = np.zeros((T_pad, R), dtype=np.float32)
    alloc_p[:T] = alloc
    prices_p = np.full(T_pad, _BIG, dtype=np.float32)
    prices_p[:T] = np.minimum(prices, _BIG)
    valid_p = np.zeros(T_pad, dtype=bool)
    valid_p[:T] = np.asarray(prices) < _BIG
    deviceplane.record_footprint(
        deviceplane.nbytes_of(reqs_p, counts_p, alloc_p, prices_p, valid_p)
    )
    with devicetime.track(phase="lp"):
        devicetime.transfer(
            "h2d", reqs_p, counts_p, alloc_p, prices_p, valid_p, phase="lp"
        )
        w, t_star, has_fit = _dual_ascent_kernel(
            jnp.asarray(reqs_p),
            jnp.asarray(counts_p),
            jnp.asarray(alloc_p),
            jnp.asarray(prices_p),
            jnp.asarray(valid_p),
            int(iters),
        )
        # the ONE intended sync of the relax dispatch
        w = np.asarray(w)  # analysis: allow-host-sync
        t_star = np.asarray(t_star)[:S]  # analysis: allow-host-sync
        has_fit = np.asarray(has_fit)[:S]  # analysis: allow-host-sync
    devicetime.transfer("d2h", w, t_star, has_fit, phase="lp")
    real = valid_p[:T]
    bound = _host_bound(
        w[:T][real].astype(np.float64),
        reqs_p[:S].astype(np.float64),
        counts_p[:S].astype(np.float64),
        alloc_p[:T][real].astype(np.float64),
        prices_p[:T][real].astype(np.float64),
    )
    return t_star, has_fit, bound


def dual_bound(
    reqs: np.ndarray, alloc: np.ndarray, prices: np.ndarray, iters: int = 256
) -> float:
    """Standalone relaxation lower bound over raw per-pod request rows
    (deduped to signatures internally) — what plancost uses to report
    the optimality gap for ANY backend's emitted plan."""
    if reqs.shape[0] == 0 or alloc.shape[0] == 0:
        return 0.0
    finite = np.isfinite(np.asarray(prices, dtype=np.float64))
    if not finite.any():
        return 0.0
    uniq, inv = np.unique(np.asarray(reqs), axis=0, return_inverse=True)
    counts = np.bincount(inv, minlength=len(uniq)).astype(np.float64)
    _, _, bound = relax(
        uniq.astype(np.float64),
        counts,
        np.asarray(alloc, dtype=np.float64)[finite],
        np.asarray(prices, dtype=np.float64)[finite],
        iters,
    )
    return bound


def _candidate_cost(
    reqs: np.ndarray,
    node_ids: np.ndarray,
    node_count: int,
    alloc: np.ndarray,
    prices: np.ndarray,
) -> float:
    """Price a candidate partition exactly as the finalize step will:
    per node, the cheapest viable type that holds its load."""
    from ..pack import assign_cheapest_types, node_usage_from_assignment

    if node_count == 0:
        return 0.0
    usage = node_usage_from_assignment(reqs, np.asarray(node_ids), int(node_count))
    chosen = assign_cheapest_types(usage, alloc, prices)
    if np.any(chosen < 0):
        return float("inf")
    return float(prices[chosen].sum())


class LPBackend(PackBackend):
    """The LP-relaxation backend behind the ``lp`` switch value."""

    name = "lp"

    def __init__(self) -> None:
        super().__init__()
        self._relax_cache = incremental.LRU("lprelax")
        self.last_stats: dict = {}
        # per-job guard outcome of the last pack_jobs call (True where
        # the LP partition won): the solver marks those jobs' merge
        # records cost-guarded
        self.last_job_flags: List[bool] = []

    @property
    def iterations(self) -> int:
        """Dual-ascent iteration budget (env-tunable; a component of
        every relax memo key AND of the job token — a budget change is
        a different computation)."""
        try:
            return max(8, int(os.environ.get("KARPENTER_TPU_LP_ITERS", "160")))
        except ValueError:
            return 160

    def job_token(self) -> tuple:
        return ("lp", int(self.iterations))

    # -- relaxation memo (cross-tick, content-addressed) ----------------

    def _relax_job(
        self,
        reqs: np.ndarray,
        alloc: np.ndarray,
        prices: np.ndarray,
        iters: int,
        stats=None,
    ) -> Tuple[np.ndarray, np.ndarray, float]:
        """Signature-level relaxation through the ``lprelax`` memo.
        The key witnesses the dual solve's full read-set: the job's
        sorted request matrix (digest), the viable capacity table, the
        price-table fingerprint, and the iteration budget."""
        key = (
            incremental.job_digest(reqs),
            alloc.tobytes(),
            prices.tobytes(),
            int(iters),
        )
        hit = self._relax_cache.get(key, stats)
        if hit is not None:
            return hit
        uniq, inv = np.unique(reqs, axis=0, return_inverse=True)
        counts = np.bincount(inv, minlength=len(uniq)).astype(np.float64)
        t_star_u, has_fit_u, bound = relax(
            uniq.astype(np.float64),
            counts,
            alloc.astype(np.float64),
            prices.astype(np.float64),
            iters,
        )
        value = (t_star_u[inv], has_fit_u[inv], bound)
        # reqs IS witnessed — by the collision-safe blake2b job_digest
        # in the key (the read-set rule cannot see through the digest
        # helper); `step` is the dual kernel's scan body, closed over
        # padded views of the same keyed inputs, not an independent one
        # analysis: allow-cache-key(reqs,step)
        self._relax_cache.put(key, value, stats)
        return value

    # -- pack ------------------------------------------------------------

    def pack_jobs(
        self, jobs: List[tuple], metas: List[dict], mesh=None, stats=None
    ) -> List[Tuple[np.ndarray, int]]:
        from ..pack import batch_pack

        n = len(jobs)
        st = {
            "jobs": n,
            "lp_won": 0,
            "ffd_kept": 0,
            "lp_bound_sum": 0.0,
            "lp_saved_per_hr": 0.0,
        }
        flags = [False] * n
        if not n:
            self.last_stats = st
            self.last_job_flags = flags
            return []
        # the FFD candidate for every job in one batched dispatch — the
        # cost guard needs it anyway, and it is the fallback partition
        ffd_packed = batch_pack(jobs, mesh=mesh)
        routes: List[Optional[tuple]] = []
        with tracer.span("lp.relax", jobs=n):
            for job, meta in zip(jobs, metas):
                reqs = job[0]
                if reqs.shape[1] != meta["alloc"].shape[1]:
                    # stateful job (appended host-port feature columns,
                    # ISSUE 12): the assignment LP prices the RESOURCE
                    # axes only — keep FFD's partition, whose kernel
                    # enforces the port columns natively
                    routes.append(None)
                    continue
                prices = np.asarray(job_prices(meta), dtype=np.float64)
                finite = np.isfinite(prices)
                if not finite.any() or reqs.shape[0] == 0:
                    routes.append(None)
                    continue
                mpn = int(job[2])
                r_alloc = metas_alloc = meta["alloc"]
                r_reqs = reqs
                if mpn < 2**31 - 1:
                    # job-level pod cap → one synthetic capacity column
                    r_alloc = np.concatenate(
                        [metas_alloc, np.full((metas_alloc.shape[0], 1), mpn, metas_alloc.dtype)],
                        axis=1,
                    )
                    r_reqs = np.concatenate(
                        [reqs, np.ones((reqs.shape[0], 1), reqs.dtype)], axis=1
                    )
                safe_prices = np.where(finite, prices, float(_BIG))
                t_star, has_fit, bound = self._relax_job(
                    r_reqs, r_alloc, safe_prices, self.iterations, stats
                )
                st["lp_bound_sum"] += bound
                routes.append((t_star, has_fit, prices))
        repair_jobs: List[tuple] = []
        repair_meta: List[tuple] = []  # (job index, type ordinal, positions)
        with tracer.span("lp.round"):
            for ji, route in enumerate(routes):
                if route is None:
                    continue
                t_star, has_fit, _prices = route
                reqs, _frontier, mpn = jobs[ji]
                alloc = metas[ji]["alloc"]
                for t in np.unique(t_star[has_fit]):
                    pos = np.flatnonzero(has_fit & (t_star == t))
                    repair_meta.append((ji, int(t), pos))
                    repair_jobs.append(
                        (reqs[pos], alloc[int(t)][None, :].astype(np.int32), mpn)
                    )
        with tracer.span("lp.repair", jobs=len(repair_jobs)):
            repaired = batch_pack(repair_jobs, mesh=mesh) if repair_jobs else []
        lp_parts: List[list] = [[] for _ in range(n)]
        for (ji, t, pos), (ids, count) in zip(repair_meta, repaired):
            lp_parts[ji].append((t, pos, np.asarray(ids), int(count)))
        results: List[Tuple[np.ndarray, int]] = []
        with tracer.span("lp.guard"):
            for ji in range(n):
                ffd_ids, ffd_count = ffd_packed[ji]
                ffd_ids = np.asarray(ffd_ids)
                if routes[ji] is None:
                    st["ffd_kept"] += 1
                    results.append((ffd_ids, int(ffd_count)))
                    continue
                reqs = jobs[ji][0]
                alloc = metas[ji]["alloc"]
                prices = routes[ji][2]
                node_ids = np.full(reqs.shape[0], -1, dtype=np.int32)
                offset = 0
                # type-ordinal order keeps node numbering deterministic
                for t, pos, ids, count in sorted(lp_parts[ji], key=lambda e: e[0]):
                    assigned = ids >= 0
                    node_ids[pos[assigned]] = ids[assigned] + offset
                    offset += count
                lp_cost = _candidate_cost(reqs, node_ids, offset, alloc, prices)
                ffd_cost = _candidate_cost(reqs, ffd_ids, int(ffd_count), alloc, prices)
                # strict improvement only, and never at the price of a
                # stranded pod: on price-flat catalogs the LP partition
                # ties and FFD's (parity-gated) plan stands
                same_sched = bool(np.array_equal(node_ids < 0, ffd_ids < 0))
                if same_sched and lp_cost < ffd_cost - 1e-9:
                    st["lp_won"] += 1
                    st["lp_saved_per_hr"] += ffd_cost - lp_cost
                    flags[ji] = True
                    results.append((node_ids, offset))
                else:
                    st["ffd_kept"] += 1
                    results.append((ffd_ids, int(ffd_count)))
        self.last_stats = st
        self.last_job_flags = flags
        return results
