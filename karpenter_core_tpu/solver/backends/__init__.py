"""Plan-quality pack backends (ISSUE 8 tentpole).

A ``PackBackend`` turns prepared pack jobs — the ``(requests, frontier,
max_per_node)`` tuples plus their finalize metadata — into per-job node
assignments, the contract ``solver._pack_and_finalize`` consumes:

    pack_jobs(jobs, metas, mesh) -> [(node_ids (P,) int32, node_count)]

aligned with ``jobs``. ``node_ids`` indexes the job's size-sorted pod
order (−1 ⇒ unschedulable) exactly like ``pack.batch_pack``; everything
downstream (usage, cheapest-fitting-type choice, offering pricing,
merge, the PR-4 job memo) is backend-agnostic, which is what makes the
backends interchangeable plan-for-plan: a backend only decides the
*partition* of pods into nodes, never the pricing or feasibility rules.

Backends:

- ``ffd``  — the existing vmapped/native first-fit-decreasing engine
  (pack.batch_pack), verbatim. The default, and the node-count parity
  reference.
- ``lp``   — the LP-relaxation backend (backends/lp.py): the
  pod-signature × instance-offering assignment LP solved as a batched
  dual ascent in pure JAX, rounded through an FFD-kernel repair pass,
  cost-guarded so its plan never prices above FFD's on the same job.
  The optimality tier (ISSUE 19) layers warm-started primal-dual
  refinement and restricted branch-and-bound on top — same guard, same
  invariants, tighter plans — with converged duals persisted as the
  warmstore's ``lprelax`` plane.
- ``auto`` — size-calibrated routing (solver/calibrate.py
  ``lp_min_job_work``): jobs big enough to amortize the LP dispatch
  route to ``lp``, the rest stay on ``ffd``.

Selection: ``KARPENTER_TPU_PACK_BACKEND`` (default ``ffd``), read per
solve — the PR-2 engine-switch pattern (cf. KARPENTER_TPU_MERGE_ENGINE,
KARPENTER_TPU_DISRUPT_ENGINE). Each job's memo key carries the backend
token (``job_token``) so switching backends between ticks can never
alias cached skeletons.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

import numpy as np


def job_prices(meta: dict) -> np.ndarray:
    """Per viable type, the cheapest offering price admitted by the
    job's zone/capacity-type requirements (zone-pinned when set) — the
    exact price model ``solver._job_skeleton`` prices packed nodes
    with (solver._job_prices; it lives there so the cachesound
    read-set analysis sees the job memo's price reads inline)."""
    from ..solver import _job_prices

    return _job_prices(meta)


class PackBackend:
    """One pack engine behind the multi-backend seam."""

    name: str = "?"

    def __init__(self) -> None:
        # backends are process-global singletons; concurrent solvers
        # (e.g. the provisioner's shadow parity solve) hold this around
        # pack_jobs + the last_stats/last_job_flags reads so one solve's
        # per-call outputs can't be overwritten by another's mid-read
        self.lock = threading.Lock()

    def job_token(self) -> tuple:
        """The backend-identity component of every job's cross-tick
        memo key: everything about THIS backend's configuration that
        can change its assignment for fixed job inputs. Deliberately
        takes NO job arguments — the job's own content is already in
        the key, and passing it here would widen the key's witness to
        the whole meta dict (masking the cachesound read-set check)."""
        raise NotImplementedError

    #: per-job guard flags of the last pack_jobs call (True ⇒ the job's
    #: partition is cost-guarded downstream); backends that never deviate
    #: from FFD leave it empty
    last_job_flags: List[bool] = []

    def pack_jobs(
        self, jobs: List[tuple], metas: List[dict], mesh=None, stats=None
    ) -> List[Tuple[np.ndarray, int]]:
        """→ [(node_ids, node_count)] aligned with ``jobs``."""
        raise NotImplementedError


class FFDBackend(PackBackend):
    """The existing engine, verbatim: vmapped device scan or the native
    C++ twin (pack.batch_pack decides)."""

    name = "ffd"

    def job_token(self) -> tuple:
        return ("ffd",)

    def pack_jobs(
        self, jobs: List[tuple], metas: List[dict], mesh=None, stats=None
    ) -> List[Tuple[np.ndarray, int]]:
        from ..pack import batch_pack

        self.last_job_flags = [False] * len(jobs)
        return batch_pack(jobs, mesh=mesh)


class AutoBackend(PackBackend):
    """Size-calibrated routing: a job routes to the LP backend when its
    P·T work clears ``calibrate.lp_min_job_work()`` (the LP's fixed
    relax-dispatch cost is only worth paying where a better partition
    can move real dollars), else it stays on FFD."""

    name = "auto"

    def __init__(self) -> None:
        super().__init__()
        from .lp import LPBackend

        self._ffd = FFDBackend()
        self._lp = LPBackend()

    def _route(self, job: tuple, meta: dict) -> PackBackend:
        from ..calibrate import lp_min_job_work

        if job[0].shape[1] != meta["alloc"].shape[1]:
            # stateful port columns (ISSUE 12): FFD enforces them
            # natively; the LP lane would just guard-reject
            return self._ffd
        work = int(job[0].shape[0]) * int(len(meta["viable_idx"]))
        return self._lp if work >= lp_min_job_work() else self._ffd

    def job_token(self) -> tuple:
        # covers BOTH lanes' configuration: the routing threshold decides
        # which lane a job takes (a pure function of job shape, already
        # keyed), and the lp lane's full token (iterations, refinement
        # rounds, branch width, Pareto weights) decides that lane's output
        from ..calibrate import lp_min_job_work

        return ("auto", int(lp_min_job_work())) + self._lp.job_token()

    def pack_jobs(
        self, jobs: List[tuple], metas: List[dict], mesh=None, stats=None
    ) -> List[Tuple[np.ndarray, int]]:
        lanes = [self._route(j, m) for j, m in zip(jobs, metas)]
        results: List[Optional[Tuple[np.ndarray, int]]] = [None] * len(jobs)
        flags = [False] * len(jobs)
        self.last_stats = {}
        for backend in (self._ffd, self._lp):
            idx = [i for i, b in enumerate(lanes) if b is backend]
            if not idx:
                continue
            packed = backend.pack_jobs(
                [jobs[i] for i in idx], [metas[i] for i in idx], mesh, stats
            )
            sub_flags = backend.last_job_flags
            for slot, (i, r) in enumerate(zip(idx, packed)):
                results[i] = r
                if sub_flags:
                    flags[i] = sub_flags[slot]
            if backend is self._lp:
                self.last_stats = dict(backend.last_stats)
        self.last_job_flags = flags
        return results


_BACKENDS: dict = {}

# per-thread backend override (fleet/megasolve.py): each tenant solve
# thread of a batched fleet round installs a coalescing facade here so
# its pack calls join the fleet-wide mega-dispatch instead of going to
# the process-global singleton directly. Thread-local by construction —
# a tenant thread can never see (or clobber) another thread's override.
_TLS = threading.local()


def set_thread_backend(backend: Optional[PackBackend]) -> None:
    """Install (or with None, clear) this thread's backend override."""
    _TLS.override = backend


def get_backend(name: str) -> PackBackend:
    """Process-global backend singletons (the LP backend's relaxation
    memo and compiled kernels are shared across solvers by design —
    they are content-addressed)."""
    b = _BACKENDS.get(name)
    if b is None:
        if name == "ffd":
            b = FFDBackend()
        elif name == "lp":
            from .lp import LPBackend

            b = LPBackend()
        elif name == "auto":
            b = AutoBackend()
        else:
            raise ValueError(f"unknown pack backend: {name!r} (ffd | lp | auto)")
        _BACKENDS[name] = b
    return b


def active_backend() -> PackBackend:
    """The per-solve backend selection (env read each solve, PR-2
    engine-switch pattern). A thread-local override (fleet mega-solve)
    wins over the env. Unknown names fall back to ffd — a typo in an
    env var must degrade, not fail solves."""
    override = getattr(_TLS, "override", None)
    if override is not None:
        return override
    name = os.environ.get("KARPENTER_TPU_PACK_BACKEND", "ffd").strip().lower()
    try:
        return get_backend(name or "ffd")
    except ValueError:
        return get_backend("ffd")


def reset_for_tests() -> None:
    """Drop backend singletons AND the shared warm-dual plane (ISSUE
    19: the lprelax memo is process-shared across LPBackend instances,
    so clearing the singletons alone would leak it into the next test's
    "cold" process — warmstore.simulate_process_death relies on this
    dropping everything a fresh process would not have)."""
    _BACKENDS.clear()
    from . import lp as _lp

    _lp.reset_for_tests()
