"""Jitsig-replay prewarmer (ISSUE 17 tentpole b): replay the restored
``jitsig`` inventory through the live registered functions at boot so a
restored process's first solve raises zero compile events.

PR 16's deviceplane persists every hot-path function's abstract call
signatures through the warmstore as the ``jitsig`` plane — described
there as "the ``warmup_compile_only`` prewarmer's exact shopping list."
This module cashes that in. ``warmup_compile_only(scheduler)`` walks
``deviceplane.replay_targets()`` (signature rows still flagged
``restored`` — imported from a snapshot, not yet replayed by live
traffic), synthesizes abstract-shaped dummy arguments per signature
(``jnp.zeros`` for array leaves, pytree recursion for dict/tuple nodes,
``ast.literal_eval`` of the bounded repr for static config), and calls
each back through its observing wrapper under
``deviceplane.prewarm_scope()``:

- bookkeeping rides the same seam as live traffic — the replayed
  signature's ``restored`` flag clears, so the first *solve* call is a
  plain signature hit raising zero compile events;
- the compiles paid here are attributed ``cause=prewarm_replay``, in
  their own process total, never the solve-attributed counters — and
  with the managed executable cache enabled (``solver.backend``) the
  trace/lower/compile is a persistent-cache hit, not a cold build;
- a row the replay cannot resynthesize (truncated repr, non-literal
  static, sparse positional slots) is counted ``skipped``, and a replay
  call that raises is counted ``errors`` — degraded coverage is a
  number, never silence (PR-7 ``family_capped`` discipline).

Boot order (serving pipeline): restore → prewarm → tick 0 — the prewarm
thread runs this before the plan loop's first tick; fleet
``add_tenant(restore_from=)`` replays on admission. Kill switch:
``KARPENTER_TPU_PREWARM=0`` skips the replay (status ``disabled``).
"""

from __future__ import annotations

import ast
import os
import time
from typing import Any, Dict, List, Optional

from ..tracing import deviceplane

#: most recent replay outcome (stats device block, /debug/device)
_LAST: Optional[dict] = None


def enabled() -> bool:
    return os.environ.get("KARPENTER_TPU_PREWARM", "1") != "0"


def last_result() -> Optional[dict]:
    return dict(_LAST) if _LAST is not None else None


def reset_for_tests() -> None:
    global _LAST
    _LAST = None


class _Unreplayable(Exception):
    """A signature row the replay cannot resynthesize — counted skipped."""


def _synth(node: Any) -> Any:
    """One abstract node back to a concrete dummy: ``("a", shape,
    dtype)`` → zeros of that shape/dtype, dict/tuple nodes recurse,
    static nodes re-literalize their bounded repr."""
    kind = node[0]
    if kind == "a":
        import jax.numpy as jnp

        _, shape, dtype = node
        return jnp.zeros(tuple(shape), dtype=dtype)
    if kind == "d":
        return {k: _synth(v) for k, v in node[1:]}
    if kind == "t":
        return tuple(_synth(v) for v in node[1:])
    if kind == "s":
        r = node[1]
        if r.endswith("..."):
            raise _Unreplayable("truncated static repr")
        try:
            return ast.literal_eval(r)
        except (ValueError, SyntaxError, MemoryError, RecursionError) as e:
            raise _Unreplayable(f"non-literal static repr: {type(e).__name__}")
    raise _Unreplayable(f"unknown node kind {kind!r}")


def _synth_call(key: tuple) -> tuple:
    """One signature key back to (args, kwargs). Positional slots must
    be dense 0..n-1 (they always are for keys recorded by ``_sig_key``,
    but a snapshot row is input, not truth)."""
    arr_part, static_part = key
    slots: Dict[Any, Any] = {}
    for pos, node in list(arr_part) + list(static_part):
        slots[pos] = _synth(node)
    int_keys = sorted(k for k in slots if isinstance(k, int))
    if int_keys != list(range(len(int_keys))):
        raise _Unreplayable("sparse positional slots")
    args = tuple(slots[i] for i in int_keys)
    kwargs = {k: v for k, v in slots.items() if isinstance(k, str)}
    return args, kwargs


def warmup_compile_only(scheduler: Any = None, restored_only: bool = True) -> dict:
    """Replay the jitsig inventory through the live wrappers; return the
    counted outcome. ``scheduler`` (a TPUScheduler, optional) supplies
    the metrics registry the ``prewarm_replay`` compile events are
    pushed to — the solve's finally block never sees them.

    The replay executes each synthesized signature once (results
    discarded): trace + lower + compile land in jax's executable cache —
    a persistent-cache hit when the managed compile-cache plane restored
    clean, a counted cold compile otherwise. Either way the first
    authoritative solve after boot dispatches against warm executables
    and raises zero compile events.
    """
    global _LAST
    t0 = time.perf_counter()
    if not enabled():
        _LAST = {
            "status": "disabled",
            "functions": 0,
            "replayed": 0,
            "skipped": 0,
            "errors": 0,
            "compile_events": 0,
            "prewarm_ms": 0.0,
        }
        return dict(_LAST)
    targets = deviceplane.replay_targets(restored_only=restored_only)
    replayed = skipped = errors = 0
    events: List[dict] = []
    with deviceplane.prewarm_scope() as scope_events:
        for target in targets:
            wrapper = target["wrapper"]
            for key in target["keys"]:
                try:
                    args, kwargs = _synth_call(key)
                except _Unreplayable:
                    skipped += 1
                    continue
                try:
                    out = wrapper(*args, **kwargs)
                    try:
                        import jax

                        jax.block_until_ready(out)
                    except Exception:  # noqa: BLE001 — non-array returns
                        pass
                    replayed += 1
                except Exception:  # noqa: BLE001 — replay must never fail boot
                    errors += 1
        events = list(scope_events)
    metrics = getattr(scheduler, "metrics", None)
    if metrics is not None and hasattr(metrics, "xla_compiles"):
        for ev in events:
            metrics.xla_compiles.inc(1, fn=ev["fn"], cause=ev["cause"])
    _LAST = {
        "status": "ok" if targets else "empty",
        "functions": len(targets),
        "replayed": replayed,
        "skipped": skipped,
        "errors": errors,
        "compile_events": len(events),
        "prewarm_ms": round((time.perf_counter() - t0) * 1000.0, 3),
    }
    return dict(_LAST)
