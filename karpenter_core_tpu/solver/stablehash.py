"""Process-stable content digests for cache keys and fingerprints.

Builtin ``hash()`` is salted per interpreter (PYTHONHASHSEED), and
``id()`` is an address — neither is a content address. Every
fingerprint that two *processes* must agree on (the solve-trace replay
comparisons, the bench cold/warm plan-identity oracle which restarts
the "cold" solver, any future checkpointed warm state) goes through
``stable_hash`` instead: a blake2b digest over a canonical, type-tagged
encoding. The `cache-determinism` analysis rule (analysis/cachesound.py)
flags ``hash()``/``id()``/set-iteration in key construction so new
fingerprints cannot silently regress to salted hashing.

Normalization rules (the part builtin hashing gets wrong silently):

- floats encode as IEEE-754 big-endian bytes with ``-0.0`` folded onto
  ``0.0`` and every NaN folded onto one canonical NaN — equal values
  digest equally, and no float ever round-trips through ``str``;
- ints encode by value (no word-size/overflow dependence), bools are
  tagged distinctly from ints (``True`` must not collide with ``1``
  keying a different computation);
- sets and dicts are REJECTED (TypeError): iteration order is exactly
  the instability this module exists to exclude. Callers sort first —
  ``tuple(sorted(...))`` — which also documents the canonical order at
  the call site.
"""

from __future__ import annotations

import hashlib
import struct

_CANON_NAN = struct.pack(">d", float("nan"))


def _feed(h, value) -> None:
    # bool before int: True is an int subclass but must tag differently
    if value is None:
        h.update(b"N")
    elif value is True:
        h.update(b"T")
    elif value is False:
        h.update(b"F")
    elif isinstance(value, int):
        b = str(value).encode()
        h.update(b"i%d:" % len(b))
        h.update(b)
    elif isinstance(value, float):
        if value != value:  # NaN (any payload) -> one canonical NaN
            h.update(b"f")
            h.update(_CANON_NAN)
        else:
            if value == 0.0:
                value = 0.0  # fold -0.0 onto +0.0
            h.update(b"f")
            h.update(struct.pack(">d", value))
    elif isinstance(value, str):
        b = value.encode("utf-8")
        h.update(b"s%d:" % len(b))
        h.update(b)
    elif isinstance(value, (bytes, bytearray, memoryview)):
        b = bytes(value)
        h.update(b"b%d:" % len(b))
        h.update(b)
    elif isinstance(value, (tuple, list)):
        h.update(b"(%d:" % len(value))
        for item in value:
            _feed(h, item)
        h.update(b")")
    else:
        # sets/dicts/objects: iteration order or default repr would leak
        # process-unstable material into the digest — make the caller
        # normalize (tuple(sorted(...))) so the canonical order is visible
        raise TypeError(
            f"stable_hash: unhashable-canonically type {type(value).__name__}; "
            f"normalize to sorted tuples first"
        )


def feed(h, value) -> None:
    """Stream one canonical scalar/tuple tree into an existing hasher —
    the building block for hot fingerprint loops that digest many small
    values without materializing a nested tuple per call (same encoding,
    same normalization rules as ``stable_hash``)."""
    _feed(h, value)


def stable_hash(value, digest_size: int = 16) -> bytes:
    """128-bit content digest of a canonical scalar/tuple tree. Equal
    trees digest equally in every interpreter; unequal trees collide
    with blake2b probability only."""
    h = hashlib.blake2b(digest_size=digest_size)
    _feed(h, value)
    return h.digest()
