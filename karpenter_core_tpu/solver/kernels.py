"""JAX kernels: signature×type compatibility, offering masks, fits.

The compat kernel is the tensorized ``Intersects`` check
(requirements.go:241): per key, set-intersection nonemptiness is mask
overlap (the OTHER slot makes complement sets exact), with the
both-negative carve-out and missing-key passes. Per-key overlaps are
(S×Vk)·(Vk×T) matmuls — MXU work once S and T are real batch sizes.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .contracts import contract
from .encode import EncodedInstanceTypes, SignaturePoolCompat
from ..tracing import deviceplane


def _compat_example(dims):
    """eval_shape inputs for the dict-pytree compat kernels (see
    analysis/shape_contracts.py): two keys, everything abstract."""
    import jax

    S, T, V = dims("S"), dims("T"), dims("V")
    keys = ("key-a", "key-b")

    def b(shape):
        return jax.ShapeDtypeStruct(shape, np.bool_)

    sig = {"valid": b((S,))}
    tm, th, tn = {}, {}, {}
    for k in keys:
        sig[f"mask:{k}"] = b((S, V))
        sig[f"has:{k}"] = b((S,))
        sig[f"neg:{k}"] = b((S,))
        tm[k] = b((T, V))
        th[k] = b((T,))
        tn[k] = b((T,))
    return (sig, tm, th, tn), {"keys": keys}


def _allowed_example(dims):
    import jax

    (sig, tm, th, tn), kw = _compat_example(dims)
    S, T = dims("S"), dims("T")
    Z, C = dims("Z"), dims("C")

    def b(shape):
        return jax.ShapeDtypeStruct(shape, np.bool_)

    return (sig, tm, th, tn, b((S, Z)), b((S, C)), b((T, Z, C))), kw


def build_compat_inputs(
    compats: List[SignaturePoolCompat], enc: EncodedInstanceTypes, vocab
) -> Dict[str, np.ndarray]:
    """Stack per-signature masks into arrays aligned with the catalog's
    key set. Keys only the pod side has are irrelevant to Intersects
    (missing on the type side ⇒ pass) **except** via the offering check,
    handled separately."""
    S = len(compats)
    arrays: Dict[str, np.ndarray] = {}
    for key, type_mask in enc.key_masks.items():
        Vk = type_mask.shape[1]
        sig_mask = np.zeros((S, Vk), dtype=bool)
        sig_has = np.zeros(S, dtype=bool)
        sig_neg = np.zeros(S, dtype=bool)
        for s, c in enumerate(compats):
            if not c.compatible:
                continue
            if key in c.key_has:
                m = c.key_mask[key]
                sig_mask[s, : m.shape[0]] = m[:Vk] if m.shape[0] >= Vk else np.pad(m, (0, Vk - m.shape[0]))
                sig_has[s] = True
                sig_neg[s] = c.key_neg[key]
        arrays[f"mask:{key}"] = sig_mask
        arrays[f"has:{key}"] = sig_has
        arrays[f"neg:{key}"] = sig_neg
    arrays["valid"] = np.array([c.compatible for c in compats], dtype=bool)
    return arrays


@deviceplane.observe_jit("kernels.compat_kernel", static_names=("keys",))
@contract(None, None, None, None, out="S T", example=_compat_example)
@partial(jax.jit, static_argnames=("keys",))
def compat_kernel(
    sig_arrays: Dict[str, jnp.ndarray],
    type_masks: Dict[str, jnp.ndarray],
    type_has: Dict[str, jnp.ndarray],
    type_neg: Dict[str, jnp.ndarray],
    keys: Tuple[str, ...],
) -> jnp.ndarray:
    """→ (S, T) bool: signature s compatible with instance type t."""
    S = sig_arrays["valid"].shape[0]
    T = next(iter(type_masks.values())).shape[0]
    ok = jnp.broadcast_to(sig_arrays["valid"][:, None], (S, T))
    for key in keys:
        q_mask = sig_arrays[f"mask:{key}"].astype(jnp.float32)  # (S, Vk)
        t_mask = type_masks[key].astype(jnp.float32)  # (T, Vk)
        overlap = (q_mask @ t_mask.T) > 0  # (S, T) — MXU matmul per key
        both_has = sig_arrays[f"has:{key}"][:, None] & type_has[key][None, :]
        both_neg = sig_arrays[f"neg:{key}"][:, None] & type_neg[key][None, :]
        key_ok = (~both_has) | overlap | both_neg
        ok = ok & key_ok
    return ok


@deviceplane.observe_jit("kernels.offering_kernel")
@contract("S Z", "S C", "T Z C", dtypes=("b1", "b1", "b1"), out="S T")
@jax.jit
def offering_kernel(
    zone_ok: jnp.ndarray,  # (S, Z) bool — signature allows zone
    ct_ok: jnp.ndarray,  # (S, C) bool — signature allows capacity type
    avail: jnp.ndarray,  # (T, Z, C) bool
) -> jnp.ndarray:
    """→ (S, T) bool: some available offering satisfies the signature's
    zone/capacity-type requirements jointly (nodeclaim.go:270
    hasOffering)."""
    pair_ok = zone_ok[:, :, None] & ct_ok[:, None, :]  # (S, Z, C)
    return jnp.einsum("szc,tzc->st", pair_ok.astype(jnp.float32), avail.astype(jnp.float32)) > 0


@deviceplane.observe_jit("kernels.allowed_kernel", static_names=("keys",))
@contract(None, None, None, None, "S Z", "S C", "T Z C", out="S T", example=_allowed_example)
@partial(jax.jit, static_argnames=("keys",))
def allowed_kernel(
    sig_arrays: Dict[str, jnp.ndarray],
    type_masks: Dict[str, jnp.ndarray],
    type_has: Dict[str, jnp.ndarray],
    type_neg: Dict[str, jnp.ndarray],
    zone_ok: jnp.ndarray,  # (S, Z)
    ct_ok: jnp.ndarray,  # (S, C)
    avail: jnp.ndarray,  # (T, Z, C)
    keys: Tuple[str, ...],
) -> jnp.ndarray:
    """Fused compat ∧ offering in ONE device dispatch → (S, T) bool.

    The solve's only mandatory device round trip; fusing the two kernels
    halves launch/transfer latency, which dominates at interactive batch
    sizes (device RTT ≫ the matmul time for S ~ tens)."""
    compat = compat_kernel(sig_arrays, type_masks, type_has, type_neg, keys)
    return compat & offering_kernel(zone_ok, ct_ok, avail)


@contract(None, None, None, None, "S Z", "S C", "T Z C", out="S T", eval_shape=False)
def allowed_host(
    sig_arrays: Dict[str, np.ndarray],
    type_masks: Dict[str, np.ndarray],
    type_has: Dict[str, np.ndarray],
    type_neg: Dict[str, np.ndarray],
    zone_ok: np.ndarray,
    ct_ok: np.ndarray,
    avail: np.ndarray,
    keys: Tuple[str, ...],
) -> np.ndarray:
    """Numpy twin of ``allowed_kernel`` for the small-S regime.

    On the tunneled TPU a device dispatch costs ~65 ms at bench widths
    (BENCH_r03 engines: compat_xla_ms 65.2 on-chip vs 2.6 on CPU —
    transfer/dispatch dominated), while this host loop finishes in
    single-digit ms up to S ~ 2k. The solver routes compat here when
    S·T is below ``COMPAT_MIN_DEVICE_WORK`` so the chip only sees
    dispatches big enough to earn their round trip."""
    S = sig_arrays["valid"].shape[0]
    T = avail.shape[0]
    ok = np.repeat(sig_arrays["valid"][:, None], T, axis=1)
    for key in keys:
        overlap = (
            sig_arrays[f"mask:{key}"].astype(np.float32)
            @ type_masks[key].astype(np.float32).T
        ) > 0
        both_has = sig_arrays[f"has:{key}"][:, None] & type_has[key][None, :]
        both_neg = sig_arrays[f"neg:{key}"][:, None] & type_neg[key][None, :]
        ok &= (~both_has) | overlap | both_neg
    # offering: some available (zone, ct) pair allowed by the signature
    pair_ok = (zone_ok[:, :, None] & ct_ok[:, None, :]).reshape(S, -1)
    off = (
        pair_ok.astype(np.float32) @ avail.reshape(T, -1).astype(np.float32).T
    ) > 0
    return ok & off


def zone_ct_masks(compats, enc: EncodedInstanceTypes) -> Tuple[np.ndarray, np.ndarray]:
    """Signature-level zone / capacity-type admissibility from merged
    requirements (missing key ⇒ all allowed)."""
    from ..apis import labels as wk

    S = len(compats)
    zone_ok = np.ones((S, len(enc.zones)), dtype=bool)
    ct_ok = np.ones((S, len(enc.capacity_types)), dtype=bool)
    for s, c in enumerate(compats):
        if not c.compatible or c.merged is None:
            continue
        if c.merged.has(wk.LABEL_TOPOLOGY_ZONE):
            req = c.merged.get_req(wk.LABEL_TOPOLOGY_ZONE)
            zone_ok[s] = [req.has(z) for z in enc.zones]
        if c.merged.has(wk.CAPACITY_TYPE_LABEL_KEY):
            req = c.merged.get_req(wk.CAPACITY_TYPE_LABEL_KEY)
            ct_ok[s] = [req.has(ct) for ct in enc.capacity_types]
    return zone_ok, ct_ok
