"""Tensor-path topology spread: seeded domain counters + closed-form
min-skew water-fill.

The oracle walks pods one at a time, each picking the min-count domain
within ``max_skew`` of the global min (scheduler/topology.py
``_next_domain_spread``; ref topologygroup.go:163-212). For a whole
signature group at once that greedy walk has a closed form:

- Let A be the placement domains (viable offerings / admitting existing
  nodes), D the pod-supported domains (merged requirements ∩ domain
  universe), C the seeded per-domain counts.
- The greedy walk always fills the argmin of A, so final counts are a
  water-fill of P pods over C[A] — except that domains in D \\ A pin
  the global min: once every A domain reaches ``ext = min C[D \\ A]``,
  eligibility caps each A domain at ``ext + max_skew``
  (count+1-min ≤ max_skew, topologygroup.go:177).
- ``min_domains`` (DoNotSchedule only): with fewer than min_domains
  pod-supported domains the global min is treated as 0
  (topologygroup.go:209), i.e. the cap is just ``max_skew``.
- Hostname topologies always see min = 0 (a new node is a new domain,
  topologygroup.go:193-196) — those stay on the per-node-cap path
  (solver.py max_per_node), not here.

So one (Z,)-vector computation replaces P sequential domain picks, and
the remaining per-pod work is a vectorized interleave of pods into
their assigned domains.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..tracing import tracer


def _fill_unbounded(counts: np.ndarray, pods: int) -> np.ndarray:
    """Exact integer water-fill: pour ``pods`` units lowest-first onto
    ``counts`` with no ceiling. Final counts equal the oracle's
    repeated-argmin walk. → per-bin quotas."""
    Z = len(counts)
    order = np.argsort(counts, kind="stable")
    cs = counts[order].astype(np.int64)
    prefix = np.cumsum(cs)
    # number of bins k the water reaches: largest k where raising the
    # first k bins to cs[k] costs ≤ pods
    k = Z
    for j in range(1, Z):
        if cs[j] * j - prefix[j - 1] > pods:
            k = j
            break
    level, rem = divmod(int(prefix[k - 1]) + pods, k)
    q_sorted = np.zeros(Z, dtype=np.int64)
    q_sorted[:k] = level - cs[:k]
    q_sorted[:rem] += 1  # sub-level remainder: one extra on the lowest bins
    quotas = np.zeros(Z, dtype=np.int64)
    quotas[order] = q_sorted
    return quotas


def water_fill(
    counts: np.ndarray, pods: int, ceiling: Optional[int]
) -> Tuple[np.ndarray, int]:
    """Fill ``pods`` units onto ``counts`` lowest-first, never raising a
    bin above ``ceiling`` (None = unbounded). → (quota per bin,
    unplaceable count)."""
    Z = len(counts)
    if Z == 0 or pods <= 0:
        return np.zeros(Z, dtype=np.int64), max(pods, 0)
    c = counts.astype(np.int64)
    if ceiling is None:
        return _fill_unbounded(c, pods), 0
    room = np.clip(int(ceiling) - c, 0, None)
    placeable = int(room.sum())
    if pods >= placeable:
        return room, pods - placeable
    # pods < placeable: unbounded fill, then clamp over-ceiling bins and
    # re-pour their excess onto the rest (≤ Z iterations, each clamps ≥ 1)
    q = _fill_unbounded(c, pods)
    for _ in range(Z):
        over = q > room
        if not over.any():
            break
        excess = int((q - room)[over].sum())
        q[over] = room[over]
        free = ~over & (q < room)
        sub = _fill_unbounded((c + q)[free], excess)
        qf = q[free]
        qf += sub
        q[free] = qf
    return q, pods - int(q.sum())


def spread_quotas(
    place_counts: np.ndarray,  # (Z_A,) seeded counts of placement domains
    ext_min: Optional[int],  # min count over pod-supported \ placement; None if D ⊆ A
    max_skew: int,
    min_domains: Optional[int],
    n_supported: int,  # |D|: pod-supported domains in the universe
    pods: int,
) -> Tuple[np.ndarray, int]:
    """Per-placement-domain quotas for one signature group → (quotas,
    unplaceable). Mirrors topologygroup.go:163-212 (see module
    docstring for the derivation)."""
    if min_domains is not None and n_supported < min_domains:
        ceiling: Optional[int] = max_skew  # global min treated as 0
    elif ext_min is not None:
        ceiling = ext_min + max_skew
    else:
        ceiling = None  # argmin filling alone keeps skew ≤ max_skew
    return water_fill(place_counts, pods, ceiling)


def interleave_by_quota(sorted_idx: np.ndarray, quotas: np.ndarray) -> List[np.ndarray]:
    """Split descending-sorted pod indices into per-domain arrays of the
    given sizes, interleaving ranks across domains (each domain gets a
    similar big/small mix so per-zone packing stays balanced).
    → list of index arrays, aligned with quotas."""
    Z = len(quotas)
    total = int(quotas.sum())
    if total == 0:
        return [sorted_idx[:0] for _ in range(Z)]
    # rank r of the assigned prefix goes to the domain whose (intra-domain
    # slot, domain) pair sorts r-th — a quota-aware round-robin
    zone_of = np.repeat(np.arange(Z), quotas)
    intra = np.concatenate([np.arange(int(q)) for q in quotas])
    assigned_zone = zone_of[np.lexsort((zone_of, intra))]
    prefix = sorted_idx[:total]
    return [prefix[assigned_zone == z] for z in range(Z)]


def seed_counts_for_selector(
    kube_client,
    exemplar,
    topology_key: str,
    label_selector,
    excluded_uids,
) -> Dict[str, int]:
    """Existing matching-pod counts per domain for a pod-affinity /
    anti-affinity term (no node filter — affinity counts every node,
    topologygroup.go:70-76 nil filter)."""
    if kube_client is None:
        return {}
    from ..scheduler.topology import (
        TOPOLOGY_TYPE_POD_AFFINITY,
        TopologyGroup,
        count_matching_pods_by_domain,
    )

    tg = TopologyGroup(
        TOPOLOGY_TYPE_POD_AFFINITY,
        topology_key,
        None,
        {exemplar.namespace},
        label_selector,
        0,
        None,
        set(),
    )
    # the count is a full kube-store pod scan — one of the host-dominated
    # prefilter paths the solve trace attributes (ISSUE 1)
    with tracer.span("topology.seed_counts", key=topology_key):
        return count_matching_pods_by_domain(kube_client, tg, excluded_uids)


def seed_counts_for_constraint(
    kube_client,
    exemplar,
    constraint,
    excluded_uids,
) -> Dict[str, int]:
    """Existing matching-pod counts per domain for one spread constraint
    — the tensor-path analogue of the oracle's seeding
    (scheduler/topology.py Topology._count_domains; ref topology.go:238).
    Reuses the oracle's TopologyGroup so selector/namespace/node-filter
    semantics can't drift between the two paths."""
    if kube_client is None:
        return {}
    from ..scheduler.topology import (
        TOPOLOGY_TYPE_SPREAD,
        TopologyGroup,
        count_matching_pods_by_domain,
    )

    tg = TopologyGroup(
        TOPOLOGY_TYPE_SPREAD,
        constraint.topology_key,
        exemplar,
        {exemplar.namespace},
        constraint.label_selector,
        constraint.max_skew,
        constraint.min_domains,
        set(),
    )
    with tracer.span("topology.seed_counts", key=constraint.topology_key):
        return count_matching_pods_by_domain(kube_client, tg, excluded_uids)
