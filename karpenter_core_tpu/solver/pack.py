"""Bin-packing as a lax.scan: K-open-node first-fit-decreasing.

The oracle packs each pod into the open claim with the fewest pods that
still fits (scheduler.go:247-254), where "fits" means *some* instance
type can hold the claim's accumulated requests. Since a claim's viable
type set is fully determined by its accumulated usage (fits is the only
narrowing for resource-constrained groups), per-node state collapses to
a usage vector — checked against the Pareto frontier of viable
allocatable vectors instead of all T types.

We keep K open slots (K=16 covers FFD's effective back-fill window for
descending pods); when none fits, the slot with the least primary-axis
headroom is closed and a new node opens. Sequential over pods, O(K·F·R)
per step, vectorized inside — exactly the shape lax.scan compiles well.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

INT_INF = np.int32(2**31 - 1)


def pareto_frontier(allocatable: np.ndarray) -> np.ndarray:
    """Maximal points of the viable types' allocatable vectors (F, R).
    A usage vector fits some type iff it fits some frontier point."""
    if len(allocatable) == 0:
        return np.zeros((1, allocatable.shape[1] if allocatable.ndim == 2 else 0), dtype=np.int32)
    pts = np.unique(allocatable, axis=0)
    keep = []
    for i, p in enumerate(pts):
        dominated = False
        for j, q in enumerate(pts):
            if i != j and np.all(q >= p) and np.any(q > p):
                dominated = True
                break
        if not dominated:
            keep.append(p)
    return np.stack(keep).astype(np.int32)


@partial(jax.jit, static_argnames=("k_open",))
def ffd_pack(
    requests: jnp.ndarray,  # (P, R) int32, pre-sorted descending by primary
    frontier: jnp.ndarray,  # (F, R) int32
    max_pods_per_node: jnp.ndarray,  # () int32
    k_open: int = 16,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (node_ids (P,) int32 [-1 ⇒ unschedulable], node_count ())."""
    P, R = requests.shape

    # tie the carry to the inputs so its varying-axis type matches under
    # shard_map (scan requires carry-in/carry-out type equality)
    zero = (requests[0, 0] * 0).astype(jnp.int32)
    init = dict(
        usage=jnp.full((k_open, R), INT_INF, dtype=jnp.int32) + zero,
        count=jnp.zeros(k_open, dtype=jnp.int32) + zero,
        node_id=jnp.full(k_open, -1, dtype=jnp.int32) + zero,
        next_id=zero,
    )

    def step(state, req):
        usage, count, node_id = state["usage"], state["count"], state["node_id"]
        active = node_id >= 0
        # (K, F, R): usage ≤ frontier - req avoids int32 overflow on the
        # INT_INF sentinel rows (frontier and req are both < 2^30)
        remaining = frontier[None, :, :] - req[None, None, :]
        fit = jnp.any(jnp.all(usage[:, None, :] <= remaining, axis=-1), axis=-1)
        fit = fit & active & (count < max_pods_per_node)

        # fresh-node feasibility (guards unschedulable pods)
        fresh_fits = jnp.any(jnp.all(req[None, :] <= frontier, axis=-1))

        # fewest pods first, ties to oldest claim (scheduler.go:247);
        # float order avoids int32 overflow for large per-node counts
        order = jnp.where(
            fit, count.astype(jnp.float32) + node_id.astype(jnp.float32) * 1e-7, jnp.inf
        )
        k_star = jnp.argmin(order)
        any_fit = fit[k_star]

        # eviction target: least primary-resource headroom (future pods are
        # no larger on the primary axis, so this slot is least useful)
        frontier_max = jnp.max(frontier, axis=0)
        headroom = jnp.where(active, frontier_max[0] - usage[:, 0], INT_INF)
        k_evict = jnp.argmin(headroom)
        k_new = jnp.where(jnp.all(active), k_evict, jnp.argmax(~active))

        k_sel = jnp.where(any_fit, k_star, k_new)
        open_new = (~any_fit) & fresh_fits

        new_usage_row = jnp.where(any_fit, usage[k_sel] + req, req)
        new_count_row = jnp.where(any_fit, count[k_sel] + 1, 1)
        new_id_row = jnp.where(any_fit, node_id[k_sel], state["next_id"])

        do_update = any_fit | open_new
        usage = jnp.where(
            do_update, usage.at[k_sel].set(new_usage_row), usage
        )
        count = jnp.where(do_update, count.at[k_sel].set(new_count_row), count)
        node_id = jnp.where(do_update, node_id.at[k_sel].set(new_id_row), node_id)
        next_id = state["next_id"] + jnp.where(open_new, 1, 0).astype(jnp.int32)

        assigned = jnp.where(do_update, new_id_row, -1)
        return (
            dict(usage=usage, count=count, node_id=node_id, next_id=next_id),
            assigned,
        )

    final, node_ids = jax.lax.scan(step, init, requests)
    return node_ids, final["next_id"]


def assign_cheapest_types(
    node_usage: np.ndarray,  # (N, R) int32 summed requests per node
    allocatable: np.ndarray,  # (T, R) int32 (viable types only)
    prices: np.ndarray,  # (T,) f64
) -> np.ndarray:
    """Per node, the cheapest viable type that holds its load — the launch
    decision the fake provider makes (fake/cloudprovider.go:105-110).
    → (N,) int32 index into the viable-type axis, -1 if none fits."""
    fits = np.all(node_usage[:, None, :] <= allocatable[None, :, :], axis=-1)  # (N, T)
    priced = np.where(fits, prices[None, :], np.inf)
    best = np.argmin(priced, axis=1).astype(np.int32)
    best[~fits.any(axis=1)] = -1
    return best


def pad_for_pack(requests: np.ndarray, frontier: np.ndarray) -> Tuple[np.ndarray, np.ndarray, int]:
    """Pad pod and frontier counts to power-of-two buckets so jit compiles
    are reused across groups. Padding pods get requests larger than any
    frontier point → they emit node_id=-1 without touching scan state;
    padding frontier rows are all-zero → never fit (requests include
    pods ≥ 1)."""
    P, R = requests.shape
    P_pad = max(128, 1 << (P - 1).bit_length())
    F_pad = 1 << (len(frontier) - 1).bit_length() if len(frontier) > 1 else 1
    fmax = frontier.max(axis=0)
    if P_pad > P:
        sentinel = np.broadcast_to(fmax + 1, (P_pad - P, R)).astype(np.int32)
        requests = np.concatenate([requests, sentinel])
    if F_pad > len(frontier):
        frontier = np.concatenate(
            [frontier, np.zeros((F_pad - len(frontier), R), dtype=np.int32)]
        )
    return requests, frontier, P


def node_usage_from_assignment(
    requests: np.ndarray, node_ids: np.ndarray, node_count: int
) -> np.ndarray:
    """Segment-sum pod requests by assigned node."""
    usage = np.zeros((node_count, requests.shape[1]), dtype=np.int64)
    valid = node_ids >= 0
    np.add.at(usage, node_ids[valid], requests[valid])
    return usage.astype(np.int32)
