"""Bin-packing as a lax.scan: K-open-node first-fit-decreasing.

The oracle packs each pod into the open claim with the fewest pods that
still fits (scheduler.go:247-254), where "fits" means *some* instance
type can hold the claim's accumulated requests. Since a claim's viable
type set is fully determined by its accumulated usage (fits is the only
narrowing for resource-constrained groups), per-node state collapses to
a usage vector — checked against the Pareto frontier of viable
allocatable vectors instead of all T types.

We keep K open slots (K=16 covers FFD's effective back-fill window for
descending pods); when none fits, the slot with the least primary-axis
headroom is closed and a new node opens. Sequential over pods, O(K·F·R)
per step, vectorized inside — exactly the shape lax.scan compiles well.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from . import devicetime
from .contracts import contract
from ..tracing import deviceplane, tracer
import numpy as np

INT_INF = np.int32(2**31 - 1)

# open-slot bound for the NATIVE packer. At K=16 the eviction heuristic
# costs ~3% extra nodes vs the oracle's keep-everything-open greedy at
# 20k-pod/330-node scale (the r4 parity-gate experiment: 342 vs 331
# nodes; K=1024 reproduces the oracle exactly at no measurable time
# cost — the fit scan early-exits). The DEVICE scan keeps K=16: its
# compiled (K,F,R) per-step state is what a TPU scan can afford, and it
# is the fallback engine only.
# import-time by design: K is a compiled kernel shape (the scan's (K,F,R)
# state), and it rides pack_engine_token so every job-memo key — including
# restored ones — witnesses the boot-time value.
try:  # analysis: allow-knob-inventory(KARPENTER_TPU_K_OPEN — static kernel shape; rides pack_engine_token so memo keys witness it)
    NATIVE_K_OPEN = max(1, int(os.environ.get("KARPENTER_TPU_K_OPEN", "1024")))
except ValueError:
    NATIVE_K_OPEN = 1024


@contract("T R", out="F R", eval_shape=False)
def pareto_frontier(allocatable: np.ndarray) -> np.ndarray:
    """Maximal points of the viable types' allocatable vectors (F, R).
    A usage vector fits some type iff it fits some frontier point.
    Vectorized dominance: one (T, T, R) broadcast instead of a Python
    pairwise loop."""
    if len(allocatable) == 0:
        return np.zeros((1, allocatable.shape[1] if allocatable.ndim == 2 else 0), dtype=np.int32)
    pts = np.unique(allocatable, axis=0)  # unique also sorts — ties deduped
    # incremental scan sorted by total size desc: each point only needs a
    # dominance check against the (small) kept frontier, O(T·F·R) instead
    # of the O(T²·R) pairwise broadcast. The frontier lives in a doubling
    # buffer — rebuilding the kept array per accepted point made the scan
    # O(F²·R) in copies
    order = np.argsort(-pts.sum(axis=1, dtype=np.int64))
    buf = np.empty((8, pts.shape[1]), dtype=pts.dtype)
    n = 0
    for i in order:
        p = pts[i]
        if n and bool(np.any(np.all(buf[:n] >= p, axis=1))):
            continue  # dominated (strictness guaranteed: duplicates removed)
        if n == len(buf):
            buf = np.concatenate([buf, np.empty_like(buf)])
        buf[n] = p
        n += 1
    return buf[:n].astype(np.int32)


@deviceplane.observe_jit("pack.ffd_pack", static_names=("k_open",))
@contract("P R", "F R", "()", out=("P", "()"))
@partial(jax.jit, static_argnames=("k_open",))
def ffd_pack(
    requests: jnp.ndarray,  # (P, R) int32, pre-sorted descending by primary
    frontier: jnp.ndarray,  # (F, R) int32
    max_pods_per_node: jnp.ndarray,  # () int32
    k_open: int = 16,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (node_ids (P,) int32 [-1 ⇒ unschedulable], node_count ())."""
    P, R = requests.shape

    # tie the carry to the inputs so its varying-axis type matches under
    # shard_map (scan requires carry-in/carry-out type equality)
    zero = (requests[0, 0] * 0).astype(jnp.int32)
    init = dict(
        usage=jnp.full((k_open, R), INT_INF, dtype=jnp.int32) + zero,
        count=jnp.zeros(k_open, dtype=jnp.int32) + zero,
        node_id=jnp.full(k_open, -1, dtype=jnp.int32) + zero,
        next_id=zero,
    )

    def step(state, req):
        usage, count, node_id = state["usage"], state["count"], state["node_id"]
        active = node_id >= 0
        # (K, F, R): usage ≤ frontier - req avoids int32 overflow on the
        # INT_INF sentinel rows (frontier and req are both < 2^30)
        remaining = frontier[None, :, :] - req[None, None, :]
        fit = jnp.any(jnp.all(usage[:, None, :] <= remaining, axis=-1), axis=-1)
        fit = fit & active & (count < max_pods_per_node)

        # fresh-node feasibility (guards unschedulable pods)
        fresh_fits = jnp.any(jnp.all(req[None, :] <= frontier, axis=-1))

        # fewest pods first, ties to oldest claim (scheduler.go:247);
        # float order avoids int32 overflow for large per-node counts
        order = jnp.where(
            fit, count.astype(jnp.float32) + node_id.astype(jnp.float32) * 1e-7, jnp.inf
        )
        k_star = jnp.argmin(order)
        any_fit = fit[k_star]

        # eviction target: least primary-resource headroom (future pods are
        # no larger on the primary axis, so this slot is least useful)
        frontier_max = jnp.max(frontier, axis=0)
        headroom = jnp.where(active, frontier_max[0] - usage[:, 0], INT_INF)
        k_evict = jnp.argmin(headroom)
        k_new = jnp.where(jnp.all(active), k_evict, jnp.argmax(~active))

        k_sel = jnp.where(any_fit, k_star, k_new)
        open_new = (~any_fit) & fresh_fits

        new_usage_row = jnp.where(any_fit, usage[k_sel] + req, req)
        new_count_row = jnp.where(any_fit, count[k_sel] + 1, 1)
        new_id_row = jnp.where(any_fit, node_id[k_sel], state["next_id"])

        do_update = any_fit | open_new
        usage = jnp.where(
            do_update, usage.at[k_sel].set(new_usage_row), usage
        )
        count = jnp.where(do_update, count.at[k_sel].set(new_count_row), count)
        node_id = jnp.where(do_update, node_id.at[k_sel].set(new_id_row), node_id)
        next_id = state["next_id"] + jnp.where(open_new, 1, 0).astype(jnp.int32)

        assigned = jnp.where(do_update, new_id_row, -1)
        return (
            dict(usage=usage, count=count, node_id=node_id, next_id=next_id),
            assigned,
        )

    # unroll amortizes scan-machinery overhead over 8 tiny steps
    final, node_ids = jax.lax.scan(step, init, requests, unroll=8)
    return node_ids, final["next_id"]


@deviceplane.observe_jit("pack.pack_existing")
@contract("P R", "P", "S M", "M R", dtypes=("i4", "i4", "b1", "i4"), out=("P", "M R"))
@jax.jit
def pack_existing(
    requests: jnp.ndarray,  # (P, R) int32, pre-sorted descending by primary
    sig_ids: jnp.ndarray,  # (P,) int32
    compat: jnp.ndarray,  # (S, M) bool
    free: jnp.ndarray,  # (M, R) int32 remaining capacity
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """First-fit pods onto existing nodes in fixed node order — the
    reference tries in-flight/real nodes before any new claim
    (scheduler.go:241-246); node order encodes initialized-then-name.
    → (assign (P,) int32 node index or -1, free' (M, R))."""

    def step(free, x):
        req, sig = x
        fits = compat[sig] & jnp.all(free >= req[None, :], axis=1)
        m = jnp.argmax(fits)  # first True in node order
        found = fits[m]
        free = jnp.where(found, free.at[m].add(-req), free)
        return free, jnp.where(found, m.astype(jnp.int32), jnp.int32(-1))

    free, assign = jax.lax.scan(step, free, (requests, sig_ids), unroll=4)
    return assign, free


def run_pack_existing(
    requests: np.ndarray,
    sig_ids: np.ndarray,
    compat: np.ndarray,
    free: np.ndarray,
    engine: str = "auto",
) -> Tuple[np.ndarray, np.ndarray]:
    """Dispatch the existing-node pack: native C++ loop when available
    (sequential scalar work, same split as batch_pack), else the device
    scan. → (assign (P,), remaining free (M, R))."""
    if requests.shape[0] == 0 or free.shape[0] == 0:
        return np.full(requests.shape[0], -1, dtype=np.int32), free
    with tracer.span("pack.existing_dispatch", pods=int(requests.shape[0])):
        return _run_pack_existing(requests, sig_ids, compat, free, engine)


def _run_pack_existing(
    requests: np.ndarray,
    sig_ids: np.ndarray,
    compat: np.ndarray,
    free: np.ndarray,
    engine: str,
) -> Tuple[np.ndarray, np.ndarray]:
    if engine in ("auto", "native"):
        from .. import native

        if native.available():
            free = np.ascontiguousarray(free, dtype=np.int32)
            assign, _ = native.pack_existing_native(requests, sig_ids, compat, free)
            return assign, free
        if engine == "native":
            raise RuntimeError("native packer requested but unavailable")
    from .backend import default_backend

    default_backend()  # device boundary: pin/probe before the first jnp
    # op so a dead TPU plugin costs a bounded fallback, not a hang
    with devicetime.track(phase="existing"):
        devicetime.transfer("h2d", requests, sig_ids, compat, free, phase="existing")
        assign, free_out = pack_existing(
            jnp.asarray(requests),
            jnp.asarray(sig_ids),
            jnp.asarray(compat.astype(bool)),
            jnp.asarray(free),
        )
        # analysis: allow-host-sync — the ONE intended sync of this dispatch
        assign, free_out = np.asarray(assign), np.asarray(free_out)
    devicetime.transfer("d2h", assign, free_out, phase="existing")
    return assign, free_out


@contract("N R", "T R", "T", out="N", eval_shape=False)
def assign_cheapest_types(
    node_usage: np.ndarray,  # (N, R) int32 summed requests per node
    allocatable: np.ndarray,  # (T, R) int32 (viable types only)
    prices: np.ndarray,  # (T,) f64
) -> np.ndarray:
    """Per node, the cheapest viable type that holds its load — the launch
    decision the fake provider makes (fake/cloudprovider.go:105-110).
    → (N,) int32 index into the viable-type axis, -1 if none fits."""
    from .. import native

    if native.available() and node_usage.size and allocatable.size:
        return native.cheapest_types_native(node_usage, allocatable, prices)
    # numpy fallback chunks the node axis: the full (N, T, R) broadcast
    # at consolidation-screen scale (5k nodes x 2k types x 6 resources)
    # would materialize a ~120 MB transient. The block height adapts to
    # the type axis so the live transient stays bounded (~32M elements)
    # at mega-shard scale too (10k types x 1M pods — ISSUE 11: no
    # (P, T, R)-shaped transient past host-RAM limits)
    N = node_usage.shape[0]
    T_, R_ = allocatable.shape
    step = max(1, min(1024, 32_000_000 // max(1, T_ * R_)))
    best = np.empty(N, dtype=np.int32)
    for s in range(0, max(N, 1), step):
        blk = node_usage[s : s + step]
        fits = np.all(blk[:, None, :] <= allocatable[None, :, :], axis=-1)  # (n, T)
        priced = np.where(fits, prices[None, :], np.inf)
        b = np.argmin(priced, axis=1).astype(np.int32)
        b[~fits.any(axis=1)] = -1
        best[s : s + step] = b
    return best


@deviceplane.observe_jit("pack.ffd_pack_batched", static_names=("k_open",))
@contract("G P R", "G F R", "G", out=("G P", "G"))
@partial(jax.jit, static_argnames=("k_open",))
def ffd_pack_batched(
    requests: jnp.ndarray,  # (G, P, R)
    frontiers: jnp.ndarray,  # (G, F, R)
    max_pods: jnp.ndarray,  # (G,)
    k_open: int = 16,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """All groups' packs in one dispatch (one device sync per solve
    instead of one per group)."""
    return jax.vmap(lambda r, f, c: ffd_pack(r, f, c, k_open=k_open))(
        requests, frontiers, max_pods
    )


def _pad_class(p: int) -> int:
    """Scan-length size classes: powers of two up to 4096, then 4096
    multiples — a small job must never inherit the biggest job's scan
    length (scan cost is the padded length, vmap lanes are free)."""
    if p <= 4096:
        return max(128, 1 << (p - 1).bit_length())
    return -(-p // 4096) * 4096


def batch_pack(jobs: list, engine: str = "auto", mesh=None) -> list:
    """Run many (requests, frontier, max_per_node) packs.

    engine="auto" prefers the native C++ packer (an exact semantic twin
    of ffd_pack — the sequential pack tail is CPU work; see native/
    pack.cc) and falls back to few padded, vmapped device calls (one per
    size class). engine="device" forces the TPU scan; engine="native"
    requires the C++ path. With a ``mesh`` (multi-chip: sharding.
    active_mesh), device packing shards the group axis over the mesh
    (SURVEY §5 groups-as-data-parallel mapping) — but the native packer
    still wins in auto mode even multi-chip: the sequential FFD tail is
    host-bound work and the device scan's K=16 eviction costs ~3% nodes
    vs native K=1024 (the r4 parity gate's finding). Each device job's
    padding pods exceed its own frontier max so they emit -1 without
    touching state.
    Returns [(node_ids, node_count)] aligned with jobs."""
    if not jobs:
        return []
    with tracer.span("pack.dispatch", jobs=len(jobs)):
        return _batch_pack(jobs, engine, mesh)


def _batch_pack(jobs: list, engine: str, mesh) -> list:
    if mesh is not None:
        # pod-axis mega jobs (ISSUE 11): a single job at or past the
        # shard threshold chunks its POD axis across the mesh — the
        # chunking decision depends only on (mesh, P, threshold, shard
        # engine), never on native availability, so the partition is
        # deterministic for a fixed configuration (and all of it is
        # job-memo key material: incremental.pack_engine_token)
        from .sharding import shard_min_pods, sharded_pod_pack

        min_pods = shard_min_pods()
        mega = [g for g, j in enumerate(jobs) if j[0].shape[0] >= min_pods]
        if mega:
            results: list = [None] * len(jobs)
            for g in mega:
                reqs, frontier, cap = jobs[g]
                results[g] = sharded_pod_pack(mesh, reqs, frontier, cap)
            rest = [g for g in range(len(jobs)) if results[g] is None]
            if rest:
                sub = _batch_pack([jobs[g] for g in rest], engine, mesh)
                for slot, g in enumerate(rest):
                    results[g] = sub[slot]
            return results
    if mesh is not None and engine in ("device", "sharded"):
        return _batch_pack_sharded(mesh, jobs)
    if engine in ("auto", "native"):
        from .. import native

        if native.available():
            return [
                native.ffd_pack_native(
                    reqs,
                    frontier,
                    int(cap),
                    k_open=max(1, min(NATIVE_K_OPEN, reqs.shape[0])),
                )
                for reqs, frontier, cap in jobs
            ]
        if engine == "native":
            raise RuntimeError("native packer requested but unavailable")
    if mesh is not None:
        # no native packer in this deployment: shard the device scan
        return _batch_pack_sharded(mesh, jobs)
    from .backend import default_backend

    default_backend()  # device boundary (see run_pack_existing)
    F_pad = 1 << max((max(len(j[1]) for j in jobs) - 1).bit_length(), 0)
    # size classes ALSO split on the column count: stateful jobs carry
    # appended host-port feature columns (ISSUE 12), so one solve can
    # hold jobs of different widths — a vmapped batch cannot
    classes: dict = {}
    for g, job in enumerate(jobs):
        classes.setdefault((_pad_class(job[0].shape[0]), job[0].shape[1]), []).append(g)

    results: list = [None] * len(jobs)
    for (p_pad, R), members in classes.items():
        G = len(members)
        requests = np.zeros((G, p_pad, R), dtype=np.int32)
        frontiers = np.zeros((G, F_pad, R), dtype=np.int32)
        caps = np.zeros(G, dtype=np.int32)
        for slot, g in enumerate(members):
            reqs, frontier, cap = jobs[g]
            fmax = frontier.max(axis=0)
            requests[slot, :, :] = fmax + 1  # sentinel: unschedulable padding
            requests[slot, : reqs.shape[0]] = reqs
            frontiers[slot, : len(frontier)] = frontier
            caps[slot] = cap
        deviceplane.record_footprint(deviceplane.nbytes_of(requests, frontiers, caps))
        with devicetime.track(phase="pack"):
            devicetime.transfer("h2d", requests, frontiers, caps, phase="pack")
            node_ids, counts = ffd_pack_batched(
                jnp.asarray(requests), jnp.asarray(frontiers), jnp.asarray(caps)
            )
            # one sync per size class, after the batched dispatch
            node_ids = np.asarray(node_ids)  # analysis: allow-host-sync
            counts = np.asarray(counts)  # analysis: allow-host-sync
        devicetime.transfer("d2h", node_ids, counts, phase="pack")
        for slot, g in enumerate(members):
            results[g] = (node_ids[slot, : jobs[g][0].shape[0]], int(counts[slot]))
    return results


def _batch_pack_sharded(mesh, jobs: list) -> list:
    """Device pack with the group axis sharded over the mesh: pad each
    size class's group count to a multiple of the mesh size (dummy
    groups have zero frontiers, so every pod emits -1 and count stays
    0), run sharding.sharded_batch_pack, slice the padding off."""
    from .sharding import sharded_batch_pack

    D = int(mesh.devices.size)
    F_pad = 1 << max((max(len(j[1]) for j in jobs) - 1).bit_length(), 0)
    classes: dict = {}
    for g, job in enumerate(jobs):
        # split on column count too (stateful port columns, ISSUE 12)
        classes.setdefault((_pad_class(job[0].shape[0]), job[0].shape[1]), []).append(g)

    results: list = [None] * len(jobs)
    for (p_pad, R), members in classes.items():
        G = -(-len(members) // D) * D
        requests = np.ones((G, p_pad, R), dtype=np.int32)
        frontiers = np.zeros((G, F_pad, R), dtype=np.int32)
        caps = np.zeros(G, dtype=np.int32)
        for slot, g in enumerate(members):
            reqs, frontier, cap = jobs[g]
            fmax = frontier.max(axis=0)
            requests[slot, :, :] = fmax + 1  # sentinel: unschedulable padding
            requests[slot, : reqs.shape[0]] = reqs
            frontiers[slot, : len(frontier)] = frontier
            caps[slot] = cap
        deviceplane.record_footprint(deviceplane.nbytes_of(requests, frontiers, caps))
        with devicetime.track(phase="shard"):
            devicetime.transfer("h2d", requests, frontiers, caps, phase="shard")
            node_ids, counts, _fleet = sharded_batch_pack(
                mesh, jnp.asarray(requests), jnp.asarray(frontiers), jnp.asarray(caps)
            )
            # one sync per size class, after the mesh-sharded dispatch
            node_ids = np.asarray(node_ids)  # analysis: allow-host-sync
            counts = np.asarray(counts)  # analysis: allow-host-sync
        devicetime.transfer("d2h", node_ids, counts, phase="shard")
        for slot, g in enumerate(members):
            results[g] = (node_ids[slot, : jobs[g][0].shape[0]], int(counts[slot]))
    return results


def pad_for_pack(requests: np.ndarray, frontier: np.ndarray) -> Tuple[np.ndarray, np.ndarray, int]:
    """Pad pod and frontier counts to power-of-two buckets so jit compiles
    are reused across groups. Padding pods get requests larger than any
    frontier point → they emit node_id=-1 without touching scan state;
    padding frontier rows are all-zero → never fit (requests include
    pods ≥ 1)."""
    P, R = requests.shape
    P_pad = max(128, 1 << (P - 1).bit_length())
    F_pad = 1 << (len(frontier) - 1).bit_length() if len(frontier) > 1 else 1
    fmax = frontier.max(axis=0)
    if P_pad > P:
        sentinel = np.broadcast_to(fmax + 1, (P_pad - P, R)).astype(np.int32)
        requests = np.concatenate([requests, sentinel])
    if F_pad > len(frontier):
        frontier = np.concatenate(
            [frontier, np.zeros((F_pad - len(frontier), R), dtype=np.int32)]
        )
    return requests, frontier, P


@contract("P R", "P", "()", out="N R", eval_shape=False)
def node_usage_from_assignment(
    requests: np.ndarray, node_ids: np.ndarray, node_count: int
) -> np.ndarray:
    """Segment-sum pod requests by assigned node."""
    usage = np.zeros((node_count, requests.shape[1]), dtype=np.int64)
    valid = node_ids >= 0
    np.add.at(usage, node_ids[valid], requests[valid])
    return usage.astype(np.int32)
