"""Vectorized instance-type filtering for the ORACLE path, backed by
the tensor path's cached catalog encodings.

The oracle's hot loop is ``filter_instance_types_by_requirements``
(scheduler/nodeclaim.py; ref nodeclaim.go:245): every pod added to a
claim re-filters the claim's remaining types with per-type Python set
algebra — at the reference benchmark's diverse mix that is millions of
``Intersects``/``fits``/``hasOffering`` calls and ~90% of the solve.
The tensor path already holds the whole catalog as mask tensors
(solver._CATALOG_CACHE); this bridge evaluates the same three
predicates as (T,)-vector numpy ops against those tensors:

- compat: the per-key Intersects mask logic of kernels.compat_kernel,
  for a single signature (the claim's merged requirements);
- fits: RAW-nanos allocatable matrix compare (no quantization — the
  oracle's exact ``resources.fits`` semantics);
- offering: zone/capacity-type-allowed ∧ available over the encoded
  (T, Z, C) offering tensor.

Shared-entry bookkeeping: the first filter call of a claim sees the
pool's FULL type list and registers/refreshes the catalog entry (same
cache the tensor path uses — one encoding serves both); subsequent
calls see shrinking sublists and resolve rows through an identity map
validated per lookup (``entry.catalog[row] is it`` — id() recycling
can never alias).

Bail-outs (return None → caller runs the exact Python loop): Gt/Lt
bounds on a shared key on either side (the both-negative carve-out is
inexact for disjoint ranges), or types that aren't registered.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..apis import labels as wk
from ..scheduling import Requirements

# id(instance_type) → (catalog entry, row); validated by identity on
# every lookup, bounded by the catalog cache (entries keep their
# catalogs alive, so registered ids stay stable while mapped)
_IT_ROWS: Dict[int, tuple] = {}
_IT_ROWS_MAX = 65536
# id(list) → (entry, rows, the list itself): a claim re-filters the
# SAME remaining-list object on every pod add, so row resolution is
# amortized to one identity check instead of an O(T) per-type walk.
# The cached strong ref keeps the list alive, so its id can never be
# recycled onto a different list while mapped.
_LIST_ROWS: Dict[int, tuple] = {}
_LIST_ROWS_MAX = 4096


def refresh(instance_types: List) -> None:
    """Register/refresh the encoding for a pool's full catalog list —
    called once per scheduler build so in-place offering mutations are
    caught by the catalog fingerprint, not rechecked per filter call."""
    if not instance_types:
        return
    from .solver import _CATALOG_CACHE, _CATALOG_LOCK, _catalog_entry

    with _CATALOG_LOCK:
        entry = _catalog_entry(instance_types)
        # prune mappings whose entry fell out of the catalog cache (or
        # was replaced by a fingerprint change), so dead encodings
        # aren't pinned and stale offering tensors are never served
        live = {id(e) for e in _CATALOG_CACHE.values()}
        if len(_IT_ROWS) > _IT_ROWS_MAX:
            _IT_ROWS.clear()
        else:
            stale = [k for k, (e, _) in _IT_ROWS.items() if id(e) not in live]
            for k in stale:
                del _IT_ROWS[k]
        stale_lists = [k for k, v in _LIST_ROWS.items() if id(v[0]) not in live]
        for k in stale_lists:
            del _LIST_ROWS[k]
        for row, it in enumerate(entry.catalog):
            _IT_ROWS[id(it)] = (entry, row)


def _bounded_keys(enc) -> frozenset:
    """Catalog keys carrying Gt/Lt bounds (cached on the encoding)."""
    cached = enc.runtime_caches.get(("bounded_keys",))
    if cached is None:
        from .solver import _cache_put

        cached = frozenset(
            key
            for key, reqs in enc.key_reqs.items()
            if any(
                r.greater_than is not None or r.less_than is not None
                for _, r in reqs
            )
        )
        _cache_put(enc, ("bounded_keys",), cached)
    return cached


_MILLI = 10**6  # nanos per milli-unit
_CLAMP = 1 << 62


def _alloc_milli(enc) -> Tuple[np.ndarray, Dict[str, int], np.ndarray]:
    """(T, R) milli-unit allocatable matrix + name→column map + per-type
    any-negative flag, cached on the encoding. Raw nanos overflow int64
    for large memory quantities; milli units are exact for the
    whole-milli values every real quantity has (capacity floors,
    requests ceil — sub-milli fragments can only make the check
    conservative, mirroring encode.py's quantization convention)."""
    cached = enc.runtime_caches.get(("alloc_milli",))
    if cached is None:
        names = sorted({k for it in enc.instance_types for k in it.allocatable()})
        cols = {n: i for i, n in enumerate(names)}
        mat = np.zeros((len(enc.instance_types), len(names)), dtype=np.int64)
        neg = np.zeros(len(enc.instance_types), dtype=bool)
        for t, it in enumerate(enc.instance_types):
            for k, v in it.allocatable().items():
                # a type with ANY negative allocatable never fits
                neg[t] |= v < 0
                mat[t, cols[k]] = min(max(int(v), 0) // _MILLI, _CLAMP)
        cached = (mat, cols, neg)
        from .solver import _cache_put

        _cache_put(enc, ("alloc_milli",), cached)
    return cached


def register_filtered(parent: List, keep: np.ndarray, remaining: List) -> None:
    """Pre-register the row mapping for a filtered sublist of `parent`.

    A claim commits ``filtered.remaining`` (a NEW list object) after
    every successful add, so without this the next filter call pays an
    O(T) identity-map walk to re-resolve rows — at the diverse mix that
    walk was ~5M id() lookups per solve. The child's rows are just the
    parent's rows masked by `keep`."""
    if len(remaining) < 32:
        return  # fast_filter bails below 32 types: entry would be dead
    cached = _LIST_ROWS.get(id(parent))
    if cached is None or cached[2] is not parent:
        return
    if len(_LIST_ROWS) > _LIST_ROWS_MAX:
        _LIST_ROWS.clear()
        return  # parent mapping gone too; next call re-resolves both
    _LIST_ROWS[id(remaining)] = (cached[0], cached[1][keep], remaining)


def fast_filter(
    instance_types: List, requirements: Requirements, requests: Dict[str, int]
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """→ (compat, fits, offering) bool vectors aligned with
    ``instance_types``, or None when this list/requirement shape isn't
    vectorizable (caller falls back to the exact loop)."""
    # below ~32 types the exact Python loop is cheaper than the per-call
    # mask assembly (config-1 measurement: 10-type catalogs regressed)
    if len(instance_types) < 32:
        return None
    from .encode import _is_neg
    from .solver import _CATALOG_LOCK

    # amortized row resolution: same list object ⇒ same rows
    lkey = id(instance_types)
    cached = _LIST_ROWS.get(lkey)
    if cached is not None and cached[2] is instance_types:
        entry, rows = cached[0], cached[1]
    else:
        # resolve through the identity map; one shared entry required.
        # Unregistered lists BAIL to the exact loop (re-encoding here
        # would thrash the 8-entry catalog cache when more pools are
        # live than it holds) — builder.refresh registers each pool's
        # catalog once per scheduler build.
        first = _IT_ROWS.get(id(instance_types[0]))
        if first is None or first[0].catalog[first[1]] is not instance_types[0]:
            return None
        entry = first[0]
        rows = np.empty(len(instance_types), dtype=np.int64)
        for j, it in enumerate(instance_types):
            hit = _IT_ROWS.get(id(it))
            if hit is None or hit[0] is not entry or entry.catalog[hit[1]] is not it:
                return None
            rows[j] = hit[1]
        if len(_LIST_ROWS) > _LIST_ROWS_MAX:
            _LIST_ROWS.clear()
        _LIST_ROWS[lkey] = (entry, rows, instance_types)
    enc = entry.enc

    with _CATALOG_LOCK:
        bounded = _bounded_keys(enc)
        # pass 1 — bail decisions BEFORE any vocab mutation: interning a
        # novel value and then bailing would leave the shared vocab
        # wider than the cached masks (poisoning later calls)
        for key, req in requirements.items():
            if key not in enc.key_masks:
                continue
            if key in bounded or req.greater_than is not None or req.less_than is not None:
                return None  # inexact both-negative carve-out for ranges
        # pass 2 — intern + collect
        sig_masks: List[tuple] = []
        zone_allowed = None
        ct_allowed = None
        grew = False
        for key, req in requirements.items():
            if key == wk.LABEL_TOPOLOGY_ZONE:
                zone_allowed = np.array([req.has(z) for z in enc.zones], dtype=bool)
            elif key == wk.CAPACITY_TYPE_LABEL_KEY:
                ct_allowed = np.array(
                    [req.has(c) for c in enc.capacity_types], dtype=bool
                )
            if key not in enc.key_masks:
                continue  # type side lacks the key entirely → Intersects passes
            kv = entry.vocab.key_vocab(key)
            before = kv.size
            for v in req.values:
                kv.intern(v)
            grew = grew or kv.size != before
            sig_masks.append((key, req))
        # self-healing width check: extend also when a past caller grew
        # the vocab without extending (belt over the pass-1 ordering)
        if grew or any(
            enc.key_masks[key].shape[1] != entry.vocab.key_vocab(key).size
            for key, _ in sig_masks
        ):
            from .encode import extend_encoded_masks

            extend_encoded_masks(enc, entry.vocab)

        compat = np.ones(len(rows), dtype=bool)
        for key, req in sig_masks:
            kv = entry.vocab.key_vocab(key)
            smask = entry.vocab.encode_mask(req, kv.size)
            tmask = enc.key_masks[key][rows]
            overlap = (tmask & smask[None, :]).any(axis=1)
            both_neg = enc.key_neg[key][rows] & _is_neg(req)
            # sig side has the key by construction; type side may not
            compat &= (~enc.key_has[key][rows]) | overlap | both_neg

    # fits: milli-unit compare over the request's keys only (ceil side)
    mat, cols, neg = _alloc_milli(enc)
    fits = ~neg[rows]
    for k, v in requests.items():
        if v <= 0:
            continue
        col = cols.get(k)
        if col is None:
            fits[:] = False
            break
        fits &= mat[rows, col] >= min(-(-int(v) // _MILLI), _CLAMP)

    # offering: some available (zone, ct) pair the requirements allow
    avail = enc.offering_avail[rows]
    if zone_allowed is not None:
        avail = avail & zone_allowed[None, :, None]
    if ct_allowed is not None:
        avail = avail & ct_allowed[None, None, :]
    offering = avail.any(axis=(1, 2))

    return compat, fits, offering
