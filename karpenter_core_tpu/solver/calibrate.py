"""On-device engine-policy calibration (VERDICT r4 weak #2: the compat
routing threshold baked in the tunneled chip's ~65 ms dispatch floor; a
locally-attached chip's floor is orders of magnitude lower, so the
policy must be measured on the chip actually serving the process).

``calibration()`` measures, once per process:

- ``host_ns_per_unit``  — the numpy compat twin's cost per S·T work
  unit (kernels.allowed_host on a bench-shaped micro-run)
- ``dispatch_floor_ms`` — min round-trip of a tiny fused compat kernel
  on the resolved device (dispatch/transfer dominated)

and derives ``compat_min_device_work`` = the S·T work where the host
twin's time crosses the device's fixed dispatch cost — below it compat
routes to the host twin, above it to the chip. The
KARPENTER_TPU_COMPAT_MIN_WORK env var still force-overrides.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

# sane clamp: never route truly tiny work to the device, never hold
# bench-scale work on the host (2^18 ≈ 128×2k, 2^26 ≈ 32k×2k)
_MIN_THRESHOLD = 1 << 18
_MAX_THRESHOLD = 1 << 26
_STATIC_DEFAULT = 1 << 24  # r4's tunnel-calibrated fallback

_CAL: Optional[dict] = None


def _compat_inputs(S: int, T: int, rng):
    keys = ("zone", "arch")
    sig_arrays = {"valid": np.ones(S, dtype=bool)}
    type_masks, type_has, type_neg = {}, {}, {}
    for key, vk in (("zone", 64), ("arch", 8)):
        sig_arrays[f"mask:{key}"] = rng.rand(S, vk) < 0.3
        sig_arrays[f"has:{key}"] = rng.rand(S) < 0.8
        sig_arrays[f"neg:{key}"] = np.zeros(S, dtype=bool)
        type_masks[key] = rng.rand(T, vk) < 0.3
        type_has[key] = np.ones(T, dtype=bool)
        type_neg[key] = np.zeros(T, dtype=bool)
    zone_ok = np.ones((S, 6), dtype=bool)
    ct_ok = np.ones((S, 2), dtype=bool)
    avail = np.ones((T, 6, 2), dtype=bool)
    return keys, sig_arrays, type_masks, type_has, type_neg, zone_ok, ct_ok, avail


def calibration(force: bool = False) -> dict:
    """Measure (cached per process). Cheap on CPU fallback (one host
    micro-run); on a live chip adds one tiny-kernel compile (cached by
    the persistent compilation cache) + a handful of dispatches."""
    global _CAL
    if _CAL is not None and not force:
        return _CAL
    from . import backend as backend_mod
    from .kernels import allowed_host, allowed_kernel

    bk = backend_mod.default_backend()
    out: dict = {"backend": bk}

    # host rate: S=512 × T=1024 is small enough to finish in ~ms and
    # large enough to be rate-stable
    rng = np.random.RandomState(7)
    S, T = 512, 1024
    keys, sig, tm, th, tn, zok, cok, avail = _compat_inputs(S, T, rng)
    allowed_host(sig, tm, th, tn, zok, cok, avail, keys)  # warm caches
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        allowed_host(sig, tm, th, tn, zok, cok, avail, keys)
    host_s = (time.perf_counter() - t0) / reps
    out["host_ns_per_unit"] = round(host_s / (S * T) * 1e9, 3)

    if bk == "tpu":
        try:
            import jax.numpy as jnp

            Sd, Td = 64, 64
            keys, sig, tm, th, tn, zok, cok, avail = _compat_inputs(Sd, Td, rng)
            jt = {k: jnp.asarray(v) for k, v in tm.items()}
            jh = {k: jnp.asarray(v) for k, v in th.items()}
            jn = {k: jnp.asarray(v) for k, v in tn.items()}
            js = {k: jnp.asarray(v) for k, v in sig.items()}
            jz, jc, ja = map(jnp.asarray, (zok, cok, avail))

            def roundtrip():
                np.asarray(allowed_kernel(js, jt, jh, jn, jz, jc, ja, keys))

            roundtrip()  # compile
            floor = min(_timed(roundtrip) for _ in range(5))
            out["dispatch_floor_ms"] = round(floor * 1000.0, 3)
            threshold = int(floor / (host_s / (S * T)))
            out["compat_min_device_work"] = max(
                _MIN_THRESHOLD, min(_MAX_THRESHOLD, threshold)
            )
        except Exception as e:  # noqa: BLE001 — calibration must not break solves
            out["calibration_error"] = str(e)[-300:]
    _CAL = out
    return out


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# LP-backend routing (ISSUE 8: KARPENTER_TPU_PACK_BACKEND=auto)

_LP_MIN_CLAMP = (1 << 10, 1 << 24)
_LP_MIN_DEFAULT = 1 << 14  # pods × viable-types work below which auto stays on ffd

_LP_CAL: Optional[dict] = None


def lp_calibration(force: bool = False) -> dict:
    """Measure, once per process, what routing a pack job through the
    LP backend costs over plain FFD:

    - ``lp_relax_floor_ms``  — round-trip of a tiny dual-ascent dispatch
      (backends/lp.py), the LP's fixed per-job overhead
    - ``lp_refine_floor_ms`` — round-trip of a warm-started re-ascent at
      the refinement budget (ISSUE 19): what each extra
      KARPENTER_TPU_LP_REFINE_ROUNDS round costs in dispatch floor
    - ``pack_ns_per_unit``   — the FFD engine's cost per pod×frontier
      work unit on a bench-shaped micro-run

    and derive ``lp_min_job_work``: the pods×types work where a job's
    own pack time crosses the relax dispatch floor — below it the LP's
    fixed cost would more than double the job latency for pennies of
    plan, so ``auto`` keeps the job on ffd; above it the relax
    amortizes. Env override: KARPENTER_TPU_LP_MIN_WORK."""
    global _LP_CAL
    if _LP_CAL is not None and not force:
        return _LP_CAL
    out: dict = {}
    try:
        from .pack import batch_pack
        from .backends import lp as lp_mod

        rng = np.random.RandomState(11)
        jobs = []
        for _ in range(8):
            reqs = rng.randint(1, 200, size=(256, 4)).astype(np.int32)
            frontier = np.sort(
                rng.randint(500, 4000, size=(16, 4)).astype(np.int32), axis=0
            )[::-1].copy()
            jobs.append((reqs, frontier, np.int32(110)))
        units = sum(j[0].shape[0] * len(j[1]) for j in jobs)
        batch_pack(jobs)  # warm/compile
        pack_s = min(_timed(lambda: batch_pack(jobs)) for _ in range(3))
        out["pack_ns_per_unit"] = round(pack_s / units * 1e9, 3)

        reqs = rng.randint(1, 200, size=(8, 4)).astype(np.float64)
        counts = np.ones(8)
        alloc = rng.randint(500, 4000, size=(8, 4)).astype(np.float64)
        prices = rng.rand(8) + 0.5

        def roundtrip():
            lp_mod.relax(reqs, counts, alloc, prices, iters=32)

        roundtrip()  # compile
        floor = min(_timed(roundtrip) for _ in range(5))
        out["lp_relax_floor_ms"] = round(floor * 1000.0, 3)

        # warm re-ascent floor (ISSUE 19): same shapes, a converged w0,
        # an 8-iteration budget — the marginal cost of one refinement
        # round's dispatch (its own compile: scan length is static)
        _, _, _, w_conv = lp_mod.relax(reqs, counts, alloc, prices, iters=32)

        def refine_roundtrip():
            lp_mod.relax(reqs, counts, alloc, prices, iters=8, w0=w_conv)

        refine_roundtrip()  # compile
        rfloor = min(_timed(refine_roundtrip) for _ in range(5))
        out["lp_refine_floor_ms"] = round(rfloor * 1000.0, 3)
        threshold = int(floor / max(pack_s / units, 1e-12))
        out["lp_min_job_work"] = max(
            _LP_MIN_CLAMP[0], min(_LP_MIN_CLAMP[1], threshold)
        )
    except Exception as e:  # noqa: BLE001 — calibration must not break solves
        out["lp_calibration_error"] = str(e)[-300:]
    _LP_CAL = out
    return out


def lp_min_job_work(fallback: Optional[int] = None) -> int:
    """The auto-backend routing threshold (pods × viable types): env
    override > on-process calibration > the static default."""
    env = os.environ.get("KARPENTER_TPU_LP_MIN_WORK")
    if env:
        try:
            return int(env)
        except ValueError:
            pass  # a typo'd override falls through to calibration
    cal = lp_calibration()
    return cal.get(
        "lp_min_job_work", fallback if fallback is not None else _LP_MIN_DEFAULT
    )


def compat_min_device_work(fallback: Optional[int] = None) -> int:
    """The live routing threshold: env override > on-chip calibration >
    ``fallback`` (the static tunnel-era default). This is the single
    source of the routing policy — callers pass their own fallback only
    to preserve a monkeypatchable module attribute."""
    env = os.environ.get("KARPENTER_TPU_COMPAT_MIN_WORK")
    if env:
        try:
            return int(env)
        except ValueError:
            pass  # a typo'd override falls through to calibration
    cal = calibration()
    return cal.get(
        "compat_min_device_work", fallback if fallback is not None else _STATIC_DEFAULT
    )


def reset_for_tests() -> None:
    global _CAL, _LP_CAL
    _CAL = None
    _LP_CAL = None
