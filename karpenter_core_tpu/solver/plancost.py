"""Plan-cost evaluation (ISSUE 8): dollars, bounds, optimality gap.

Node-count parity (the PR-2/PR-7 gates) proves the solver opens no more
nodes than the greedy oracle — it says nothing about what the fleet
*costs*. This module prices emitted plans and certifies how far they
can possibly be from optimal:

- ``fleet_cost(plans)`` — $/hr of the emitted fleet: the sum of each
  plan's offering price, exactly what the provisioner will pay.
- ``relaxation_lower_bound(plans, instance_types)`` — a certified lower
  bound on the $/hr of ANY feasible plan that schedules the same pods
  onto these instance types, from the LP dual (backends/lp.py
  ``dual_bound``). The bound deliberately relaxes in the safe
  direction everywhere: full (un-daemon-adjusted) allocatable, each
  type's cheapest offering price unconditionally, no viability masks
  beyond resource fit — every loosening can only LOWER the bound, so
  ``bound ≤ fleet_cost`` holds for every emitted plan by weak duality
  (the property tests/test_backends.py holds the inequality on
  randomized workloads).
- ``optimality_gap(cost, bound)`` — (cost − bound) / bound, the number
  the benches report alongside node counts: how much of the fleet
  price is *provably* irreducible vs potentially-recoverable slack.
  The gap conflates true suboptimality with bound looseness (integer
  slack the relaxation cannot see), so it is an upper bound on the
  recoverable dollars.

The optimality tier (ISSUE 19) generalizes the objective beyond $/hr:
``cost_weights()`` parses ``KARPENTER_TPU_COST_WEIGHTS`` into weighted
terms — offering price, disruption cost (the PR-7 ``pod_eviction_cost``
memo), topology-spread slack, consolidation headroom — and
``pareto_report(plans)`` evaluates every term per solve regardless of
weights, so the trade-off surface is visible even when only price is
optimized. Price stays the DOMINANT objective everywhere plans are
chosen: the LP guard admits a candidate on strict price improvement
only, and the non-price weights act as tie-breaks (headroom) and
reporting weights, never as license to emit a costlier plan. The
weights ride the LP backend's ``job_token`` so two weight settings can
never alias one memoized skeleton stream.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

#: fixed weight order — weights_token() must be stable across processes
_WEIGHT_NAMES = ("price", "disruption", "spread", "headroom")


def cost_weights() -> dict:
    """The multi-objective weight vector, parsed fresh per read (the
    PR-2 env-switch pattern): ``KARPENTER_TPU_COST_WEIGHTS`` as
    ``"price=1,disruption=0.5,spread=0.1,headroom=0.2"``. Defaults to
    price-only (1, 0, 0, 0) — the pre-ISSUE-19 objective exactly.
    Unknown names and malformed entries are ignored, negatives clamp to
    0: a typo must degrade to the default, never fail a solve."""
    weights = {name: 0.0 for name in _WEIGHT_NAMES}
    weights["price"] = 1.0
    raw = os.environ.get("KARPENTER_TPU_COST_WEIGHTS", "")
    for part in raw.split(","):
        if "=" not in part:
            continue
        name, _, val = part.partition("=")
        name = name.strip().lower()
        if name not in weights:
            continue
        try:
            weights[name] = max(0.0, float(val))
        except ValueError:
            continue
    return weights


def weights_token() -> tuple:
    """The weights as a deterministic tuple in ``_WEIGHT_NAMES`` order —
    the component the LP backend folds into ``job_token`` so a weight
    change is a different memo stream, never an aliased one."""
    w = cost_weights()
    return tuple(round(w[name], 9) for name in _WEIGHT_NAMES)


def weights_active() -> bool:
    """True when any non-price objective carries weight."""
    w = cost_weights()
    return any(w[name] > 0.0 for name in _WEIGHT_NAMES if name != "price")


def fleet_cost(plans: Sequence) -> float:
    """$/hr of the emitted fleet — the sum of each NodePlan's offering
    price (SolverResult.total_price over an explicit plan list)."""
    return float(sum(p.price for p in plans))


def optimality_gap(cost: float, bound: float) -> Optional[float]:
    """(cost − bound)/bound, or None when the bound is degenerate."""
    if bound is None or bound <= 0 or not np.isfinite(bound):
        return None
    return max(0.0, (float(cost) - float(bound)) / float(bound))


def relaxation_lower_bound(
    plans: Sequence,
    instance_types: Sequence,
    iters: int = 256,
) -> float:
    """Certified $/hr lower bound for the pods of ``plans`` on
    ``instance_types`` (pass the union catalog when plans span pools —
    more types only loosens, which is the safe direction).

    Sound against ``fleet_cost(plans)`` because every emitted plan is
    feasible in the relaxation: each node's pods fit its (quantized)
    type capacity, and each node's offering price is ≥ its type's
    cheapest offering price."""
    from .backends import lp as lp_mod
    from .encode import build_axis_from_capacities, build_requests_matrix, quantize_capacity

    instance_types = list(instance_types)
    if not plans or not instance_types:
        return 0.0
    requests: List[dict] = []
    for plan in plans:
        pod_requests = getattr(plan, "_pod_requests", None) or []
        requests.extend(pod_requests)
    if not requests:
        return 0.0
    axis = build_axis_from_capacities([it.capacity for it in instance_types])
    alloc = np.stack(
        [quantize_capacity(it.allocatable(), axis) for it in instance_types]
    ).astype(np.float64)
    prices = np.array(
        [
            min(
                (o.price for o in it.offerings if o.available),
                default=float("inf"),
            )
            for it in instance_types
        ],
        dtype=np.float64,
    )
    reqs = build_requests_matrix(requests, axis).astype(np.float64)
    return lp_mod.dual_bound(reqs, alloc, prices, iters=iters)


def cost_block(result, instance_types: Sequence, iters: int = 256) -> dict:
    """The bench-facing rollup: plan cost, relaxation bound, gap —
    ``result`` is a SolverResult (new node plans only; existing-node
    placements are free)."""
    cost = fleet_cost(result.node_plans)
    bound = relaxation_lower_bound(result.node_plans, instance_types, iters=iters)
    gap = optimality_gap(cost, bound)
    return {
        "plan_cost_per_hr": round(cost, 4),
        "lp_bound_per_hr": round(bound, 4),
        "opt_gap_pct": round(gap * 100.0, 2) if gap is not None else None,
    }


def pareto_report(plans: Sequence) -> Optional[dict]:
    """Per-solve multi-objective report (ISSUE 19): every objective
    evaluated on the emitted plans, plus the active weights and the
    weighted scalarization. Reporting only — plan choice happens in the
    backends under the price-dominant guard; this surfaces what that
    choice cost along the other axes (stats.py ``pareto`` block, flight
    recorder, bench ``_split``).

    Objectives (all "smaller is better" except headroom):

    - ``price`` — fleet_cost, $/hr.
    - ``disruption`` — Σ pod_eviction_cost over the plans' pods (the
      PR-7 memo): what consolidating these placements away would cost
      later. Falls back to pod count where pod objects aren't resolved.
    - ``spread_slack`` — max−min of the per-zone new-node counts: how
      unbalanced the plan leaves the zone topology (0 = perfectly
      spread or single-zone).
    - ``headroom`` — mean free-capacity fraction across opened nodes
      (dominant resource axis): consolidation room the plan keeps.

    ``weighted_total`` folds them with cost_weights(), headroom entering
    as its complement (1 − headroom) so every term is a cost."""
    plans = list(plans)
    if not plans:
        return None
    from ..disruption.types import pod_eviction_cost

    weights = cost_weights()
    price = fleet_cost(plans)
    disruption = 0.0
    zone_counts: dict = {}
    headroom_fracs: List[float] = []
    # plans repeat a handful of types — resolve each type's allocatable
    # dict once per report, not once per opened node (this runs on the
    # warm solve path, where per-plan Python work is the latency)
    alloc_memo: dict = {}
    for plan in plans:
        pods = getattr(plan, "pods", None)
        if pods:
            disruption += float(sum(pod_eviction_cost(p) for p in pods))
        else:
            disruption += float(len(getattr(plan, "pod_indices", ()) or ()))
        zone = getattr(plan, "zone", None) or ""
        zone_counts[zone] = zone_counts.get(zone, 0) + 1
        it = getattr(plan, "instance_type", None)
        reqs = getattr(plan, "requests", None)
        if it is None or not reqs:
            continue
        try:
            alloc = alloc_memo.get(id(it))
            if alloc is None:
                alloc = [
                    (res, float(cap))
                    for res, cap in it.allocatable().items()
                    if float(cap) > 0
                ]
                alloc_memo[id(it)] = alloc
            used = max(
                (float(reqs.get(res, 0.0)) / cap for res, cap in alloc),
                default=0.0,
            )
        except (TypeError, ValueError):
            continue
        headroom_fracs.append(min(max(1.0 - used, 0.0), 1.0))
    spread_slack = (
        float(max(zone_counts.values()) - min(zone_counts.values()))
        if len(zone_counts) > 1
        else 0.0
    )
    headroom = (
        sum(headroom_fracs) / len(headroom_fracs) if headroom_fracs else None
    )
    weighted = (
        weights["price"] * price
        + weights["disruption"] * disruption
        + weights["spread"] * spread_slack
        + weights["headroom"] * (1.0 - (headroom if headroom is not None else 1.0))
    )
    return {
        "price_per_hr": round(price, 4),
        "disruption_cost": round(disruption, 4),
        "spread_slack": round(spread_slack, 4),
        "headroom": round(headroom, 4) if headroom is not None else None,
        "weights": {name: weights[name] for name in _WEIGHT_NAMES},
        "weighted_total": round(weighted, 4),
    }
