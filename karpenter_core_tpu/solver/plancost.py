"""Plan-cost evaluation (ISSUE 8): dollars, bounds, optimality gap.

Node-count parity (the PR-2/PR-7 gates) proves the solver opens no more
nodes than the greedy oracle — it says nothing about what the fleet
*costs*. This module prices emitted plans and certifies how far they
can possibly be from optimal:

- ``fleet_cost(plans)`` — $/hr of the emitted fleet: the sum of each
  plan's offering price, exactly what the provisioner will pay.
- ``relaxation_lower_bound(plans, instance_types)`` — a certified lower
  bound on the $/hr of ANY feasible plan that schedules the same pods
  onto these instance types, from the LP dual (backends/lp.py
  ``dual_bound``). The bound deliberately relaxes in the safe
  direction everywhere: full (un-daemon-adjusted) allocatable, each
  type's cheapest offering price unconditionally, no viability masks
  beyond resource fit — every loosening can only LOWER the bound, so
  ``bound ≤ fleet_cost`` holds for every emitted plan by weak duality
  (the property tests/test_backends.py holds the inequality on
  randomized workloads).
- ``optimality_gap(cost, bound)`` — (cost − bound) / bound, the number
  the benches report alongside node counts: how much of the fleet
  price is *provably* irreducible vs potentially-recoverable slack.
  The gap conflates true suboptimality with bound looseness (integer
  slack the relaxation cannot see), so it is an upper bound on the
  recoverable dollars.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def fleet_cost(plans: Sequence) -> float:
    """$/hr of the emitted fleet — the sum of each NodePlan's offering
    price (SolverResult.total_price over an explicit plan list)."""
    return float(sum(p.price for p in plans))


def optimality_gap(cost: float, bound: float) -> Optional[float]:
    """(cost − bound)/bound, or None when the bound is degenerate."""
    if bound is None or bound <= 0 or not np.isfinite(bound):
        return None
    return max(0.0, (float(cost) - float(bound)) / float(bound))


def relaxation_lower_bound(
    plans: Sequence,
    instance_types: Sequence,
    iters: int = 256,
) -> float:
    """Certified $/hr lower bound for the pods of ``plans`` on
    ``instance_types`` (pass the union catalog when plans span pools —
    more types only loosens, which is the safe direction).

    Sound against ``fleet_cost(plans)`` because every emitted plan is
    feasible in the relaxation: each node's pods fit its (quantized)
    type capacity, and each node's offering price is ≥ its type's
    cheapest offering price."""
    from .backends import lp as lp_mod
    from .encode import build_axis_from_capacities, build_requests_matrix, quantize_capacity

    instance_types = list(instance_types)
    if not plans or not instance_types:
        return 0.0
    requests: List[dict] = []
    for plan in plans:
        pod_requests = getattr(plan, "_pod_requests", None) or []
        requests.extend(pod_requests)
    if not requests:
        return 0.0
    axis = build_axis_from_capacities([it.capacity for it in instance_types])
    alloc = np.stack(
        [quantize_capacity(it.allocatable(), axis) for it in instance_types]
    ).astype(np.float64)
    prices = np.array(
        [
            min(
                (o.price for o in it.offerings if o.available),
                default=float("inf"),
            )
            for it in instance_types
        ],
        dtype=np.float64,
    )
    reqs = build_requests_matrix(requests, axis).astype(np.float64)
    return lp_mod.dual_bound(reqs, alloc, prices, iters=iters)


def cost_block(result, instance_types: Sequence, iters: int = 256) -> dict:
    """The bench-facing rollup: plan cost, relaxation bound, gap —
    ``result`` is a SolverResult (new node plans only; existing-node
    placements are free)."""
    cost = fleet_cost(result.node_plans)
    bound = relaxation_lower_bound(result.node_plans, instance_types, iters=iters)
    gap = optimality_gap(cost, bound)
    return {
        "plan_cost_per_hr": round(cost, 4),
        "lp_bound_per_hr": round(bound, 4),
        "opt_gap_pct": round(gap * 100.0, 2) if gap is not None else None,
    }
