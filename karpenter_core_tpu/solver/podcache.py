"""Cross-solve per-pod memoization.

The provisioner's steady state re-solves largely the same pending pods
every batch window (the reference re-lists pods each loop but its
per-pod work is cheap Go; our per-pod work is Python attribute walking
— profiling shows signature extraction + request summing dominate the
50k-pod solve). Informer-style clients hand back the *same* object
until it changes, and every write through ``kube.client`` bumps
``metadata.resource_version`` — so (identity, resource_version) is a
sound memo key for everything derived from a pod's spec:

- its request ResourceList (``resources.requests_for_pods``), interned
  so the 50k-pod batch collapses to a few dozen unique request rows
  that quantize once per axis instead of once per pod;
- the label keys its topology/affinity selectors reference (the input
  to ``encode.selector_label_keys``);
- its constraint signature (``encode.pod_signature``), revalidated per
  batch against the batch's relevant-label-key fingerprint.

The memo rides on the Pod object itself (``pod._karp_memo``), so it is
garbage-collected with the pod and needs no eviction policy. The two
module-global intern maps (request shapes, signature tuples) are pure
dedup accelerators: ids are monotonic and never reused, so clearing a
map (size bound, or ``reset()`` in tests) can never alias two different
contents onto one id — it only costs some dedup until re-interned.
Consumers resolve ids through their own batch's memos
(``encode.build_requests_matrix_ids``), never through the global maps.

Invariant: mutating a pod's spec/labels without bumping
``metadata.resource_version`` (every kube-client write does) serves a
stale memo — any in-place mutator must drop ``pod._karp_memo`` itself,
as ``scheduler.preferences.Preferences.relax`` does. The tensor path's
``_relax_and_retry`` relaxes deep copies that never re-enter signature
grouping, and relaxation does not change requests, so the shared
uid/rv is safe there.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..scheduling import resources


@dataclass(slots=True)
class PodMemo:
    selector_keys: tuple  # label keys this pod's selectors reference
    requests: dict  # interned request ResourceList (do not mutate)
    req_id: int  # interned request-shape id (monotonic, never reused)
    # (relevant-label-keys stable digest, signature tuple, interned sig id) —
    # one field written/read atomically (single reference assignment under
    # the GIL), so concurrent group_pods calls with different fingerprints
    # (provisioner vs disruption threads) can never observe a torn
    # fp/sig/sig_id triple
    sig_state: Optional[Tuple[bytes, tuple, int]] = None


_REQ_INTERN: Dict[tuple, Tuple[int, dict]] = {}
_SIG_INTERN: Dict[tuple, int] = {}
_NEXT_REQ = itertools.count()
_NEXT_SIG = itertools.count()
_LOCK = threading.Lock()
# dedup-map size bound: a weeks-long provisioner under heavy deployment
# churn must not accumulate request/signature shapes forever. Clearing
# only loses dedup (ids are monotonic), never correctness.
_INTERN_MAX = 100_000


def _selector_keys(pod) -> tuple:
    spec = pod.spec
    tsc = spec.topology_spread_constraints
    a = spec.affinity
    # fast path: no selectors anywhere (the common pod at 50k scale)
    if not tsc and (a is None or (a.pod_affinity is None and a.pod_anti_affinity is None)):
        return ()
    keys = set()

    def collect(sel) -> None:
        if sel is None:
            return
        keys.update(sel.match_labels.keys())
        keys.update(e.key for e in sel.match_expressions)

    for c in tsc:
        collect(c.label_selector)
    if a is not None:
        for pa in (a.pod_affinity, a.pod_anti_affinity):
            if pa is None:
                continue
            for t in pa.required:
                collect(t.label_selector)
            for w in pa.preferred:
                collect(w.pod_affinity_term.label_selector)
    # sorted: the key tuple is memo material feeding signature digests —
    # raw set iteration order is process-unstable (PYTHONHASHSEED)
    return tuple(sorted(keys))


def _intern_requests(requests: dict) -> Tuple[dict, int]:
    key = tuple(sorted(requests.items()))
    with _LOCK:
        hit = _REQ_INTERN.get(key)
        if hit is None:
            if len(_REQ_INTERN) >= _INTERN_MAX:
                _REQ_INTERN.clear()
            hit = (next(_NEXT_REQ), requests)
            _REQ_INTERN[key] = hit
        return hit[1], hit[0]


def intern_sig(sig: tuple) -> int:
    """Small-int id for a signature tuple: equal tuples get equal ids,
    so grouping hashes one int per pod instead of a nested tuple."""
    with _LOCK:
        sid = _SIG_INTERN.get(sig)
        if sid is None:
            if len(_SIG_INTERN) >= _INTERN_MAX:
                _SIG_INTERN.clear()
            sid = next(_NEXT_SIG)
            _SIG_INTERN[sig] = sid
        return sid


def _build(pod) -> PodMemo:
    requests, rid = _intern_requests(resources.requests_for_pods(pod))
    return PodMemo(selector_keys=_selector_keys(pod), requests=requests, req_id=rid)


def get_memos(pods) -> List[PodMemo]:
    return get_memos_rvs(pods)[0]


def get_memos_rvs(pods) -> Tuple[List[PodMemo], List[object]]:
    """Memos plus the resource_versions read while validating them —
    one walk serves both the encode path and the incremental solve's
    replay identity check (solver/incremental.py), which would
    otherwise re-read every pod's rv."""
    out: List[PodMemo] = []
    rvs: List[object] = []
    append = out.append
    rv_append = rvs.append
    build = _build
    for pod in pods:
        d = pod.__dict__
        rv = pod.metadata.resource_version
        rv_append(rv)
        cached = d.get("_karp_memo")
        if cached is not None and cached[0] == rv:
            append(cached[1])
            continue
        memo = build(pod)
        d["_karp_memo"] = (rv, memo)
        append(memo)
    return out, rvs


def sig_for_id() -> Dict[int, tuple]:
    """Reverse view of the signature intern table (id → signature
    tuple), for the warm-state snapshot writer (solver/warmstore.py):
    interned ids are process-local ordinals, so persisted keys carry the
    signature CONTENT and re-intern on load."""
    with _LOCK:
        return {sid: sig for sig, sid in _SIG_INTERN.items()}


def reset() -> None:
    """Test hook: drop the dedup maps (ids stay monotonic, so stale
    memos on live pods remain harmless — they just re-intern)."""
    with _LOCK:
        _REQ_INTERN.clear()
        _SIG_INTERN.clear()


def reset_process() -> None:
    """Restart-simulation hook (warmstore tests / profiling): reset the
    intern maps AND their counters as a fresh interpreter would. Unlike
    ``reset()`` this DOES reuse ids — callers must also discard every
    pod object carrying a ``_karp_memo`` from the old world (a real
    restart re-reads pods from the apiserver, memo-free)."""
    global _NEXT_REQ, _NEXT_SIG
    with _LOCK:
        _REQ_INTERN.clear()
        _SIG_INTERN.clear()
        _NEXT_REQ = itertools.count()
        _NEXT_SIG = itertools.count()
