"""Warm-state persistence: snapshot/restore of the cross-solve cache
planes (ISSUE 13 tentpole).

At production scale rolling restarts are constant, and every restart
pays the cold "restart-shaped" solve (bench config 7: cold p50 ~131 ms
vs warm ~32 ms; config 9's disruption path ~20x). The memo planes are
already content-addressed with process-stable blake2b fingerprints
(solver/stablehash.py, PR 5) — this module serializes them to a
versioned on-disk snapshot and re-anchors them into a fresh process so
the first post-restart solve is a warm solve.

What persists, per plane (the "snapshot contents" table in README):

- **catalog entries** (``solver._CATALOG_CACHE``): vocab + axis + the
  encoded tensors + the ``sig_rows`` compat-row LRU. Entries are keyed
  on disk by CONTENT fingerprint only — the in-memory identity key is
  an address and never persisted.
- **job skeletons** (``WarmState.jobs``), **merge skeletons**
  (``WarmState.merges``), **emit choices** (``WarmState.emits``) and
  **merge screen rows** (``WarmState.screen_rows``): keys carry the
  catalog entry's identity head ``(id(entry), fingerprint)`` — stored
  as ``("encfp", fingerprint)`` and rebound on load.
- **route split** (``WarmState.routes``): keys are interned signature
  ids (process-local ordinals) — stored as the signature TUPLES and
  re-interned through ``podcache.intern_sig`` on load.
- **topology seeds** (``WarmState.seed_lru``): guarded by the live
  ``Cluster.generation()`` counter, which does not survive a restart.
  The snapshot records a content witness of the kube-visible pod/node
  world instead; on load the witness must match the LIVE world, and the
  plane re-anchors to the LIVE generation — the persisted counter value
  is another process's counter and witnesses nothing here.
- **intersects memo**: fingerprint-addressed, persisted as-is.
- **jit-signature inventory** (``tracing/deviceplane.py``, ISSUE 16):
  the abstract call-signature population of every registered jit entry
  point — the ``solver/prewarm.py`` replay's shopping list (ISSUE 17).
  Witnessed on restore by the live registry: a row only lands
  on a function this process registered through ``deviceplane.wrap()``
  with the same static-argname contract; everything else is dropped
  and counted like any other plane.
- **compile-cache fingerprint** (``solver/backend.py``, ISSUE 17): the
  managed XLA executable cache stays on disk, but the snapshot records
  its content fingerprint — jax/jaxlib versions, resolved platform,
  and a per-entry digest manifest. On restore the fingerprint is
  compared against the live process in ``_restore_compile_cache``: a
  mismatched jax/platform (or a corrupted/evicted cache dir) drops the
  plane counted, and the jitsig prewarm replay degrades to counted
  cold compiles instead of trusting stale executables blind.
- **fleet content planes** (``fleetenv``/``fleetcanon``/``fleetjob``,
  fleet/megasolve.py): restored through the same job-key rebinding; the
  per-tenant variant (``FleetRegistry.snapshot_tenant``) gives tenant
  migration between schedulers the same way.

Soundness discipline (the PR-5 cachesound rules, extended to persisted
keys by ``analysis/cachesound.py``'s ``cache-persist`` rule): a
restored entry must witness the same read-set as a freshly computed
one. Any entry whose fingerprint witness does not match the live world
is DROPPED, never trusted — and restores are never silent: every plane
reports ``restored``/``dropped`` counts through
``karpenter_tpu_warmstore_{restored,dropped}_entries{plane=...}``, the
``/debug/solve/stats`` ``warmstore`` block (stats.py SCHEMA=4), and the
bench ``_split`` output.

Knobs: ``KARPENTER_TPU_WARMSTORE_DIR`` (snapshot directory; unset =
persistence disabled), ``KARPENTER_TPU_WARMSTORE_MAX_MB`` (snapshot
size cap — oversized planes are trimmed largest-first, never silently).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..tracing import deviceplane, tracer
from . import backend, incremental, podcache
from .stablehash import stable_hash

log = logging.getLogger("karpenter.warmstore")

SCHEMA = 2

#: The writer's key-layout contract, one line per plane. Any change to
#: how a plane's keys are built MUST edit the matching line (and thereby
#: the contract hash): a reader whose contract differs drops the whole
#: snapshot instead of re-anchoring keys it would misparse.
_KEY_CONTRACT = (
    ("catalog", "content fp -> (vocab, axis, enc); sig_rows[(pool_fp, sig_tuple)]"),
    ("compat", "(pool_fp, sig_tuple) -> SigRow on the owning catalog entry"),
    ("route", "(sig_tuple..., ('ce', engine)) -> (tensor_idx, parked_idx, oracle_idx)"),
    ("job", "(('encfp', fp), pool_fp, zone, reqs digest, masks..., engine+backend tokens) + tenant scope -> JobSkeleton"),
    ("merge", "(engine, scan_cap, rkey stream) -> MergeSkeleton; rkey = (job key, node ordinal)"),
    ("emit", "absorption trail (rkey...) -> emitted offering choice"),
    ("mergerow", "rkey -> packed screen row"),
    ("seeds", "(constraint key..., exclusion uids, sim_drained, tenant scope) -> domain counts; plane guard = cluster witness"),
    ("intersects", "(reqs fp, reqs fp) -> bool"),
    ("fleetjob", "tenant-free job-key content prefix -> JobSkeleton"),
    ("jitsig", "(fn name, static-argname tuple) -> abstract signature keys (deviceplane inventory; static reprs bounded at 512 for literal-eval replay)"),
    ("compilecache", "jax/jaxlib/platform + per-entry digest manifest of the managed XLA executable cache (backend.compile_cache_fingerprint)"),
    ("lprelax", "(reqs digest, capacity bytes, price-table float64 bytes, iteration budget int, refine-stage tag...) -> (t_star, has_fit, float64 bound, dual weights); restore witnesses a finite price table and a sane budget, then REBUILDS the live key"),
)
CONTRACT = stable_hash(_KEY_CONTRACT).hex()

_MAGIC = b"KTPU-WARMSTORE\n"

# payload planes in trim order: when the snapshot exceeds
# KARPENTER_TPU_WARMSTORE_MAX_MB the cheapest-to-recompute planes drop
# first (screen rows re-derive from the merge pass; catalogs last — they
# are the single biggest cold-solve cost)
_TRIM_ORDER = ("jitsigs", "lprelax", "screen_rows", "emits", "merges", "intersects", "jobs", "routes", "seeds", "catalogs")

_PLANES = ("catalog", "compat", "route", "job", "merge", "emit", "mergerow", "seeds", "intersects", "fleetjob", "jitsig", "compilecache", "lprelax")

# most recent snapshot/restore outcome (observability; guarded — the
# serving pipeline snapshots from its plan thread while debug routes
# read from the server thread)
_LAST_LOCK = threading.Lock()
_LAST: Dict[str, Optional[dict]] = {"snapshot": None, "restore": None}


def warmstore_dir() -> Optional[str]:
    d = os.environ.get("KARPENTER_TPU_WARMSTORE_DIR", "").strip()
    return d or None


def max_bytes() -> int:
    try:
        mb = float(os.environ.get("KARPENTER_TPU_WARMSTORE_MAX_MB", "256"))
    except ValueError:
        mb = 256.0
    return max(1, int(mb * 1024 * 1024))


def last_outcomes() -> dict:
    with _LAST_LOCK:
        return {k: dict(v) if v else None for k, v in _LAST.items()}


def _set_last(kind: str, outcome: dict) -> None:
    with _LAST_LOCK:
        _LAST[kind] = dict(outcome)


# ---------------------------------------------------------------------------
# key codecs: in-memory identity heads <-> content-addressed stored keys


def _store_job_key(key: tuple) -> Optional[tuple]:
    """Persisted form of one job-memo key: the identity head
    ``(id(entry), fp)`` becomes ``("encfp", fp)`` and the trailing
    tenant scope is split off (persisted once per snapshot — the key
    layout contract says scope is LAST)."""
    head = key[0]
    if not (isinstance(head, tuple) and len(head) == 2 and isinstance(head[1], bytes)):
        return None
    return (("encfp", head[1]),) + key[1:-1]


def _rebind_job_key(stored: tuple, enc_heads: Dict[bytes, tuple], tenant_scope: tuple) -> Optional[tuple]:
    """Re-anchor one persisted job key to the live world: the stored
    ``("encfp", fp)`` head rebinds to the live catalog entry's identity
    head (fingerprint witness — no live entry with this content means
    the key is dropped), and the snapshot's tenant scope rides the
    rebuilt key unchanged. Dropping the scope would let a scope-free
    lookup alias another tenant's restored entries — the
    ``cache-persist`` rule holds this line."""
    tag = stored[0]
    if not (isinstance(tag, tuple) and len(tag) == 2 and tag[0] == "encfp"):
        return None
    head = enc_heads.get(tag[1])
    if head is None:
        return None
    return (head,) + stored[1:] + (tenant_scope,)


def _store_rkey(rkey: tuple) -> Optional[tuple]:
    jk = _store_job_key(rkey[0])
    return None if jk is None else (jk, int(rkey[1]))


def _rebind_rkey(stored: tuple, enc_heads: Dict[bytes, tuple], tenant_scope: tuple) -> Optional[tuple]:
    jk = _rebind_job_key(stored[0], enc_heads, tenant_scope)
    return None if jk is None else (jk, int(stored[1]))


def _sanitize_runtime_caches(caches: dict) -> dict:
    """Persistable subset of an encoding's derived-tensor cache: numpy
    values under content keys only. The ``("type_ord",)`` table maps
    object ids (rebuilt lazily against the live catalog objects) and
    must never cross a process boundary."""
    out = {}
    for k, v in caches.items():
        if k == ("type_ord",) or not isinstance(v, np.ndarray):
            continue
        out[k] = v
    return out


# ---------------------------------------------------------------------------
# the cluster-world witness for the topology seed plane


def cluster_witness(kube_client) -> Optional[bytes]:
    """Content digest of the kube-visible pod/node/claim world the
    topology seed counts derive from. Conservative by design: any
    difference (including ones seeds would not observe) drops the seed
    plane to a cold recompute — sound in the only direction that
    matters."""
    if kube_client is None:
        return None
    try:
        pods = tuple(sorted(
            (
                p.namespace,
                p.metadata.name,
                tuple(sorted((p.metadata.labels or {}).items())),
                p.spec.node_name or "",
                getattr(p.status, "phase", "") or "",
                p.metadata.deletion_timestamp is not None,
            )
            for p in kube_client.list("Pod")
        ))
        nodes = tuple(sorted(
            (n.metadata.name, tuple(sorted((n.metadata.labels or {}).items())))
            for n in kube_client.list("Node")
        ))
        claims = tuple(sorted(
            (c.metadata.name, tuple(sorted((c.metadata.labels or {}).items())))
            for c in kube_client.list("NodeClaim")
        ))
        return stable_hash((pods, nodes, claims))
    except Exception:  # noqa: BLE001 — a witness failure must degrade to "no seeds", not crash
        log.debug("cluster witness failed", exc_info=True)
        return None


# ---------------------------------------------------------------------------
# snapshot (writer)


def _collect_catalog_entries(solver) -> List[tuple]:
    """(fingerprint, entry) for every live catalog entry this solver's
    pools resolve to (under _CATALOG_LOCK — entries are shared)."""
    from .solver import _CATALOG_CACHE, _CATALOG_LOCK

    _pools, pool_catalogs = solver._build_pools()
    out: List[tuple] = []
    seen = set()
    with _CATALOG_LOCK:
        for cat in pool_catalogs:
            entry = _CATALOG_CACHE.get(tuple(map(id, cat)))  # analysis: allow-cache-determinism(id)
            if entry is None or entry.fingerprint in seen:
                continue
            seen.add(entry.fingerprint)
            out.append((entry.fingerprint, entry))
    return out


def _export_lprelax() -> list:
    """Persistable rows of the warm-dual plane (empty until an
    LPBackend has run). Lazy import: importing warmstore must not drag
    in the lp module's jax surface at module-load time."""
    from .backends import lp as lp_backend

    return lp_backend.export_relax_plane()


def build_payload(solver) -> dict:
    """Assemble the (pre-pickle) snapshot payload from the solver's warm
    state and its catalog entries. Pure read — never mutates the planes."""
    from .solver import _CATALOG_LOCK

    ws = incremental.warm_state_for(solver)
    tenant_scope = tuple(getattr(solver, "_tenant_scope", ()) or ())
    sig_names = podcache.sig_for_id()

    catalogs: List[dict] = []
    # the catalog fetch inside _collect_catalog_entries probes the cloud
    # provider (its own lock; for fleet tenants also the canonical
    # catalog plane) — it must run before _CATALOG_LOCK so the global
    # catalog lock never nests a foreign lock. Only the shared-entry
    # reads below hold it.
    entries = _collect_catalog_entries(solver)
    with _CATALOG_LOCK:
        for fp, entry in entries:
            rows = []
            for (pool_fp, sid), row in entry.sig_rows.items():
                sig = sig_names.get(sid)
                if sig is not None:  # intern table may have been cleared: drop, never guess
                    rows.append((pool_fp, sig, row))
            enc = entry.enc
            catalogs.append(dict(
                fingerprint=fp,
                vocab=entry.vocab,
                axis=entry.axis,
                enc=enc,
                runtime_caches=_sanitize_runtime_caches(enc.runtime_caches),
                sig_rows=rows,
            ))

    payload: dict = {
        "schema": SCHEMA,
        "contract": CONTRACT,
        "tenant": tenant_scope,
        "catalogs": catalogs,
        "routes": [],
        "jobs": [],
        "merges": [],
        "emits": [],
        "screen_rows": [],
        "seeds": {"witness": None, "generation": None, "entries": []},
        "intersects": [],
        # jit-signature inventory (ISSUE 16): keys only — counts and
        # compile history stay process-local
        "jitsigs": deviceplane.export_signatures(),
        # compile-cache plane (ISSUE 17): the executable cache itself
        # stays on disk — the snapshot witnesses its content fingerprint
        # (None when the managed cache is not enabled)
        "compilecache": backend.compile_cache_fingerprint(),
        # warm-dual plane (ISSUE 19): the LP backend's converged dual
        # weights, content-keyed (keys are digests/bytes/ints only —
        # nothing process-private crosses the boundary); a restored
        # tick's relax hits the memo and re-ascends nothing
        "lprelax": _export_lprelax(),
    }
    if ws is None:
        return payload

    for key, val in ws.routes.items():
        sigs = []
        ok = True
        for part in key[:-1]:
            sig = sig_names.get(part)
            if sig is None:
                ok = False
                break
            sigs.append(sig)
        if ok:
            payload["routes"].append((tuple(sigs), key[-1], val))

    for key, skel in ws.jobs.items():
        stored = _store_job_key(key)
        if stored is not None:
            payload["jobs"].append((stored, skel))

    for key, skel in ws.merges.items():
        engine, cap, rkeys = key
        srk = [_store_rkey(rk) for rk in rkeys]
        if any(s is None for s in srk):
            continue
        clusters = []
        bad = False
        for cluster in skel.clusters:
            trail = [_store_rkey(rk) for rk in cluster[0]]
            if any(t is None for t in trail):
                bad = True
                break
            clusters.append((tuple(trail),) + tuple(cluster[1:]))
        if not bad:
            payload["merges"].append(
                ((engine, cap, tuple(srk)), clusters, int(skel.applied))
            )

    for trail, emitted in ws.emits.items():
        strail = [_store_rkey(rk) for rk in trail]
        if not any(s is None for s in strail):
            payload["emits"].append((tuple(strail), emitted))

    for rkey, row in ws.screen_rows.items():
        stored = _store_rkey(rkey)
        if stored is not None:
            payload["screen_rows"].append((stored, row))

    # the witness digest reads the kube store (KubeClient._lock) — taken
    # before ws.lock so the warm-state lock never nests the client's
    witness = cluster_witness(solver.kube_client)
    with ws.lock:
        payload["seeds"] = {
            "witness": witness,
            # snapshot-time counter value, recorded for debugging ONLY:
            # restore re-anchors to the live cluster's counter and must
            # never trust this one (cache-persist rule)
            "generation": ws.seed_generation,
            "entries": [(k, dict(v)) for k, v in ws.seed_lru.items()],
        }
    payload["intersects"] = list(ws.intersects.items())
    return payload


def _plane_counts(payload: dict) -> dict:
    return {
        "catalog": len(payload.get("catalogs", ())),
        "compat": sum(len(c["sig_rows"]) for c in payload.get("catalogs", ())),
        "route": len(payload.get("routes", ())),
        "job": len(payload.get("jobs", ())),
        "merge": len(payload.get("merges", ())),
        "emit": len(payload.get("emits", ())),
        "mergerow": len(payload.get("screen_rows", ())),
        "seeds": len((payload.get("seeds") or {}).get("entries", ())),
        "intersects": len(payload.get("intersects", ())),
        "jitsig": sum(len(r[2]) for r in payload.get("jitsigs", ()) if len(r) == 3),
        "compilecache": (
            1 + len(payload["compilecache"].get("entries") or {})
            if isinstance(payload.get("compilecache"), dict)
            else 0
        ),
        "lprelax": len(payload.get("lprelax", ())),
    }


def write_snapshot(payload: dict, directory: str) -> Optional[str]:
    """Serialize one payload to a content-addressed snapshot file.
    Oversized payloads trim planes in ``_TRIM_ORDER`` (recorded in the
    header and the outcome — never silent). Returns the path, or None
    when nothing useful fits."""
    trimmed: List[str] = []
    cap = max_bytes()
    body = pickle.dumps(payload, protocol=4)
    for plane in _TRIM_ORDER:
        if len(body) <= cap:
            break
        if plane == "seeds":
            payload["seeds"] = {"witness": None, "generation": None, "entries": []}
        elif payload.get(plane):
            payload[plane] = []
        else:
            continue
        trimmed.append(plane)
        body = pickle.dumps(payload, protocol=4)
    if len(body) > cap:
        log.warning("warmstore snapshot exceeds cap even after trimming; not written")
        return None
    header = {
        "schema": SCHEMA,
        "contract": CONTRACT,
        "payload_sha256": hashlib.sha256(body).hexdigest(),
        "planes": _plane_counts(payload),
        "tenant": list(payload.get("tenant", ())),
        "trimmed": trimmed,
    }
    os.makedirs(directory, exist_ok=True)
    digest = hashlib.blake2b(body, digest_size=8).hexdigest()
    path = os.path.join(directory, f"warmstore-{digest}.snap")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write((json.dumps(header) + "\n").encode())
        f.write(body)
    os.replace(tmp, path)  # a killed writer never leaves a half-snapshot
    return path


def snapshot(solver, directory: Optional[str] = None) -> Optional[str]:
    """Snapshot this solver's warm planes to ``directory`` (default
    ``KARPENTER_TPU_WARMSTORE_DIR``; unset = disabled → None). Never
    raises: persistence is an optimization, failures degrade to the
    cold restart the process would have paid anyway."""
    directory = directory or warmstore_dir()
    if directory is None:
        return None
    try:
        # own trace root: build_payload runs _build_pools (encode.*
        # spans) and may execute on a quiescing pipeline's caller thread
        # with no enclosing trace — a span without a root is an orphan,
        # and the serving identity tests gate orphans at zero
        with tracer.trace_root("warmstore.snapshot", buffer_if="never"):
            payload = build_payload(solver)
            path = write_snapshot(payload, directory)
    except Exception:  # noqa: BLE001 — see docstring: never fail the caller's shutdown path
        log.exception("warmstore snapshot failed")
        return None
    if path is not None:
        _set_last("snapshot", {"path": path, "planes": _plane_counts(payload)})
    return path


# ---------------------------------------------------------------------------
# restore (reader)


def read_snapshot(path: str) -> Tuple[Optional[dict], Optional[str]]:
    """→ (payload, drop_reason). A snapshot is dropped WHOLE on any
    version/contract/digest mismatch or corruption — restored state is
    either provably the writer's, or absent."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        return None, f"unreadable: {e}"
    if not raw.startswith(_MAGIC):
        return None, "bad magic"
    try:
        nl = raw.index(b"\n", len(_MAGIC))
        header = json.loads(raw[len(_MAGIC):nl])
        body = raw[nl + 1:]
    except (ValueError, json.JSONDecodeError) as e:
        return None, f"bad header: {e}"
    if header.get("schema") != SCHEMA:
        return None, f"schema mismatch: {header.get('schema')} != {SCHEMA}"
    if header.get("contract") != CONTRACT:
        return None, "key-layout contract mismatch"
    if hashlib.sha256(body).hexdigest() != header.get("payload_sha256"):
        return None, "payload digest mismatch (truncated or corrupt)"
    try:
        payload = pickle.loads(body)
    except Exception as e:  # noqa: BLE001 — any unpickling failure means "no snapshot"
        return None, f"unpicklable payload: {e}"
    if payload.get("schema") != SCHEMA or payload.get("contract") != CONTRACT:
        return None, "payload/header version skew"
    return payload, None


class _Outcome:
    """Per-plane restored/dropped accounting (never silent)."""

    def __init__(self, path: str):
        self.path = path
        self.restored: Dict[str, int] = {}
        self.dropped: Dict[str, int] = {}
        self.reason: Optional[str] = None

    def ok(self, plane: str, n: int = 1) -> None:
        if n:
            self.restored[plane] = self.restored.get(plane, 0) + n

    def drop(self, plane: str, n: int = 1) -> None:
        if n:
            self.dropped[plane] = self.dropped.get(plane, 0) + n

    def drop_all(self, payload: Optional[dict], reason: str) -> dict:
        self.reason = reason
        if payload is not None:
            for plane, n in _plane_counts(payload).items():
                self.drop(plane, n)
        else:
            self.drop("snapshot", 1)
        return self.to_dict()

    def to_dict(self) -> dict:
        out = {
            "path": self.path,
            "restored": dict(self.restored),
            "dropped": dict(self.dropped),
        }
        if self.reason:
            out["reason"] = self.reason
        return out


def _restore_catalogs(solver, payload: dict, out: _Outcome) -> Dict[bytes, tuple]:
    """Install snapshotted catalog entries whose content fingerprint
    matches a LIVE catalog, rebound to the live objects and the live
    catalog generation. Returns fp → live identity head for job-key
    rebinding."""
    from .solver import _CATALOG_CACHE, _CATALOG_LOCK, _CatalogEntry, _catalog_cache_max, _catalog_fingerprint

    cg = getattr(solver.cloud_provider, "catalog_generation", None)
    pools, pool_catalogs = solver._build_pools()
    live: Dict[bytes, tuple] = {}  # fp -> (catalog list, generation)
    for pool, cat in zip(pools, pool_catalogs):
        fp = _catalog_fingerprint(cat)
        gen = cg(pool.nodepool) if callable(cg) else None
        live.setdefault(fp, (cat, gen))

    enc_heads: Dict[bytes, tuple] = {}
    with _CATALOG_LOCK:
        for snap in payload.get("catalogs", ()):
            fp = snap["fingerprint"]
            hit = live.get(fp)
            if hit is None:
                # fingerprint witness failed: the live world's catalog
                # content differs — the entry (and every row on it) is
                # dropped, never trusted
                out.drop("catalog")
                out.drop("compat", len(snap["sig_rows"]))
                continue
            cat, gen = hit
            key = tuple(map(id, cat))  # analysis: allow-cache-determinism(id)
            entry = _CATALOG_CACHE.get(key)
            if entry is None or entry.fingerprint != fp:
                enc = snap["enc"]
                # rebind the encoding to the LIVE catalog objects: the
                # fingerprint streams in catalog order, so equal digests
                # mean position-wise identical content
                enc.instance_types = list(cat)
                enc.runtime_caches = dict(snap.get("runtime_caches") or {})
                entry = _CatalogEntry(
                    list(cat), fp, snap["vocab"], snap["axis"], enc, generation=gen
                )
                _CATALOG_CACHE[key] = entry
                _CATALOG_CACHE.move_to_end(key)
                while len(_CATALOG_CACHE) > _catalog_cache_max():
                    _CATALOG_CACHE.popitem(last=False)
            else:
                entry.generation = gen
            out.ok("catalog")
            enc_heads[fp] = (id(entry), fp)
            restored_rows = 0
            cap = incremental.cache_cap("compat")
            for pool_fp, sig, row in snap["sig_rows"]:
                sid = podcache.intern_sig(sig)
                if (pool_fp, sid) not in entry.sig_rows:
                    entry.sig_rows[(pool_fp, sid)] = row
                    entry.sig_rows.move_to_end((pool_fp, sid))
                    while len(entry.sig_rows) > cap:
                        entry.sig_rows.popitem(last=False)
                restored_rows += 1
            out.ok("compat", restored_rows)
    return enc_heads


def _restore_seeds(ws, plane: dict, live_witness: Optional[bytes], live_generation: Optional[int], out: _Outcome) -> None:
    """Re-anchor the topology seed plane. The persisted generation
    (``plane["generation"]``) is another process's counter value: the
    plane is valid iff the recorded cluster-world witness matches the
    LIVE world, and then it binds to the LIVE generation so the very
    next informer event invalidates it exactly like home-grown seeds."""
    entries = plane.get("entries") or []
    if not entries:
        return
    witness = plane.get("witness")
    if (
        live_generation is None
        or witness is None
        or live_witness is None
        or witness != live_witness
    ):
        out.drop("seeds", len(entries))
        return
    with ws.lock:
        ws.seed_lru.clear()
        ws.seed_generation = live_generation
        for key, val in entries:
            ws.seed_lru.put(key, dict(val))
    out.ok("seeds", len(entries))


def _restore_compile_cache(payload: dict, out: "_Outcome") -> bool:
    """Witness the snapshot's compile-cache plane against the LIVE
    process (ISSUE 17). The executable cache is bytes XLA will map and
    run — it is only trustworthy when the jax/jaxlib versions and the
    resolved platform that produced it match this process exactly, and
    the witnessed cache entries are still present byte-identical. Any
    mismatch drops the plane counted (never trusted blind) and the
    jitsig prewarm replay degrades to counted cold compiles. Returns
    True iff the plane restored clean."""
    stored = payload.get("compilecache")
    if not isinstance(stored, dict):
        return False  # writer had no managed cache: nothing to witness
    n = 1 + len(stored.get("entries") or {})
    live = backend.compile_cache_fingerprint()
    if live is None:
        out.drop("compilecache", n)
        return False
    if (
        stored.get("jax") != live.get("jax")
        or stored.get("jaxlib") != live.get("jaxlib")
        or stored.get("platform") != live.get("platform")
    ):
        out.drop("compilecache", n)
        return False
    live_entries = live.get("entries") or {}
    stale = sum(
        1
        for rel, digest in (stored.get("entries") or {}).items()
        if live_entries.get(rel) != digest
    )
    if stale:
        # corrupted or partially evicted cache dir: some witnessed
        # executables are gone — their compiles come back cold, counted
        out.drop("compilecache", stale)
        out.ok("compilecache", n - stale)
        return False
    out.ok("compilecache", n)
    return True


def _restore_lprelax(payload: dict, out: "_Outcome") -> None:
    """Re-anchor the warm-dual plane (ISSUE 19). The keys are pure
    content — reqs digest, capacity bytes, price-table fingerprint,
    iteration budget, refine-stage tag — but NOTHING is trusted blind:
    each row must parse exactly as the writer's contract line says, and
    the live key is REBUILT by threading the parsed components, so a
    malformed or contract-skewed row drops counted instead of landing
    as an unreachable (or aliasing) memo key. Values seed warm dual
    ascents; a wrong value could mis-route a primal but can never break
    soundness (the bound is host-recertified and the cost guard reprices
    every candidate) — the witnesses below still reject anything that
    fails to parse as what the writer claims to have stored."""
    rows = payload.get("lprelax", ())
    if not rows:
        return
    from .backends import lp as lp_backend
    from .backends import get_backend

    get_backend("lp")  # materialize the shared plane before adopting it
    cache = lp_backend.shared_relax_cache()
    if cache is None:
        out.drop("lprelax", len(rows))
        return
    for row in rows:
        try:
            key, value = row
            digest, alloc_b, prices_b, iters = key[0], key[1], key[2], key[3]
            stage = tuple(key[4:])
            if not (
                isinstance(digest, bytes)
                and isinstance(alloc_b, bytes)
                and isinstance(prices_b, bytes)
            ):
                out.drop("lprelax")
                continue
            # iteration-budget witness: the budget is a first-class key
            # component (job_token and the memo key both thread it) — a
            # row whose budget is not a sane int must not land, or a
            # future budget change could alias a foreign solve's duals
            if not isinstance(iters, int) or iters < 8:
                out.drop("lprelax")
                continue
            # price-table witness: the stored fingerprint must parse as
            # the finite float64 table the dual solve actually read —
            # a non-finite price in the key would mean the stored bound
            # certifies a price model the live guard never prices with
            prices = np.frombuffer(prices_b, dtype=np.float64)
            if prices.size == 0 or not np.isfinite(prices).all():
                out.drop("lprelax")
                continue
            t_star, has_fit, bound, w = value
            if not (np.isfinite(float(bound)) and float(bound) >= 0.0):
                out.drop("lprelax")
                continue
            live_key = (digest, alloc_b, prices_b, int(iters)) + stage
            cache.put(
                live_key,
                (
                    np.asarray(t_star, dtype=np.int32),
                    np.asarray(has_fit, dtype=bool),
                    float(bound),
                    np.asarray(w),
                ),
            )
            out.ok("lprelax")
        except (TypeError, ValueError, IndexError):
            out.drop("lprelax")


def restore(solver, path: str, metrics=None, fleet_plane=None) -> dict:
    """Restore a snapshot into ``solver``'s warm world. Every plane
    re-anchors against the live world (catalog fingerprints, cluster
    witness, re-interned signatures); whatever fails its witness is
    dropped and counted. Returns the outcome dict (also mirrored to
    ``solver.last_warmstore_stats`` and the warmstore metrics)."""
    out = _Outcome(path)
    try:
        # own trace root (the snapshot() rationale): restore runs the
        # live-world catalog fetch/fingerprint before the first tick's
        # decision root exists
        with tracer.trace_root("warmstore.restore", buffer_if="never"):
            return _restore_under_root(solver, path, metrics, fleet_plane, out)
    except Exception:  # noqa: BLE001 — a corrupt plane degrades to cold, never crashes the caller
        log.exception("warmstore restore failed; remaining planes dropped")
        out.reason = "restore error (see logs)"
    return _publish(solver, out.to_dict(), metrics)


def _restore_under_root(solver, path: str, metrics, fleet_plane, out: "_Outcome") -> dict:
    try:
        payload, reason = read_snapshot(path)
        if payload is None:
            result = out.drop_all(None, reason or "unreadable")
            return _publish(solver, result, metrics)
        ws = incremental.warm_state_for(solver)
        if ws is None:
            result = out.drop_all(payload, "incremental path disabled")
            return _publish(solver, result, metrics)

        snap_scope = tuple(payload.get("tenant", ()) or ())
        enc_heads = _restore_catalogs(solver, payload, out)

        for sigs, engine_tok, val in payload.get("routes", ()):
            key = tuple(podcache.intern_sig(s) for s in sigs) + (engine_tok,)
            ws.routes.put(key, val)
            out.ok("route")

        for stored, skel in payload.get("jobs", ()):
            key = _rebind_job_key(stored, enc_heads, snap_scope)
            if key is None:
                out.drop("job")
                continue
            ws.jobs.put(key, skel)
            if fleet_plane is not None:
                # fleet content plane: same tenant-free content prefix
                # contract as the live put in solver._pack_and_finalize
                fleet_plane.skeleton_put(key[:-1], skel)
            out.ok("job")

        for (engine, cap, srkeys), clusters, applied in payload.get("merges", ()):
            rkeys = [_rebind_rkey(rk, enc_heads, snap_scope) for rk in srkeys]
            if any(rk is None for rk in rkeys):
                out.drop("merge")
                continue
            rebuilt = []
            bad = False
            for cluster in clusters:
                trail = [_rebind_rkey(rk, enc_heads, snap_scope) for rk in cluster[0]]
                if any(t is None for t in trail):
                    bad = True
                    break
                rebuilt.append((tuple(trail),) + tuple(cluster[1:]))
            if bad:
                out.drop("merge")
                continue
            ws.merges.put(
                (engine, cap, tuple(rkeys)),
                incremental.MergeSkeleton(rebuilt, applied),
            )
            out.ok("merge")

        for strail, emitted in payload.get("emits", ()):
            trail = [_rebind_rkey(rk, enc_heads, snap_scope) for rk in strail]
            if any(t is None for t in trail):
                out.drop("emit")
                continue
            ws.emits.put(tuple(trail), emitted)
            out.ok("emit")

        for stored, row in payload.get("screen_rows", ()):
            rkey = _rebind_rkey(stored, enc_heads, snap_scope)
            if rkey is None:
                out.drop("mergerow")
                continue
            ws.screen_rows.put(rkey, row)
            out.ok("mergerow")

        cluster = solver.cluster
        live_gen = (
            cluster.generation()
            if cluster is not None and hasattr(cluster, "generation")
            else None
        )
        _restore_seeds(
            ws,
            payload.get("seeds") or {},
            cluster_witness(solver.kube_client),
            live_gen,
            out,
        )

        inter = ws.intersects_cache()
        n_inter = 0
        for key, verdict in payload.get("intersects", ()):
            if key not in inter:
                inter[key] = verdict
                n_inter += 1
        out.ok("intersects", n_inter)

        # jit-signature inventory (ISSUE 16): witnessed inside
        # import_signatures — a row restores only onto a live wrap()
        # registration with the same static-argname contract
        jitsig_rows = payload.get("jitsigs", ())
        if jitsig_rows:
            n_ok, n_drop = deviceplane.import_signatures(jitsig_rows)
            out.ok("jitsig", n_ok)
            out.drop("jitsig", n_drop)

        # compile-cache plane (ISSUE 17): witnessed in its own unit so
        # the jax/platform fingerprint comparison is a named, analyzable
        # seam (the cache-persist rule holds this line)
        _restore_compile_cache(payload, out)

        # warm-dual plane (ISSUE 19): same discipline — its own named
        # unit so the price-table and iteration-budget witnesses are
        # analyzable seams (cache-persist rule, check 5)
        _restore_lprelax(payload, out)
    except Exception:  # noqa: BLE001 — a corrupt plane degrades to cold, never crashes the caller
        log.exception("warmstore restore failed; remaining planes dropped")
        out.reason = "restore error (see logs)"
    return _publish(solver, out.to_dict(), metrics)


def _publish(solver, result: dict, metrics) -> dict:
    _set_last("restore", result)
    try:
        solver.last_warmstore_stats = dict(result)
    except Exception:  # noqa: BLE001 — read-only solver doubles must not fail the restore
        log.debug("could not stamp last_warmstore_stats", exc_info=True)
    if metrics is not None and hasattr(metrics, "warmstore_restored"):
        for plane, n in result.get("restored", {}).items():
            metrics.warmstore_restored.inc(n, plane=plane)
        for plane, n in result.get("dropped", {}).items():
            metrics.warmstore_dropped.inc(n, plane=plane)
    return result


# ---------------------------------------------------------------------------
# fleet content planes (fleet/megasolve.py): the canonical-catalog plane
# persists by content fingerprint; the fleetenv envelope memo does NOT
# (its keys are per-provider generation counters that die with the
# process — admission prewarm recomputes them against live counters)


def snapshot_fleet_plane(plane, directory: Optional[str] = None) -> Optional[str]:
    """Snapshot a CatalogPlane's canonical catalogs → path or None."""
    directory = directory or warmstore_dir()
    if directory is None:
        return None
    try:
        payload = {
            "schema": SCHEMA,
            "contract": CONTRACT,
            "tenant": (),
            "fleet_canon": plane.export_canon(),
        }
        return write_snapshot(payload, directory)
    except Exception:  # noqa: BLE001 — persistence never fails the fleet control plane
        log.exception("fleet-plane snapshot failed")
        return None


def restore_fleet_plane(plane, path: str) -> dict:
    """Restore canonical catalogs into a CatalogPlane (content-addressed
    — fingerprints are their own witness; plane generations re-mint)."""
    payload, reason = read_snapshot(path)
    if payload is None:
        return {"path": path, "restored": {}, "dropped": {"fleetcanon": 1}, "reason": reason}
    n = plane.import_canon(payload.get("fleet_canon", ()))
    return {"path": path, "restored": {"fleetcanon": n}, "dropped": {}}


# ---------------------------------------------------------------------------
# restart simulation (tests, profiling): drop every in-memory plane
# exactly as a process exit would — the on-disk snapshot is all that
# survives


def simulate_process_death() -> None:
    """Wipe every cross-solve in-memory plane: the catalog cache, every
    WarmState, and the podcache intern maps INCLUDING their counters (a
    fresh interpreter restarts ids at zero). Callers must also discard
    pod objects carrying ``_karp_memo`` from the old world — a real
    restart re-reads pods from the apiserver, memo-free."""
    from .solver import _CATALOG_CACHE, _CATALOG_LOCK

    from . import prewarm

    with _CATALOG_LOCK:
        _CATALOG_CACHE.clear()
    incremental.reset()
    podcache.reset_process()
    deviceplane.reset()
    prewarm.reset_for_tests()
    # backend singletons AND the process-shared warm-dual plane (ISSUE
    # 19): a fresh interpreter has neither — leaving them would let
    # "restored" ticks read duals that never crossed the snapshot
    from . import backends

    backends.reset_for_tests()
    with _LAST_LOCK:
        _LAST["snapshot"] = None
        _LAST["restore"] = None
