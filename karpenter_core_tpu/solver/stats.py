"""Consolidated per-solve stats (ISSUE 10 satellite): one stable schema
over the stat blobs that accreted per-PR on the solver — ``last_timings``
(PR 1), ``last_merge_stats`` (PR 2), ``last_cache_stats`` (PR 4),
``last_pack_stats`` (PR 8) — plus the disruption engine's
``last_decision_stats`` (PR 7) when a controller is wired in.

Consumers:

- ``/debug/solve/stats`` (operator/server.py) serves exactly this dict;
- ``bench.py _split`` derives its per-config columns from it (the
  emitted BENCH keys are unchanged, so round-over-round trajectories
  stay comparable — see ``bench_fields``);
- the flight recorder (tracing/flightrec.py) embeds it per decision.

Schema discipline: every top-level key below is always present (empty
dict / None when the layer didn't run), and additions bump ``SCHEMA``.
"""

from __future__ import annotations

from typing import Optional

SCHEMA = 7  # 7: "pareto" block (plancost.pareto_report — per-solve
# multi-objective report: price, disruption cost, spread slack,
# consolidation headroom, active weights; None when no plans were
# emitted), ISSUE 19; 6: device block carries "compile_cache" (managed XLA
# executable cache status: enabled|disabled|unavailable:<why>, dir,
# entry count — a cacheless process is visible, never silent) and
# "prewarm" (the boot jitsig-replay outcome), ISSUE 17; 5: "device"
# block (compile/transfer/HBM attribution per solve, ISSUE 16); 4:
# "warmstore" block (snapshot/restore outcome — per-plane restored/
# dropped counts, ISSUE 13); 3: "route" block added (tensor/parked/
# oracle pod split per solve + oracle share, ISSUE 12); 2: "shard"
# block (mesh padding)


def _round3(v) -> float:
    try:
        return round(float(v), 3)
    except (TypeError, ValueError):
        return 0.0


def solve_stats(solver, disruption=None) -> dict:
    """The stable consolidated view of ``solver``'s most recent solve.
    ``disruption`` (optional DisruptionController or anything exposing
    ``last_decision_stats``) contributes the last disruption decision."""
    t = getattr(solver, "last_timings", None) or {}
    cs = getattr(solver, "last_cache_stats", None) or {}
    ms = getattr(solver, "last_merge_stats", None) or {}
    ps = getattr(solver, "last_pack_stats", None) or {}
    dstats = getattr(disruption, "last_decision_stats", None) if disruption is not None else None
    return {
        "schema": SCHEMA,
        "trace_id": t.get("trace_id"),
        "timings": {
            "total_ms": _round3(t.get("total_ms", 0.0)),
            "device_ms": _round3(t.get("device_ms", 0.0)),
            "host_ms": _round3(t.get("host_ms", 0.0)),
        },
        "cache": {
            "hits": dict(cs.get("hits", {})),
            "misses": dict(cs.get("misses", {})),
            "evictions": dict(cs.get("evictions", {})),
            "hit_rate": cs.get("hit_rate"),
        },
        "merge": {
            "ms": _round3(ms.get("merge_ms", 0.0)),
            "engine": ms.get("merge_engine"),
            "records": int(ms.get("merge_records", 0) or 0),
            "candidates_screened": int(ms.get("merge_candidates_screened", 0) or 0),
            "pairs_applied": int(ms.get("merge_pairs_applied", 0) or 0),
        },
        "pack_backend": dict(ps),
        "pareto": dict(pp) if (pp := getattr(solver, "last_pareto", None)) else None,
        "shard": dict(ss) if (ss := getattr(solver, "last_shard_stats", None)) else None,
        "route": dict(rs) if (rs := getattr(solver, "last_route_stats", None)) else None,
        "disruption": dict(dstats) if dstats else None,
        "warmstore": _warmstore_block(solver),
        "device": _device_block(solver),
    }


def _device_block(solver) -> Optional[dict]:
    """The per-solve device block plus the process-level compile-cache
    status and boot prewarm-replay outcome (ISSUE 17): a solve that ran
    cacheless — or a restore whose replay degraded — is a visible
    status, never silence."""
    ds = getattr(solver, "last_device_stats", None)
    if not ds:
        return None
    from . import backend, prewarm

    out = dict(ds)
    out["compile_cache"] = backend.compile_cache_status()
    out["prewarm"] = prewarm.last_result()
    return out


def _warmstore_block(solver) -> Optional[dict]:
    """The most recent snapshot/restore outcome. The solver's own stamp
    wins; the process-level fallback covers the restore-before-first-
    tick path, where the restore ran through a throwaway solver before
    the provisioner built its live one (the planes are shared module
    state either way — only the outcome record rides an instance)."""
    wss = getattr(solver, "last_warmstore_stats", None)
    if wss:
        return dict(wss)
    from . import warmstore

    return warmstore.last_outcomes().get("restore")


def bench_fields(stats: dict) -> dict:
    """Project the consolidated schema onto the flat per-config BENCH
    columns (``device_ms``/``host_ms``/``cache_*``/``merge_*``/
    ``pack_backend``) the round artifacts have carried since PR 1-8 —
    the bench readers consume the stable schema, the emitted artifact
    keys stay byte-compatible with prior rounds."""
    out: dict = {}
    t = stats.get("timings", {})
    out["device_ms"] = round(t.get("device_ms", 0.0), 2)
    out["host_ms"] = round(t.get("host_ms", 0.0), 2)
    cache = stats.get("cache", {})
    if cache.get("hits") or cache.get("misses"):
        out["cache_hits"] = dict(cache.get("hits", {}))
        out["cache_misses"] = dict(cache.get("misses", {}))
        if cache.get("hit_rate") is not None:
            out["cache_hit_rate"] = cache["hit_rate"]
    ps = stats.get("pack_backend", {})
    if ps and ps.get("backend") not in (None, "ffd"):
        out["pack_backend"] = dict(ps)
    pp = stats.get("pareto")
    if pp:
        out["pareto"] = dict(pp)
    sh = stats.get("shard")
    if sh:
        out["shard"] = dict(sh)
    rt = stats.get("route")
    if rt:
        out["route"] = dict(rt)
    wss = stats.get("warmstore")
    if wss:
        out["warmstore"] = dict(wss)
    dev = stats.get("device")
    if dev:
        # compact projection: the event list stays on the debug route
        cc = dev.get("compile_cache") or {}
        out["device"] = {
            "compiles": dev.get("compiles", 0),
            "transfer_bytes": dict(dev.get("transfer_bytes", {})),
            "footprint_bytes": dev.get("footprint_bytes", 0),
            "tile_headroom_frac": dev.get("tile_headroom_frac"),
            "compile_cache_status": cc.get("status"),
            "compile_cache_entries": cc.get("entries"),
        }
    merge = stats.get("merge", {})
    out["merge_ms"] = round(merge.get("ms", 0.0), 2)
    out["merge_candidates_screened"] = merge.get("candidates_screened", 0)
    out["merge_pairs_applied"] = merge.get("pairs_applied", 0)
    if merge.get("engine"):
        out["merge_engine"] = merge["engine"]
    return out


def route_payload(solver_ref, disruption_ref=None) -> Optional[dict]:
    """The /debug/solve/stats payload builder: ``solver_ref`` /
    ``disruption_ref`` are zero-arg callables resolving the CURRENT
    solver / disruption controller (the operator swaps solvers when the
    nodepool set changes, so the route must re-resolve per request).
    Returns None when no solver has solved yet (route answers 404)."""
    solver = solver_ref() if callable(solver_ref) else solver_ref
    if solver is None or not getattr(solver, "last_timings", None):
        return None
    disruption = disruption_ref() if callable(disruption_ref) else disruption_ref
    return solve_stats(solver, disruption)
