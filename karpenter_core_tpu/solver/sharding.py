"""Multi-chip sharding for the solver (SURVEY §5 distributed mapping).

Scaling axes, in jax.sharding terms:
- **groups** (data-parallel-like): signature groups / zone sub-batches
  pack independently — shard the group axis over the mesh, each device
  scans its groups, ICI collectives reduce fleet totals.
- **types** (tensor-parallel-like): the S×T compat kernel shards the
  type axis; each device computes a T-shard of the masks, results
  all-gather (XLA inserts the collective from shardings).
- **pods** (ISSUE 11 tentpole): one giant job's pod axis chunks into
  contiguous shards across the mesh — each device FFD-packs its chunk,
  per-shard node ids renumber into one global id space on the host, and
  the per-shard partial plans merge downstream through the existing
  vectorized merge engine (a chunk's underfull tail nodes are ordinary
  merge records). This is what takes a single solve to 500k–1M pods ×
  10k types: no (P, T, R)-shaped transient ever materializes — the pack
  state per device is (K, F, R), the compat matrices stay tiled
  (type-axis shards here, (TILE_S, TILE_T) VMEM blocks in
  pallas_kernels), and the host-side type assignment is row-blocked
  under a byte budget.

Engine switch (the PR-2/PR-7 pattern): the pod-axis chunk pack runs
``KARPENTER_TPU_SHARD_ENGINE={sharded,unsharded}`` — ``sharded`` is the
shard_map dispatch across the mesh, ``unsharded`` the vmap twin of the
SAME chunked computation on one device, so the two engines are
plan-identical by construction and ``unsharded`` is the parity oracle
at subsampled shapes. The chunk threshold is
``KARPENTER_TPU_SHARD_MIN_PODS`` (chunking changes the pod→node
partition, so both knobs are job-memo key material:
``incremental.pack_engine_token``).

Padding is never silent (the PR-7 ``family_capped`` discipline): both
the type-axis padding of ``prepare_sharded_catalog`` and the pod-axis
chunk padding accumulate into per-solve shard stats
(``TPUScheduler.last_shard_stats``, bench ``shard_*`` columns) and the
``karpenter_tpu_shard_padding_waste`` gauge.

Fleet-level repack for consolidation reuses the same mesh with a psum
over candidate-subset scores.
"""

from __future__ import annotations

import os
import threading
import time
from functools import lru_cache, partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import devicetime
from .pack import ffd_pack, ffd_pack_batched
from ..tracing import deviceplane, tracer

# jax.shard_map landed at top level only in newer jax; older images ship
# it under jax.experimental.shard_map. Feature-detect once so the
# sharded pack/screen paths work on both (and skip cleanly on neither).
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # analysis: allow-broad-except — no shard_map in this jax
        _shard_map = None


def shard_map_available() -> bool:
    """True when this jax exposes shard_map (top-level or experimental)."""
    return _shard_map is not None


def _require_shard_map():
    if _shard_map is None:
        raise RuntimeError(
            "shard_map is unavailable in this jax build "
            "(neither jax.shard_map nor jax.experimental.shard_map)"
        )
    return _shard_map


def make_mesh(n_devices: Optional[int] = None, axis: str = "groups") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


# ---------------------------------------------------------------------------
# pod-axis mega-shard configuration (ISSUE 11)

def shard_engine() -> str:
    """``sharded`` (shard_map across the mesh) or ``unsharded`` (the
    vmap twin of the same chunked pack on one device — the parity
    oracle). Read per dispatch, the PR-2 engine-switch pattern; unknown
    values degrade to ``sharded``."""
    eng = os.environ.get("KARPENTER_TPU_SHARD_ENGINE", "sharded").strip().lower()
    return eng if eng in ("sharded", "unsharded") else "sharded"


def shard_min_pods() -> int:
    """Pod count at which a single pack job chunks across the mesh.
    Chunking changes the pod→node partition (each chunk packs its own
    nodes; tails re-merge downstream), so this is job-memo key material
    — see ``incremental.pack_engine_token``."""
    try:
        return max(1, int(os.environ.get("KARPENTER_TPU_SHARD_MIN_PODS", "65536")))
    except ValueError:
        return 65536


def pod_shard_token(mesh) -> tuple:
    """The pod-axis chunk configuration a pack result depends on, for
    job-memo keys: with a mesh active, (engine, threshold, mesh size)
    decide whether/how a job chunks. Returns () single-device so
    meshless keys stay stable."""
    if mesh is None:
        return ()
    return (shard_engine(), shard_min_pods(), int(mesh.devices.size))


# ---------------------------------------------------------------------------
# per-solve shard padding stats — padding is NEVER silent (the PR-7
# family_capped discipline). Thread-local: concurrent solvers (fleet
# lanes, disruption sims) each accumulate their own solve's stats.

_PAD_TLS = threading.local()


def _shard_acc() -> dict:
    acc = getattr(_PAD_TLS, "acc", None)
    if acc is None:
        acc = _PAD_TLS.acc = {}
    return acc


def reset_shard_stats() -> None:
    """Start a fresh per-solve accumulator on this thread (the solver
    calls this at solve entry)."""
    _PAD_TLS.acc = {}


def record_shard_padding(
    axis: str, used: int, padded: int, accumulate: bool = True, **extra
) -> None:
    """Record one padding event: ``used`` real slots inside ``padded``
    total slots along ``axis`` (``pods`` | ``types``). ``accumulate``
    sums across events (many chunk packs per solve); False replaces
    (the type axis is a property of the active catalog, re-observed per
    solve). ``extra`` merges scalar context (engine, n_devices)."""
    acc = _shard_acc()
    a = acc.get(axis)
    if a is None or not accumulate:
        acc[axis] = {"used": int(used), "padded": int(padded)}
    else:
        a["used"] += int(used)
        a["padded"] += int(padded)
    for k, v in extra.items():
        acc[k] = v


def consume_shard_stats() -> dict:
    """Drain this thread's accumulator into the per-solve stats shape:
    ``{axis}_used`` / ``{axis}_padded`` / ``{axis}_waste`` (wasted-slot
    fraction) per recorded axis, plus any scalar context."""
    acc = _shard_acc()
    _PAD_TLS.acc = {}
    out: dict = {}
    for axis in ("pods", "types"):
        a = acc.pop(axis, None)
        if a is None:
            continue
        used, padded = a["used"], a["padded"]
        out[f"{axis}_used"] = used
        out[f"{axis}_padded"] = padded
        out[f"{axis}_waste"] = round(1.0 - used / padded, 4) if padded else 0.0
    out.update(acc)
    return out


_MESH: Optional[Mesh] = None


def active_mesh(backend: str) -> Optional[Mesh]:
    """The mesh the solve should shard over, or None for the single-
    device path. KARPENTER_TPU_SHARDED: 'auto' (shard when the resolved
    backend is a multi-chip TPU), 'on' (shard whenever >1 device — how
    the CPU-mesh tests and dryrun drive the integrated path), 'off'."""
    mode = os.environ.get("KARPENTER_TPU_SHARDED", "auto")
    if mode == "off":
        return None
    try:
        n = len(jax.devices())
    except Exception:  # analysis: allow-broad-except — no devices ⇒ single-device path
        return None
    if n < 2 or (mode == "auto" and backend != "tpu"):
        return None
    global _MESH
    if _MESH is None or _MESH.devices.size != n:
        _MESH = make_mesh()
    return _MESH


@lru_cache(maxsize=16)
def _sharded_pack_fn(mesh: Mesh):
    """The jitted shard_map group pack for one mesh, cached — a fresh
    jit-of-closure per call would recompile on every solve (Mesh is
    hashable, so the mesh IS the cache key; shapes re-specialize inside
    jit's own cache)."""

    def per_device(reqs, fronts, caps):
        node_ids, counts = jax.vmap(
            lambda r, f, c: ffd_pack(r, f, c)
        )(reqs, fronts, caps)
        local_total = jnp.sum(counts)
        fleet_total = jax.lax.psum(local_total, axis_name="groups")
        return node_ids, counts, fleet_total

    shard = partial(
        _require_shard_map(),
        mesh=mesh,
        in_specs=(P("groups"), P("groups"), P("groups")),
        out_specs=(P("groups"), P("groups"), P()),
    )
    return deviceplane.wrap("sharding.sharded_batch_pack", jax.jit(shard(per_device)))


def sharded_batch_pack(
    mesh: Mesh,
    requests: jnp.ndarray,  # (G, Pmax, R) int32 — padded groups
    frontiers: jnp.ndarray,  # (G, F, R) int32
    max_per_node: jnp.ndarray,  # (G,) int32
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pack G groups across the mesh; returns (node_ids (G, Pmax),
    node_counts (G,), fleet_total ()). The fleet total is a real ICI
    collective (psum over the groups axis)."""
    return _sharded_pack_fn(mesh)(requests, frontiers, max_per_node)


def sharded_pod_pack(
    mesh: Optional[Mesh],
    requests: np.ndarray,  # (P, R) int32, pre-sorted descending by primary
    frontier: np.ndarray,  # (F, R) int32
    max_per_node,
    engine: Optional[str] = None,
) -> Tuple[np.ndarray, int]:
    """Pod-axis sharded FFD pack of ONE mega job (ISSUE 11 tentpole).

    The sorted pod axis chunks into D contiguous shards (chunk d holds
    pods [d·Pc, (d+1)·Pc) — each chunk is itself sorted, so each
    device's scan is a well-formed FFD); every device packs its chunk
    independently, and the per-shard node ids renumber into one global
    id space via an exclusive cumsum of shard node counts. Chunk tails
    re-merge downstream through the ordinary merge records, so the
    chunked partition costs at most D-1 underfull tails before the
    merge engine folds them.

    ``engine`` (default: ``shard_engine()``): ``sharded`` dispatches
    one shard_map across the mesh; ``unsharded`` runs the vmap twin of
    the SAME chunked computation on one device — identical chunking,
    identical per-chunk scan (k_open=16 both ways), so the engines are
    plan-identical by construction. No shard_map in this jax build (or
    no mesh) degrades to ``unsharded`` explicitly.

    Padding pods (chunk tail slots) exceed the frontier max, emit -1
    without touching scan state, and are recorded — never silent —
    into the per-solve shard stats.

    → (node_ids (P,) int32 global ids [-1 ⇒ unschedulable], node_count).
    """
    if engine is None:
        engine = shard_engine()
    D = int(mesh.devices.size) if mesh is not None else 1
    if engine == "sharded" and (mesh is None or _shard_map is None):
        engine = "unsharded"  # explicit degrade, recorded in the stats
    P, R = requests.shape
    Pc = -(-P // D)
    fmax = frontier.max(axis=0)
    padded = np.empty((D * Pc, R), dtype=np.int32)
    padded[:P] = requests
    padded[P:] = fmax + 1  # sentinel: padding packs nowhere
    reqs = padded.reshape(D, Pc, R)
    fronts = np.broadcast_to(frontier, (D,) + frontier.shape)
    caps = np.full(D, max_per_node, dtype=np.int32)
    with tracer.span(
        "pack.shard.dispatch", pods=P, chunks=D, chunk_len=Pc, engine=engine
    ):
        deviceplane.record_footprint(deviceplane.nbytes_of(reqs, fronts, caps))
        with devicetime.track(phase="shard"):
            devicetime.transfer("h2d", reqs, fronts, caps, phase="shard")
            if engine == "sharded":
                ids, counts, _fleet = sharded_batch_pack(
                    mesh, jnp.asarray(reqs), jnp.asarray(fronts), jnp.asarray(caps)
                )
            else:
                ids, counts = ffd_pack_batched(
                    jnp.asarray(reqs), jnp.asarray(fronts), jnp.asarray(caps)
                )
            # the ONE host sync of the mega dispatch, after all chunks
            ids = np.asarray(ids)  # analysis: allow-host-sync
            counts = np.asarray(counts, dtype=np.int64)  # analysis: allow-host-sync
        devicetime.transfer("d2h", ids, counts, phase="shard")
    offsets = np.zeros(D, dtype=np.int64)
    np.cumsum(counts[:-1], out=offsets[1:])
    gids = np.where(ids >= 0, ids + offsets[:, None].astype(np.int32), -1)
    record_shard_padding(
        "pods", P, D * Pc, engine=engine, n_devices=D, chunks=D
    )
    return gids.reshape(-1)[:P].astype(np.int32), int(counts.sum())


def sharded_mega_solve(
    mesh: Optional[Mesh],
    requests: np.ndarray,  # (P, R) int32 pod requests, any order
    alloc: np.ndarray,  # (T, R) int32 allocatable per type
    prices: np.ndarray,  # (T,) f64
    sig_masks: Optional[np.ndarray] = None,  # (S, W) f32 — compat screen
    type_masks: Optional[np.ndarray] = None,  # (T, W) f32
    max_per_node: int = 2**31 - 1,
    engine: Optional[str] = None,
    trace_ctx=None,
) -> dict:
    """One giant-tenant solve at the tensor level: the 500k–1M-pod ×
    10k-type scale path (bench config 12, ``profile_solve --shard``).

    Stages, each tiled so no (P, T, R)-shaped transient materializes:

    1. compat screen (optional): the class's shared viable-type set via
       the type-axis-sharded overlap matmul (``sharded_compat``) — each
       device holds a T-shard, the (S, T) result comes back from the
       output sharding's all-gather, and the class intersection folds
       it to (T,). Tiled further in (TILE_S, TILE_T) VMEM blocks when
       the pallas compat path is enabled (pallas_kernels).
    2. frontier: Pareto points of the viable allocatable rows (F ≪ T).
    3. pack: pod-axis sharded chunk pack (``sharded_pod_pack``).
    4. assign: cheapest viable type per packed node, row-blocked under
       the transient byte budget (``pack.assign_cheapest_types``).

    ``trace_ctx`` (PR-10): a driver thread passes its decision's
    TraceContext so the shard lane's spans land under that decision
    instead of orphaning; on the owning thread adopt degrades to a
    plain span.

    Plan identity: for fixed inputs the result is engine-independent —
    ``unsharded`` is the subsampled-shape parity oracle. Returns the
    plan arrays plus per-stage wall times and the shard padding stats.
    """
    from .pack import assign_cheapest_types, node_usage_from_assignment, pareto_frontier

    reset_shard_stats()
    out: dict = {}
    with tracer.adopt(trace_ctx, "shard.mega.adopt", pods=int(requests.shape[0])):
        with tracer.span("shard.mega", pods=int(requests.shape[0])):
            t0 = time.perf_counter()
            viable = np.ones(alloc.shape[0], dtype=bool)
            if sig_masks is not None and type_masks is not None:
                with tracer.span("shard.mega.compat"):
                    if mesh is not None:
                        # pad the type axis to the mesh multiple (padded
                        # rows are all-zero ⇒ no overlap ⇒ not viable),
                        # sliced back off below — and recorded, never
                        # silent (the pad_t discipline)
                        D = int(mesh.devices.size)
                        T = type_masks.shape[0]
                        Tp = -(-T // D) * D
                        tm = type_masks
                        if Tp != T:
                            tm = np.concatenate(
                                [tm, np.zeros((Tp - T,) + tm.shape[1:], tm.dtype)]
                            )
                        record_shard_padding(
                            "types", T, Tp, accumulate=False, n_devices=D
                        )
                        overlap = sharded_compat(
                            mesh, jnp.asarray(sig_masks), jnp.asarray(tm)
                        )
                        # sync folds the all-gathered (S, T) once
                        compat = (
                            np.asarray(overlap)[:, :T] > 0.0  # analysis: allow-host-sync
                        )
                    else:
                        compat = (sig_masks @ type_masks.T) > 0.0
                    # the merged class admits a type iff EVERY signature
                    # does (solver._prepare_class_jobs class semantics)
                    viable = compat.all(axis=0)
            t1 = time.perf_counter()
            viable_idx = np.flatnonzero(viable)
            if viable_idx.size == 0:
                return {
                    "nodes": 0,
                    "pods": int(requests.shape[0]),
                    "scheduled": 0,
                    "total_price": 0.0,
                    "shard": consume_shard_stats(),
                    "error": "no viable instance type",
                }
            valloc = np.ascontiguousarray(alloc[viable_idx], dtype=np.int32)
            vprices = np.asarray(prices, dtype=np.float64)[viable_idx]
            with tracer.span("shard.mega.frontier"):
                frontier = pareto_frontier(valloc)
            # descending by primary then secondary axis (queue.go:76)
            order = np.lexsort((-requests[:, 1], -requests[:, 0]))
            sorted_reqs = np.ascontiguousarray(requests[order], dtype=np.int32)
            t2 = time.perf_counter()
            node_ids, node_count = sharded_pod_pack(
                mesh, sorted_reqs, frontier, np.int32(max_per_node), engine=engine
            )
            t3 = time.perf_counter()
            with tracer.span("shard.mega.assign", nodes=node_count):
                usage = node_usage_from_assignment(sorted_reqs, node_ids, node_count)
                chosen = assign_cheapest_types(usage, valloc, vprices)
            t4 = time.perf_counter()
            ok = chosen >= 0
            scheduled = int((node_ids >= 0).sum()) - int(
                np.isin(node_ids, np.flatnonzero(~ok)).sum()
            )
            out.update(
                nodes=int(ok.sum()),
                pods=int(requests.shape[0]),
                scheduled=scheduled,
                total_price=float(vprices[chosen[ok]].sum()),
                node_ids=node_ids,
                node_order=order,
                chosen_types=viable_idx[np.maximum(chosen, 0)][ok],
                frontier_rows=int(frontier.shape[0]),
                viable_types=int(viable_idx.size),
                compat_ms=round((t1 - t0) * 1000.0, 2),
                prep_ms=round((t2 - t1) * 1000.0, 2),
                pack_ms=round((t3 - t2) * 1000.0, 2),
                assign_ms=round((t4 - t3) * 1000.0, 2),
                wall_ms=round((t4 - t0) * 1000.0, 2),
                shard=consume_shard_stats(),
            )
    return out


def sharded_prefix_screen(
    mesh: Mesh,
    candidate_loads: jnp.ndarray,  # (N, R) int32, N divisible by mesh size
    candidate_free: jnp.ndarray,  # (N, R) int32
    fleet_free_local: jnp.ndarray,  # (D, R) int32 — per-device fleet shard
    new_node_cap: jnp.ndarray,  # (R,) int32
) -> jnp.ndarray:
    """Fleet-scale consolidation screen for multi-host fleets (SURVEY §5:
    "fleet-level repacking sharded over DCN for >1 host").

    Each device holds one shard of the fleet's per-node free capacity
    (a host's worth of state nodes); the total frees come from a real
    psum collective, then every device evaluates its candidate shard's
    prefixes. Returns (N,) bool like prefix_screen_kernel.

    Prefix sums over the candidate axis need the *global* running sum —
    computed from a psum of shard totals plus an exclusive scan of
    shard-prefix offsets (log-depth, collective-friendly)."""
    axis = mesh.axis_names[0]
    D = mesh.devices.size

    def per_device(loads, free, fleet_local, cap):
        # loads/free: (N/D, R) local shard; fleet_local: (1, R)
        fleet_total = jax.lax.psum(jnp.sum(fleet_local, axis=0), axis_name=axis)
        free_total = jax.lax.psum(jnp.sum(free, axis=0), axis_name=axis)
        local_cum = jnp.cumsum(loads.astype(jnp.float32), axis=0)
        local_free_cum = jnp.cumsum(free.astype(jnp.float32), axis=0)
        # exclusive prefix offset across devices for both running sums
        idx = jax.lax.axis_index(axis)
        shard_load = local_cum[-1]
        shard_free = local_free_cum[-1]
        # all-gather shard totals, mask to devices before this one
        all_loads = jax.lax.all_gather(shard_load, axis_name=axis)  # (D, R)
        all_frees = jax.lax.all_gather(shard_free, axis_name=axis)
        mask = (jnp.arange(D) < idx).astype(jnp.float32)[:, None]
        offset_load = jnp.sum(all_loads * mask, axis=0)
        offset_free = jnp.sum(all_frees * mask, axis=0)
        cum_load = local_cum + offset_load[None, :]
        cum_free = local_free_cum + offset_free[None, :]
        surviving_candidate_free = free_total.astype(jnp.float32)[None, :] - cum_free
        headroom = (
            fleet_total.astype(jnp.float32)[None, :]
            + surviving_candidate_free
            + cap.astype(jnp.float32)[None, :]
        )
        return jnp.all(cum_load <= headroom, axis=-1)

    shard = partial(
        _require_shard_map(),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P()),
        out_specs=P(axis),
    )
    return deviceplane.wrap("sharding.sharded_prefix_screen", jax.jit(shard(per_device)))(
        candidate_loads, candidate_free, fleet_free_local, new_node_cap
    )


def prepare_sharded_catalog(
    mesh: Mesh,
    type_masks: Dict[str, np.ndarray],
    type_has: Dict[str, np.ndarray],
    type_neg: Dict[str, np.ndarray],
    avail: np.ndarray,
) -> tuple:
    """Device-put the catalog side of the compat kernel sharded over the
    mesh's type axis, padded to a multiple of the mesh size. Callers
    cache the result per catalog generation (solver._entry_sharded) so
    the full-catalog transfer happens once, not per solve — the pinned-
    buffer design _entry_device_packed already uses for pallas. Padded
    type rows have no available offering, so they read as disallowed —
    but the padding is never silent: the wasted type slots land in this
    solve's shard stats (and the solver re-records the active catalog's
    padding per solve, cache hits included — see _encode_phase)."""
    axis = mesh.axis_names[0]
    D = int(mesh.devices.size)
    T = avail.shape[0]
    Tp = -(-T // D) * D
    record_shard_padding("types", T, Tp, accumulate=False, n_devices=D)

    def pad_t(a: np.ndarray) -> np.ndarray:
        a = np.asarray(a)
        if a.shape[0] == Tp:
            return a
        pad = np.zeros((Tp - a.shape[0],) + a.shape[1:], dtype=a.dtype)
        return np.concatenate([a, pad], axis=0)

    sh = NamedSharding(mesh, P(axis))
    tm = {k: jax.device_put(pad_t(v), sh) for k, v in type_masks.items()}
    th = {k: jax.device_put(pad_t(v), sh) for k, v in type_has.items()}
    tn = {k: jax.device_put(pad_t(v), sh) for k, v in type_neg.items()}
    av = jax.device_put(pad_t(avail), sh)
    devicetime.transfer(
        "h2d", *tm.values(), *th.values(), *tn.values(), av, phase="shard"
    )
    return tm, th, tn, av, T


def allowed_sharded(
    prepared: tuple,
    sig_arrays: Dict[str, np.ndarray],
    zone_ok: np.ndarray,
    ct_ok: np.ndarray,
    keys: Tuple[str, ...],
):
    """Type-axis-sharded fused compat ∧ offering against a prepared
    (cached, device-resident) catalog: signatures replicate, GSPMD
    propagates the shardings through kernels.allowed_kernel, and the
    (S, T) result's columns come back from an all-gather XLA inserts."""
    from .kernels import allowed_kernel

    tm, th, tn, av, T = prepared
    out = allowed_kernel(
        {k: jnp.asarray(v) for k, v in sig_arrays.items()},
        tm,
        th,
        tn,
        jnp.asarray(zone_ok),
        jnp.asarray(ct_ok),
        av,
        keys,
    )
    return out[:, :T]


def sharded_compat(
    mesh: Mesh,
    sig_masks: jnp.ndarray,  # (S, W) f32 — flattened key masks
    type_masks: jnp.ndarray,  # (T, W) f32
) -> jnp.ndarray:
    """Type-axis-sharded overlap matmul: each device holds a T-shard,
    XLA all-gathers the (S, T) result from the output sharding."""
    axis = mesh.axis_names[0]
    jitted = deviceplane.wrap(
        "sharding.sharded_compat",
        jax.jit(
            lambda q, m: q @ m.T,
            in_shardings=(
                NamedSharding(mesh, P()),  # signatures replicated
                NamedSharding(mesh, P(axis)),  # types sharded
            ),
            out_shardings=NamedSharding(mesh, P(None, axis)),
        ),
    )
    return jitted(sig_masks, type_masks)
