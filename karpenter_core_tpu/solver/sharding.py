"""Multi-chip sharding for the solver (SURVEY §5 distributed mapping).

Scaling axes, in jax.sharding terms:
- **groups** (data-parallel-like): signature groups / zone sub-batches
  pack independently — shard the group axis over the mesh, each device
  scans its groups, ICI collectives reduce fleet totals.
- **types** (tensor-parallel-like): the S×T compat kernel shards the
  type axis; each device computes a T-shard of the masks, results
  all-gather (XLA inserts the collective from shardings).

Fleet-level repack for consolidation reuses the same mesh with a psum
over candidate-subset scores.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .pack import ffd_pack


def make_mesh(n_devices: Optional[int] = None, axis: str = "groups") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def sharded_batch_pack(
    mesh: Mesh,
    requests: jnp.ndarray,  # (G, Pmax, R) int32 — padded groups
    frontiers: jnp.ndarray,  # (G, F, R) int32
    max_per_node: jnp.ndarray,  # (G,) int32
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pack G groups across the mesh; returns (node_ids (G, Pmax),
    node_counts (G,), fleet_total ()). The fleet total is a real ICI
    collective (psum over the groups axis)."""

    def per_device(reqs, fronts, caps):
        node_ids, counts = jax.vmap(
            lambda r, f, c: ffd_pack(r, f, c)
        )(reqs, fronts, caps)
        local_total = jnp.sum(counts)
        fleet_total = jax.lax.psum(local_total, axis_name="groups")
        return node_ids, counts, fleet_total

    shard = partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("groups"), P("groups"), P("groups")),
        out_specs=(P("groups"), P("groups"), P()),
    )
    return jax.jit(shard(per_device))(requests, frontiers, max_per_node)


def sharded_compat(
    mesh: Mesh,
    sig_masks: jnp.ndarray,  # (S, W) f32 — flattened key masks
    type_masks: jnp.ndarray,  # (T, W) f32
) -> jnp.ndarray:
    """Type-axis-sharded overlap matmul: each device holds a T-shard,
    XLA all-gathers the (S, T) result from the output sharding."""
    axis = mesh.axis_names[0]
    jitted = jax.jit(
        lambda q, m: q @ m.T,
        in_shardings=(
            NamedSharding(mesh, P()),  # signatures replicated
            NamedSharding(mesh, P(axis)),  # types sharded
        ),
        out_shardings=NamedSharding(mesh, P(None, axis)),
    )
    return jitted(sig_masks, type_masks)
