"""Multi-chip sharding for the solver (SURVEY §5 distributed mapping).

Scaling axes, in jax.sharding terms:
- **groups** (data-parallel-like): signature groups / zone sub-batches
  pack independently — shard the group axis over the mesh, each device
  scans its groups, ICI collectives reduce fleet totals.
- **types** (tensor-parallel-like): the S×T compat kernel shards the
  type axis; each device computes a T-shard of the masks, results
  all-gather (XLA inserts the collective from shardings).

Fleet-level repack for consolidation reuses the same mesh with a psum
over candidate-subset scores.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .pack import ffd_pack

# jax.shard_map landed at top level only in newer jax; older images ship
# it under jax.experimental.shard_map. Feature-detect once so the
# sharded pack/screen paths work on both (and skip cleanly on neither).
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # analysis: allow-broad-except — no shard_map in this jax
        _shard_map = None


def shard_map_available() -> bool:
    """True when this jax exposes shard_map (top-level or experimental)."""
    return _shard_map is not None


def _require_shard_map():
    if _shard_map is None:
        raise RuntimeError(
            "shard_map is unavailable in this jax build "
            "(neither jax.shard_map nor jax.experimental.shard_map)"
        )
    return _shard_map


def make_mesh(n_devices: Optional[int] = None, axis: str = "groups") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


_MESH: Optional[Mesh] = None


def active_mesh(backend: str) -> Optional[Mesh]:
    """The mesh the solve should shard over, or None for the single-
    device path. KARPENTER_TPU_SHARDED: 'auto' (shard when the resolved
    backend is a multi-chip TPU), 'on' (shard whenever >1 device — how
    the CPU-mesh tests and dryrun drive the integrated path), 'off'."""
    mode = os.environ.get("KARPENTER_TPU_SHARDED", "auto")
    if mode == "off":
        return None
    try:
        n = len(jax.devices())
    except Exception:  # analysis: allow-broad-except — no devices ⇒ single-device path
        return None
    if n < 2 or (mode == "auto" and backend != "tpu"):
        return None
    global _MESH
    if _MESH is None or _MESH.devices.size != n:
        _MESH = make_mesh()
    return _MESH


def sharded_batch_pack(
    mesh: Mesh,
    requests: jnp.ndarray,  # (G, Pmax, R) int32 — padded groups
    frontiers: jnp.ndarray,  # (G, F, R) int32
    max_per_node: jnp.ndarray,  # (G,) int32
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pack G groups across the mesh; returns (node_ids (G, Pmax),
    node_counts (G,), fleet_total ()). The fleet total is a real ICI
    collective (psum over the groups axis)."""

    def per_device(reqs, fronts, caps):
        node_ids, counts = jax.vmap(
            lambda r, f, c: ffd_pack(r, f, c)
        )(reqs, fronts, caps)
        local_total = jnp.sum(counts)
        fleet_total = jax.lax.psum(local_total, axis_name="groups")
        return node_ids, counts, fleet_total

    shard = partial(
        _require_shard_map(),
        mesh=mesh,
        in_specs=(P("groups"), P("groups"), P("groups")),
        out_specs=(P("groups"), P("groups"), P()),
    )
    return jax.jit(shard(per_device))(requests, frontiers, max_per_node)


def sharded_prefix_screen(
    mesh: Mesh,
    candidate_loads: jnp.ndarray,  # (N, R) int32, N divisible by mesh size
    candidate_free: jnp.ndarray,  # (N, R) int32
    fleet_free_local: jnp.ndarray,  # (D, R) int32 — per-device fleet shard
    new_node_cap: jnp.ndarray,  # (R,) int32
) -> jnp.ndarray:
    """Fleet-scale consolidation screen for multi-host fleets (SURVEY §5:
    "fleet-level repacking sharded over DCN for >1 host").

    Each device holds one shard of the fleet's per-node free capacity
    (a host's worth of state nodes); the total frees come from a real
    psum collective, then every device evaluates its candidate shard's
    prefixes. Returns (N,) bool like prefix_screen_kernel.

    Prefix sums over the candidate axis need the *global* running sum —
    computed from a psum of shard totals plus an exclusive scan of
    shard-prefix offsets (log-depth, collective-friendly)."""
    axis = mesh.axis_names[0]
    D = mesh.devices.size

    def per_device(loads, free, fleet_local, cap):
        # loads/free: (N/D, R) local shard; fleet_local: (1, R)
        fleet_total = jax.lax.psum(jnp.sum(fleet_local, axis=0), axis_name=axis)
        free_total = jax.lax.psum(jnp.sum(free, axis=0), axis_name=axis)
        local_cum = jnp.cumsum(loads.astype(jnp.float32), axis=0)
        local_free_cum = jnp.cumsum(free.astype(jnp.float32), axis=0)
        # exclusive prefix offset across devices for both running sums
        idx = jax.lax.axis_index(axis)
        shard_load = local_cum[-1]
        shard_free = local_free_cum[-1]
        # all-gather shard totals, mask to devices before this one
        all_loads = jax.lax.all_gather(shard_load, axis_name=axis)  # (D, R)
        all_frees = jax.lax.all_gather(shard_free, axis_name=axis)
        mask = (jnp.arange(D) < idx).astype(jnp.float32)[:, None]
        offset_load = jnp.sum(all_loads * mask, axis=0)
        offset_free = jnp.sum(all_frees * mask, axis=0)
        cum_load = local_cum + offset_load[None, :]
        cum_free = local_free_cum + offset_free[None, :]
        surviving_candidate_free = free_total.astype(jnp.float32)[None, :] - cum_free
        headroom = (
            fleet_total.astype(jnp.float32)[None, :]
            + surviving_candidate_free
            + cap.astype(jnp.float32)[None, :]
        )
        return jnp.all(cum_load <= headroom, axis=-1)

    shard = partial(
        _require_shard_map(),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P()),
        out_specs=P(axis),
    )
    return jax.jit(shard(per_device))(
        candidate_loads, candidate_free, fleet_free_local, new_node_cap
    )


def prepare_sharded_catalog(
    mesh: Mesh,
    type_masks: Dict[str, np.ndarray],
    type_has: Dict[str, np.ndarray],
    type_neg: Dict[str, np.ndarray],
    avail: np.ndarray,
) -> tuple:
    """Device-put the catalog side of the compat kernel sharded over the
    mesh's type axis, padded to a multiple of the mesh size. Callers
    cache the result per catalog generation (solver._entry_sharded) so
    the full-catalog transfer happens once, not per solve — the pinned-
    buffer design _entry_device_packed already uses for pallas. Padded
    type rows have no available offering, so they read as disallowed."""
    axis = mesh.axis_names[0]
    D = int(mesh.devices.size)
    T = avail.shape[0]
    Tp = -(-T // D) * D

    def pad_t(a: np.ndarray) -> np.ndarray:
        a = np.asarray(a)
        if a.shape[0] == Tp:
            return a
        pad = np.zeros((Tp - a.shape[0],) + a.shape[1:], dtype=a.dtype)
        return np.concatenate([a, pad], axis=0)

    sh = NamedSharding(mesh, P(axis))
    tm = {k: jax.device_put(pad_t(v), sh) for k, v in type_masks.items()}
    th = {k: jax.device_put(pad_t(v), sh) for k, v in type_has.items()}
    tn = {k: jax.device_put(pad_t(v), sh) for k, v in type_neg.items()}
    av = jax.device_put(pad_t(avail), sh)
    return tm, th, tn, av, T


def allowed_sharded(
    prepared: tuple,
    sig_arrays: Dict[str, np.ndarray],
    zone_ok: np.ndarray,
    ct_ok: np.ndarray,
    keys: Tuple[str, ...],
):
    """Type-axis-sharded fused compat ∧ offering against a prepared
    (cached, device-resident) catalog: signatures replicate, GSPMD
    propagates the shardings through kernels.allowed_kernel, and the
    (S, T) result's columns come back from an all-gather XLA inserts."""
    from .kernels import allowed_kernel

    tm, th, tn, av, T = prepared
    out = allowed_kernel(
        {k: jnp.asarray(v) for k, v in sig_arrays.items()},
        tm,
        th,
        tn,
        jnp.asarray(zone_ok),
        jnp.asarray(ct_ok),
        av,
        keys,
    )
    return out[:, :T]


def sharded_compat(
    mesh: Mesh,
    sig_masks: jnp.ndarray,  # (S, W) f32 — flattened key masks
    type_masks: jnp.ndarray,  # (T, W) f32
) -> jnp.ndarray:
    """Type-axis-sharded overlap matmul: each device holds a T-shard,
    XLA all-gathers the (S, T) result from the output sharding."""
    axis = mesh.axis_names[0]
    jitted = jax.jit(
        lambda q, m: q @ m.T,
        in_shardings=(
            NamedSharding(mesh, P()),  # signatures replicated
            NamedSharding(mesh, P(axis)),  # types sharded
        ),
        out_shardings=NamedSharding(mesh, P(None, axis)),
    )
    return jitted(sig_masks, type_masks)
