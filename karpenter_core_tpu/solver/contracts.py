"""Shape contracts for the solver's tensor functions.

A contract declares the dimensional type of a tensor function once, at
the def site, in einops-style letters::

    @contract("P R", "F R", "()", out=("P", "()"))
    @partial(jax.jit, static_argnames=("k_open",))
    def ffd_pack(requests, frontier, max_pods_per_node, k_open=16): ...

Same letter = same size, bound left to right across arguments and then
checked on the outputs; integer tokens pin an exact size; ``"()"``
accepts a 0-d array or a Python scalar; ``None`` skips an argument
(dicts, static config). Letters that first appear in ``out`` bind free
(e.g. the frontier count of ``pareto_frontier``) — only their arity and
already-bound letters are checked.

Two consumers:

- **runtime asserts** (cheap: a handful of int comparisons per call,
  zero device work — shapes live on the host even for jax arrays),
  enabled under tests via ``KARPENTER_TPU_SHAPE_CONTRACTS=1`` or
  :func:`enable`; disabled by default so production solves pay one
  truthiness check;
- **static verification**: ``karpenter_core_tpu/analysis`` binds each
  letter to a distinct prime and runs ``jax.eval_shape`` over the
  registry (``python -m karpenter_core_tpu.analysis --contracts``) — no
  kernels execute, but every contract is checked against the real
  traced output shapes.

Keep this module dependency-free (no jax/numpy import): it is imported
by every solver module at startup.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

_ENABLED = os.environ.get("KARPENTER_TPU_SHAPE_CONTRACTS", "0") not in ("", "0", "false", "off")

#: all contracted functions, for the static verifier:
#: dicts with fn (undecorated), wrapper, name, in_specs, out_spec,
#: dtypes, example (optional builder for eval_shape inputs), static (kwargs)
REGISTRY: List[dict] = []


class ContractError(TypeError):
    """A tensor function was called with (or returned) shapes violating
    its declared contract."""


def enable(on: bool = True) -> None:
    """Flip runtime checking (tests use this; production leaves it off)."""
    global _ENABLED
    _ENABLED = on


def enabled() -> bool:
    return _ENABLED


def _parse(spec: Optional[str]) -> Optional[Tuple[str, ...]]:
    if spec is None:
        return None
    spec = spec.strip()
    if spec in ("()", ""):
        return ()
    return tuple(spec.split())


def _shape_of(value: Any) -> Optional[Tuple[int, ...]]:
    shape = getattr(value, "shape", None)
    if shape is not None:
        try:
            return tuple(int(d) for d in shape)
        except TypeError:
            return None  # symbolic dims — leave to eval_shape mode
    if isinstance(value, (int, float, bool)):
        return ()  # Python scalar ⇒ 0-d
    return None


def _check_one(
    name: str, what: str, dims: Tuple[str, ...], value: Any, env: Dict[str, int]
) -> None:
    shape = _shape_of(value)
    if shape is None:
        raise ContractError(
            f"{name}: {what} expected an array of rank {len(dims)} "
            f"({' '.join(dims) or 'scalar'}), got {type(value).__name__}"
        )
    if len(shape) != len(dims):
        raise ContractError(
            f"{name}: {what} expected rank {len(dims)} ({' '.join(dims) or 'scalar'}), "
            f"got shape {shape}"
        )
    for letter, actual in zip(dims, shape):
        if letter in ("*", "_"):
            continue
        if letter.isdigit():
            if actual != int(letter):
                raise ContractError(
                    f"{name}: {what} dim '{letter}' expected {letter}, got {actual} "
                    f"(shape {shape})"
                )
            continue
        bound = env.get(letter)
        if bound is None:
            env[letter] = actual
        elif bound != actual:
            raise ContractError(
                f"{name}: {what} dim '{letter}'={actual} contradicts "
                f"'{letter}'={bound} bound earlier (shape {shape})"
            )


def _check_out(name: str, out_specs, result: Any, env: Dict[str, int]) -> None:
    if out_specs is None:
        return
    if isinstance(out_specs, str):
        parts: List[Optional[str]] = [out_specs]
        values: tuple = (result,)
    else:
        parts = list(out_specs)
        values = tuple(result) if isinstance(result, (tuple, list)) else (result,)
        if len(parts) != len(values):
            raise ContractError(
                f"{name}: output expected {len(parts)} values, got {len(values)}"
            )
    for i, (spec, value) in enumerate(zip(parts, values)):
        dims = _parse(spec)
        if dims is None:
            continue
        _check_one(name, f"output[{i}]", dims, value, env)


def contract(
    *in_specs: Optional[str],
    out=None,
    dtypes: Optional[Sequence[str]] = None,
    example=None,
    static: Optional[dict] = None,
    eval_shape: bool = True,
):
    """Declare a shape contract. ``in_specs`` align with positional
    parameters; ``out`` is a spec or tuple of specs; ``dtypes`` (aligned
    with in_specs, default int32) and ``example``/``static`` feed the
    eval_shape verifier for functions whose inputs a plain spec cannot
    describe (dict pytrees, static kwargs). ``eval_shape=False`` marks
    host/numpy functions that cannot be abstractly traced — they keep
    runtime checks but are skipped by the static verifier."""
    parsed_in = [_parse(s) for s in in_specs]

    def deco(fn):
        name = getattr(fn, "__name__", str(fn))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _ENABLED:
                return fn(*args, **kwargs)
            env: Dict[str, int] = {}
            for i, dims in enumerate(parsed_in):
                if dims is None or i >= len(args):
                    continue
                _check_one(name, f"arg[{i}]", dims, args[i], env)
            result = fn(*args, **kwargs)
            _check_out(name, out, result, env)
            return result

        wrapper.__shape_contract__ = {
            "name": name,
            "fn": fn,
            "in_specs": tuple(in_specs),
            "out": out,
            "dtypes": tuple(dtypes) if dtypes is not None else None,
            "example": example,
            "static": dict(static or {}),
            "eval_shape": eval_shape,
        }
        REGISTRY.append(wrapper.__shape_contract__)
        return wrapper

    return deco
