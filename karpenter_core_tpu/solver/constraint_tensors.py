"""Residual constraint algebra, tensorized (ISSUE 12 tentpole).

Host ports and CSI volume attach limits are the reference's per-node
*stateful* scheduling constraints (hostportusage.go, volumeusage.go).
This module turns both into array form so the batched pack kernels can
enforce them without a per-pod host walk:

Host ports → pseudo-resource columns
    ``HostPort.matches`` (same proto+port; IPs conflict when equal or
    either is unspecified) has an exact additive encoding over two
    feature families per (proto, port) pair:

    - the *pair* axis with capacity ``PORT_K``: a wildcard-IP port loads
      the full ``PORT_K``, a specific-IP port loads 1. Two wildcards
      (2K > K), or a wildcard next to any specific IP (K+1 > K), exceed
      the capacity; distinct specific IPs coexist (m ≤ K).
    - one *exact-IP* axis per specific IP with capacity 1: two pods (or
      a pod and a node reservation) on the same (proto, port, ip)
      collide.

    Appending these columns to a pack job's request matrix and frontier
    (or to the existing-node free matrix) makes ``ffd_pack`` /
    ``pack_existing`` enforce port conflicts natively — state rides the
    scan carry, so within-dispatch interleavings are exact.

Volumes → per-node admissibility masks + ephemeral driver axes
    A signature group's claim-backed PVCs are one *shared* id set (the
    claim names ride the signature), so any number of its pods charge a
    node's per-driver counters once — a boolean (group, node) mask over
    the union check, with the placement charging the overlay a single
    time. Generic-ephemeral volumes mint one PVC per pod, so their
    per-driver counts are exactly additive and become driver columns in
    the free matrix.

Both encoders are property-tested against the scalar reference checks
(``HostPortUsage.conflicts`` / ``VolumeUsage.exceeds_limits``) in
tests/test_constraint_tensors.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..scheduling.hostports import UNSPECIFIED, HostPort
from ..scheduling.volumes import Volumes

# capacity of a (proto, port) pair axis; must exceed any realistic
# specific-IP count per node AND stay far below the int32 pack
# saturation (2^30) so sums never overflow
PORT_K = np.int32(1 << 20)


# ---------------------------------------------------------------------------
# canonical port forms


def canonical_ports(pod) -> Tuple[Tuple[str, int, str], ...]:
    """Sorted (protocol, port, ip) triples of a pod's host ports —
    the content identity stateful job-memo keys carry. Empty host_ip
    defaults to 0.0.0.0 (hostportusage.go:93)."""
    out = set()
    spec = pod.spec
    for c in list(spec.containers) + list(spec.init_containers):
        for p in c.ports:
            if p.host_port:
                out.add((p.protocol or "TCP", int(p.host_port), p.host_ip or "0.0.0.0"))
    return tuple(sorted(out))


def ports_from_triples(triples: Sequence[Tuple[str, int, str]]) -> List[HostPort]:
    return [HostPort(ip=ip, port=port, protocol=proto) for proto, port, ip in triples]


def ports_conflict(
    a: Sequence[Tuple[str, int, str]], b: Sequence[Tuple[str, int, str]]
) -> bool:
    """Any pair across the two canonical triple sets conflicts — the
    scalar reference predicate (HostPort.matches), used by the merge
    pass's pairwise guard where sets are tiny."""
    if not a or not b:
        return False
    pa, pb = ports_from_triples(a), ports_from_triples(b)
    return any(x.matches(y) for x in pa for y in pb)


# ---------------------------------------------------------------------------
# port feature axes


class PortFeatures:
    """Feature-axis layout for a universe of canonical port triples.

    ``features`` lists the axes in a stable sorted order: the
    (proto, port, None) pair axis first, then one (proto, port, ip)
    axis per specific IP observed. ``caps`` is the per-axis fresh-node
    capacity (PORT_K for pair axes, 1 for exact-IP axes)."""

    __slots__ = ("features", "index", "caps")

    def __init__(self, triple_sets: Sequence[Sequence[Tuple[str, int, str]]]):
        feats = set()
        for triples in triple_sets:
            for proto, port, ip in triples:
                feats.add((proto, port, None))
                if ip not in UNSPECIFIED:
                    feats.add((proto, port, ip))
        self.features: List[tuple] = sorted(
            feats, key=lambda f: (f[0], f[1], f[2] is not None, f[2] or "")
        )
        self.index = {f: i for i, f in enumerate(self.features)}
        self.caps = np.array(
            [1 if f[2] is not None else int(PORT_K) for f in self.features],
            dtype=np.int32,
        )

    @property
    def count(self) -> int:
        return len(self.features)

    def load_row(self, triples: Sequence[Tuple[str, int, str]]) -> np.ndarray:
        """(F,) int32 load vector of one pod's canonical ports. A pod's
        OWN ports never conflict with each other (the scalar check skips
        the pod's own reservation entry), so per pair axis the load
        saturates at PORT_K: any wildcard ⇒ exactly K, else one unit per
        distinct specific IP."""
        row = np.zeros(self.count, dtype=np.int64)
        wild_pairs = set()
        for proto, port, ip in triples:
            if ip in UNSPECIFIED:
                wild_pairs.add((proto, port))
            else:
                row[self.index[(proto, port, None)]] += 1
                row[self.index[(proto, port, ip)]] = 1
        for pair in wild_pairs:
            row[self.index[pair + (None,)]] = int(PORT_K)
        return np.minimum(row, np.int64(2**30)).astype(np.int32)

    def load_matrix(
        self, triple_sets: Sequence[Sequence[Tuple[str, int, str]]]
    ) -> np.ndarray:
        """(G, F) int32 — one row per port set."""
        if not self.count:
            return np.zeros((len(triple_sets), 0), dtype=np.int32)
        return np.stack([self.load_row(t) for t in triple_sets])

    def free_row(self, reserved: Sequence[HostPort]) -> np.ndarray:
        """(F,) int32 remaining capacity of a node already reserving
        ``reserved``: a wildcard reservation zeroes its pair axis (and
        every exact-IP axis of the pair); a specific reservation takes
        one pair unit and its exact axis."""
        free = self.caps.astype(np.int64).copy()
        for hp in reserved:
            pair = (hp.protocol, hp.port, None)
            pi = self.index.get(pair)
            if pi is None:
                continue  # port outside the batch universe: never probed
            if hp.ip in UNSPECIFIED:
                free[pi] = 0
                for f, fi in self.index.items():
                    if f[2] is not None and f[0] == hp.protocol and f[1] == hp.port:
                        free[fi] = 0
            else:
                free[pi] -= 1
                ei = self.index.get((hp.protocol, hp.port, hp.ip))
                if ei is not None:
                    free[ei] -= 1
        return np.maximum(free, 0).astype(np.int32)

    def free_matrix(self, reserved_per_node: Sequence[Sequence[HostPort]]) -> np.ndarray:
        """(M, F) int32 — one row per node's reserved port list."""
        if not self.count:
            return np.zeros((len(reserved_per_node), 0), dtype=np.int32)
        return np.stack([self.free_row(r) for r in reserved_per_node])


def node_reserved_ports(state_node) -> List[HostPort]:
    """Flattened HostPort reservations of a StateNode (its
    HostPortUsage map), the free_matrix input."""
    out: List[HostPort] = []
    for entries in state_node.host_port_usage.reserved.values():
        out.extend(entries)
    return out


def port_conflict_matrix(
    group_triples: Sequence[Sequence[Tuple[str, int, str]]],
    reserved_per_node: Sequence[Sequence[HostPort]],
) -> np.ndarray:
    """(G, M) bool — group g's port set conflicts with node m's existing
    reservations (≥1 matching pair). The vectorized twin of running
    ``HostPortUsage.conflicts`` per (group, node); equality with the
    scalar check is gated in tests/test_constraint_tensors.py."""
    feats = PortFeatures(group_triples)
    G, M = len(group_triples), len(reserved_per_node)
    if not feats.count or not G or not M:
        return np.zeros((G, M), dtype=bool)
    loads = feats.load_matrix(group_triples).astype(np.int64)  # (G, F)
    free = feats.free_matrix(reserved_per_node).astype(np.int64)  # (M, F)
    return (loads[:, None, :] > free[None, :, :]).any(axis=2)


# ---------------------------------------------------------------------------
# volumes


class GroupVolumes:
    """One signature group's resolved volume demand.

    ``shared``: driver → set of pvc ids the whole group mounts (claim-
    backed volumes: every pod names the same claims, so a node is
    charged once no matter how many of the group's pods land on it).
    ``eph_counts``: driver → per-POD count of generic-ephemeral PVCs
    (ids embed the pod name → exactly additive per pod).
    ``unresolved``: a referenced PVC was missing — the oracle's
    existing-node add() fails with the KeyError for every node, so the
    tensor path marks every existing node inadmissible (new nodes carry
    no volume check, matching SchedulingNodeClaim)."""

    __slots__ = ("shared", "eph_counts", "unresolved")

    def __init__(self) -> None:
        self.shared = Volumes()
        self.eph_counts: Dict[str, int] = {}
        self.unresolved = False

    @property
    def empty(self) -> bool:
        return not self.shared and not self.eph_counts and not self.unresolved

    def drivers(self) -> set:
        return set(self.shared) | set(self.eph_counts)


def resolve_group_volumes(kube_client, group) -> GroupVolumes:
    """Resolve one group's volumes through the PVC → StorageClass → CSI
    driver chain (scheduling/volumes.py get_volumes semantics, evaluated
    once per signature instead of per pod)."""
    from ..scheduling.volumes import _default_storage_class, _resolve_driver

    gv = GroupVolumes()
    pod = group.exemplar
    if kube_client is None:
        return gv  # the oracle skips volume checks without a client too
    default_sc = None
    have_default = False
    for volume in pod.spec.volumes:
        if volume.persistent_volume_claim:
            pvc = kube_client.get(
                "PersistentVolumeClaim",
                volume.persistent_volume_claim,
                namespace=pod.namespace,
            )
            if pvc is None:
                gv.unresolved = True
                continue
            if pvc.storage_class_name is None and not have_default:
                default_sc, have_default = _default_storage_class(kube_client), True
            driver = _resolve_driver(
                kube_client, pvc.volume_name, pvc.storage_class_name or default_sc
            )
            if driver:
                gv.shared.add(driver, f"{pod.namespace}/{volume.persistent_volume_claim}")
        elif volume.ephemeral:
            if not have_default:
                default_sc, have_default = _default_storage_class(kube_client), True
            driver = _resolve_driver(kube_client, "", default_sc)
            if driver:
                gv.eph_counts[driver] = gv.eph_counts.get(driver, 0) + 1
    return gv


def volume_admit_row(
    gv: GroupVolumes, node_volumes: Volumes, csi_limits: Dict[str, int]
) -> bool:
    """Would mounting the group's shared set plus ONE pod's ephemeral
    PVCs keep every driver under the node's limit? (The per-pod
    ephemeral tail is charged additively by the pack axes; this row is
    the ≥1-pod admissibility gate.)"""
    if gv.unresolved:
        return False
    # every driver of the would-be union — including drivers only the
    # NODE mounts (an already-over-limit node rejects any volume-bearing
    # pod, exactly like exceeds_limits' union walk)
    for driver in gv.drivers() | set(node_volumes):
        limit = csi_limits.get(driver)
        if limit is None:
            continue
        mounted = set(node_volumes.get(driver, ()))
        would = len(mounted | set(gv.shared.get(driver, ()))) + gv.eph_counts.get(
            driver, 0
        )
        if would > limit:
            return False
    return True


def volume_admit_matrix(
    group_vols: Sequence[GroupVolumes], nodes: Sequence
) -> np.ndarray:
    """(G, M) bool — group g may place ≥1 pod on state node m under the
    node's CSI attach limits. The vectorized-shape twin of
    ``VolumeUsage.exceeds_limits`` per (group, node); equality with the
    scalar check is gated in tests/test_constraint_tensors.py."""
    G, M = len(group_vols), len(nodes)
    out = np.ones((G, M), dtype=bool)
    for m, n in enumerate(nodes):
        vu = n.volume_usage
        for g, gv in enumerate(group_vols):
            out[g, m] = volume_admit_row(gv, vu.volumes, vu.csi_limits)
    return out


def eph_free_columns(
    drivers: Sequence[str], nodes: Sequence, overlays: Optional[Dict[int, Volumes]] = None
) -> np.ndarray:
    """(M, D) int32 remaining attach slots per node per driver, for the
    ephemeral-volume pack axes: limit − |mounted ∪ overlay| (saturating
    at the int32 pack ceiling for unlimited drivers)."""
    M = len(nodes)
    out = np.full((M, len(drivers)), 2**30 - 1, dtype=np.int64)
    for m, n in enumerate(nodes):
        vu = n.volume_usage
        over = overlays.get(m) if overlays else None
        for d, driver in enumerate(drivers):
            limit = vu.csi_limits.get(driver)
            if limit is None:
                continue
            mounted = set(vu.volumes.get(driver, ()))
            if over:
                mounted |= set(over.get(driver, ()))
            out[m, d] = max(int(limit) - len(mounted), 0)
    return out.astype(np.int32)


# ---------------------------------------------------------------------------
# disruption-screen axes (tpu_repack): sound necessary-condition columns
#
# The capacity screens require load ≤ feasible-headroom to be NECESSARY
# for true feasibility (k_hi == 0 proves the no-op with zero
# simulations), so appended axes must UNDER-approximate displaced load
# and OVER-approximate surviving capacity:
#  - port loads dedup per candidate node (ports that coexisted on one
#    node never conflict pairwise more than their feature encoding) and
#    capacity counts every surviving node's conflict-free slots;
#  - volume loads dedup pvc ids across the WHOLE candidate set (a pvc
#    appearing on two candidates charges only the first), capacity
#    treats unlimited drivers as unbounded.


def screen_axes_for_candidates(candidates: Sequence, kube_client=None):
    """→ (feats, drivers, loads_ext (N, F+D), free_ext (N, F+D),
    new_cap_ext (F+D,)) — the stateful columns screen kernels append to
    their resource matrices; every array empty-width when the
    candidates carry no ports/volumes."""
    from ..utils import pod as podutils

    triples_per_cand: List[list] = []
    pvcs_per_cand: List[Volumes] = []
    for c in candidates:
        triples: list = []
        vols = Volumes()
        for p in c.pods or ():
            if not podutils.is_reschedulable(p):
                continue
            triples.extend(canonical_ports(p))
            if kube_client is not None and p.spec.volumes:
                try:
                    from ..scheduling.volumes import get_volumes

                    vols.insert(get_volumes(kube_client, p))
                except KeyError:
                    pass  # unresolvable: charge nothing (load under-approx)
        triples_per_cand.append(triples)
        pvcs_per_cand.append(vols)

    feats = PortFeatures(triples_per_cand)
    drivers = sorted({d for v in pvcs_per_cand for d in v})
    N = len(candidates)
    F, D = feats.count, len(drivers)
    loads = np.zeros((N, F + D), dtype=np.int32)
    free = np.zeros((N, F + D), dtype=np.int32)
    for i, c in enumerate(candidates):
        if F:
            loads[i, :F] = feats.load_row(triples_per_cand[i])
            free[i, :F] = feats.free_row(node_reserved_ports(c.state_node))
    if D:
        seen: Dict[str, set] = {d: set() for d in drivers}
        for i, c in enumerate(candidates):
            for d, driver in enumerate(drivers):
                ids = set(pvcs_per_cand[i].get(driver, ())) - seen[driver]
                seen[driver] |= ids  # global dedup: later candidates charge 0
                loads[i, F + d] = len(ids)
        free[:, F:] = eph_free_columns(drivers, [c.state_node for c in candidates])
    new_cap = np.concatenate(
        [feats.caps, np.full(D, 2**30 - 1, dtype=np.int32)]
    ) if F + D else np.zeros(0, dtype=np.int32)
    return feats, drivers, loads, free, new_cap


def screen_axes_for_fleet(feats: PortFeatures, drivers: Sequence[str], nodes) -> np.ndarray:
    """(F+D,) int32 aggregated surviving-fleet capacity on the stateful
    axes (sum of per-node free — an over-approximation of placeable
    slots, which is the sound direction for the screens)."""
    F, D = feats.count, len(drivers)
    total = np.zeros(F + D, dtype=np.int64)
    for n in nodes:
        if F:
            total[:F] += feats.free_row(node_reserved_ports(n))
        if D:
            total[F:] += eph_free_columns(drivers, [n])[0]
    return np.minimum(total, 2**30).astype(np.int32)
