"""Bucketed, vectorized cross-group merge engine (ISSUE 2 tentpole).

The scalar merge pass (`TPUScheduler._merge_scalar`) is a pure-Python
O(N·K) pairwise first-fit loop: per candidate pair it runs a dozen small
numpy ops, two fingerprint lookups, and a Requirements rebuild. PR 1's
tracer attributed ~75% of config-2 host time to it. This module keeps
the exact first-fit semantics (the scalar loop is the semantic twin of
the Go oracle's shared-node behavior) but restructures the work:

Phase 1 — bucket (host, `pack.merge.bucket`): records group by
(encoding, pool) identity — the first checks the scalar loop makes —
and each bucket precomputes stacked arrays: usage ``(N, R)``, seed
``alloc_cap (N, R)``, bit-packed ``(N, ceil(T/8))`` screen masks
(viable ∧ self-fits ∧ self-offering — each a *necessary* condition of
the pair checks, see below), zone/capacity-type masks, zone-pin ids,
and interned requirement fingerprints backed by a dense
intersects matrix seeded from the solver's ``_intersects_cache`` —
computed once per distinct fingerprint pair instead of per record pair.

Phase 2 — screen + apply (`pack.merge.screen` / `pack.merge.apply`):
records run in the global sorted order. Each record's full candidate
row over its bucket's open clusters is computed in one broadcast:
zone-pin agreement, nonempty zone/ct intersections, pinned-zone bit,
bitwise-AND of the packed type masks, the combined-usage-vs-
min(alloc_cap) reject, and the exact requirements-intersects lookup.
Only the (typically tiny) surviving candidate list is walked in Python
— in cluster-creation order, preserving first-fit — through
``TPUScheduler._merge_pair_exact``, the same exact tail (combined-load
fits against ``_alloc_full``, offering availability on the intersected
masks, per-node hostname limits, Requirements union) the scalar engine
uses, so the two engines cannot drift.

Screen soundness: every vectorized reject is a necessary condition of
the scalar accept. The packed per-record mask ANDs ``viable`` with
"this record's own usage fits the type" and "the type has an available
offering within this record's own zone/ct masks"; a cluster's mask is
the AND over members. Combined usage ≥ each member's usage and the
intersected zone/ct masks ⊆ each side's own, so any type passing the
scalar's combined fits ∧ off_ok check sets the bit on every member and
on the record — the AND is nonzero. The intersects lookup is *exact*
(it is the scalar's own cached combined-fingerprint check, interned),
so the apply tail skips it.

Engine selection: ``KARPENTER_TPU_MERGE_ENGINE={vector,scalar}``
(default vector; scalar is the escape hatch and the parity reference).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from .contracts import contract
from ..tracing import tracer

_ENGINES = ("vector", "scalar")

# bucket-local fits precompute walks records in blocks so the
# (block, T, R) broadcast stays small
_FITS_BLOCK = 128

# optimistic pairwise screen cap: above this many records per bucket the
# (N, N, R) combined-usage broadcast outgrows its win — fall back to the
# per-record screen
_PAIR_MAX = 768


def merge_engine() -> str:
    """Active merge engine (env escape hatch; unknown values → vector)."""
    eng = os.environ.get("KARPENTER_TPU_MERGE_ENGINE", "vector").strip().lower()
    return eng if eng in _ENGINES else "vector"


class _Bucket:
    """One (encoding, pool) class: stacked per-record tensors plus the
    live vectorized state of its open (screenable) merge clusters."""

    __slots__ = (
        "enc",
        "Z",
        "T",
        "zone_index",
        "usage",
        "alloc_cap",
        "zone_ok",
        "ct_ok",
        "zid",
        "screen8",
        "rec_fp",
        "fp_ids",
        "fps",
        "fp_reqs",
        "imat",
        "k",
        "cl_list",
        "cl_usage",
        "cl_alloc_cap",
        "cl_zone_ok",
        "cl_ct_ok",
        "cl_zid",
        "cl_screen8",
        "cl_fp",
        "cl_seed",
        "dirty",
        "pair_cand",
    )

    def __init__(self, solver, records: List[dict], idxs: List[int], scan_cap: int):
        r0 = records[idxs[0]]
        enc = r0["enc"]
        self.enc = enc
        T = len(enc.instance_types)
        Z = len(enc.zones)
        self.Z = Z
        self.T = T
        self.zone_index = {z: zi for zi, z in enumerate(enc.zones)}
        N = len(idxs)
        R = len(r0["usage"])

        self.usage = np.empty((N, R), dtype=np.int64)
        self.alloc_cap = np.empty((N, R), dtype=np.int64)
        zone_ok = np.empty((N, Z), dtype=bool)
        ct_ok = np.empty((N, len(enc.capacity_types)), dtype=bool)
        self.zid = np.empty(N, dtype=np.int32)
        for j, i in enumerate(idxs):
            r = records[i]
            self.usage[j] = r["usage"]
            self.alloc_cap[j] = r["alloc_cap"]
            zone_ok[j] = r["zone_ok"]
            ct_ok[j] = r["ct_ok"]
            self.zid[j] = self.zone_index[r["zone"]] if r["zone"] is not None else -1
        self.zone_ok = zone_ok
        self.ct_ok = ct_ok

        # packed screen rows (viable ∧ self-fits ∧ self-offering): a pure
        # function of each record's content, so records carrying a job-
        # memo identity reuse last tick's row (solver/incremental.py)
        # instead of re-broadcasting the (N, T, R) fits check
        ws = getattr(solver, "_warm", None)
        stats = getattr(solver, "_cstats", None)
        rkeys = [records[i].get("_rkey") for i in idxs]
        self.screen8 = np.empty((N, (T + 7) // 8), dtype=np.uint8)
        missing: List[int] = []
        if ws is None:
            missing = list(range(N))
        else:
            for j, rk in enumerate(rkeys):
                row = ws.screen_rows.get(rk, stats) if rk is not None else None
                if row is None:
                    missing.append(j)
                else:
                    self.screen8[j] = row

        if missing:
            M = len(missing)
            viable = np.empty((M, T), dtype=bool)
            for k, j in enumerate(missing):
                viable[k] = records[idxs[j]]["viable"]
            # self-fits: types holding each record's OWN usage — combined
            # usage dominates every member's, so this is a sound screen bit
            alloc = solver._alloc_full(enc, r0["daemon"])
            usage_m = self.usage[missing]
            fits = np.empty((M, T), dtype=bool)
            for s in range(0, M, _FITS_BLOCK):
                e = min(s + _FITS_BLOCK, M)
                fits[s:e] = np.all(
                    usage_m[s:e, None, :] <= alloc[None, :, :], axis=-1
                )

            # self-offering: types with an available offering within the
            # record's own zone/ct masks (zone-pin narrows to one zone);
            # records of one pack job share masks, so combos dedupe hard
            off = np.empty((M, T), dtype=bool)
            combos: Dict[tuple, np.ndarray] = {}
            avail = enc.offering_avail
            for k, j in enumerate(missing):
                if self.zid[j] >= 0:
                    zsel = np.zeros(Z, dtype=bool)
                    zsel[self.zid[j]] = True
                else:
                    zsel = zone_ok[j]
                ckey = (zsel.tobytes(), ct_ok[j].tobytes())
                v = combos.get(ckey)
                if v is None:
                    v = avail[:, zsel][:, :, ct_ok[j]].any(axis=(1, 2))
                    combos[ckey] = v
                off[k] = v

            sub8 = np.packbits(viable & fits & off, axis=1)
            for k, j in enumerate(missing):
                self.screen8[j] = sub8[k]
                if ws is not None and rkeys[j] is not None:
                    # the only solver state read is _alloc_full's
                    # content-addressed (enc, daemon) table — both are
                    # fixed by the record's _rkey (job-key identity)
                    # analysis: allow-cache-key(solver)
                    ws.screen_rows.put(rkeys[j], sub8[k].copy(), stats)

        # requirement fingerprints interned per bucket; the intersects
        # matrix is EXACT (the scalar's own check, memoized per distinct
        # pair) and lazily filled, seeded from solver._intersects_cache
        self.fp_ids: Dict[tuple, int] = {}
        self.fps: List[tuple] = []
        self.fp_reqs: List[object] = []
        self.imat = np.full((16, 16), -1, dtype=np.int8)
        self.rec_fp = np.empty(N, dtype=np.int32)
        for j, i in enumerate(idxs):
            merged = records[i]["merged"]
            self.rec_fp[j] = (
                -1 if merged is None else self._intern(merged.fingerprint(), merged)
            )

        # open-cluster state (only the globally screenable prefix)
        cap = scan_cap
        self.k = 0
        self.cl_list: List[dict] = []
        self.cl_usage = np.empty((cap, R), dtype=np.int64)
        self.cl_alloc_cap = np.empty((cap, R), dtype=np.int64)
        self.cl_zone_ok = np.empty((cap, Z), dtype=bool)
        self.cl_ct_ok = np.empty((cap, ct_ok.shape[1]), dtype=bool)
        self.cl_zid = np.empty(cap, dtype=np.int32)
        self.cl_screen8 = np.empty((cap, self.screen8.shape[1]), dtype=np.uint8)
        self.cl_fp = np.empty(cap, dtype=np.int32)
        # optimistic screen state: while no cluster of this bucket has
        # absorbed anything, every open cluster is bit-identical to its
        # seed record, so the per-record screen is a row gather from ONE
        # pairwise record×record candidate matrix (computed lazily).
        # The first absorb sets ``dirty`` and the bucket falls back to
        # the per-record broadcast (screen_candidates) for good.
        self.cl_seed = np.empty(cap, dtype=np.int64)
        self.dirty = False
        self.pair_cand: Optional[np.ndarray] = None

    # -- fingerprint interning / exact intersects lookups ---------------

    def _intern(self, fp: tuple, reqs) -> int:
        fid = self.fp_ids.get(fp)
        if fid is None:
            fid = len(self.fps)
            self.fp_ids[fp] = fid
            self.fps.append(fp)
            self.fp_reqs.append(reqs)
            if fid >= self.imat.shape[0]:
                grown = np.full((2 * fid, 2 * fid), -1, dtype=np.int8)
                grown[: self.imat.shape[0], : self.imat.shape[1]] = self.imat
                self.imat = grown
        return fid

    def _intersects_row(self, solver, cl_fp: np.ndarray, rid: int) -> np.ndarray:
        """(len(cl_fp),) bool of exact Requirements.intersects verdicts
        between each cluster fingerprint and the record's, via the dense
        matrix; unknown pairs compute once and land in the matrix AND in
        the solver's cross-engine ``_intersects_cache``."""
        vals = self.imat[cl_fp, rid]
        unknown = np.flatnonzero(vals < 0)
        if unknown.size:
            cache = solver._intersects_cache
            fp_r, req_r = self.fps[rid], self.fp_reqs[rid]
            for u in unknown:
                aid = int(cl_fp[u])
                key = (self.fps[aid], fp_r)
                ok = cache.get(key)
                if ok is None:
                    ok = self.fp_reqs[aid].intersects(req_r) is None
                    # fp_reqs[i] is the Requirements object interned
                    # UNDER fps[i] (same index, _intern): the key's
                    # fingerprints are content addresses of exactly the
                    # two objects intersected; cl_fp/rid/imat only select
                    # which interned pair is being resolved
                    # analysis: allow-cache-key(self.fp_reqs, self.imat, cl_fp, rid)
                    cache[key] = ok
                    cache[(fp_r, self.fps[aid])] = ok
                v = np.int8(1 if ok else 0)
                self.imat[aid, rid] = v
                self.imat[rid, aid] = v
                vals[u] = v
        return vals > 0

    def pair_candidates(self, solver) -> np.ndarray:
        """(N, N) screen verdicts between every record pair of this
        bucket, condition-for-condition identical to screen_candidates
        PLUS the exact intersects lookup — valid against any cluster
        that is still bit-identical to its seed record (no absorbs).
        Computed once per bucket, lazily."""
        if self.pair_cand is not None:
            return self.pair_cand
        N = self.usage.shape[0]
        zid = self.zid
        # zone-pin agreement (cluster axis = columns / seeds)
        cand = (zid[None, :] == -1) | (zid[:, None] == -1) | (
            zid[None, :] == zid[:, None]
        )
        # both sides carry a requirement fingerprint
        cand &= (self.rec_fp[:, None] >= 0) & (self.rec_fp[None, :] >= 0)
        zo = self.zone_ok.astype(np.float32)
        co = self.ct_ok.astype(np.float32)
        cand &= (zo @ zo.T) > 0
        cand &= (co @ co.T) > 0
        # the effective pinned zone must survive the intersection
        if self.Z:
            eff = np.where(zid[None, :] >= 0, zid[None, :], zid[:, None])
            effc = np.clip(eff, 0, self.Z - 1)
            rows = np.arange(N)
            zi_at = self.zone_ok[rows[:, None], effc]
            zj_at = self.zone_ok[rows[None, :], effc]
            cand &= (eff < 0) | (zi_at & zj_at)
        # packed screen masks overlap (viable ∧ fits ∧ offering)
        sb = np.unpackbits(self.screen8, axis=1)[:, : self.T].astype(np.float32)
        cand &= (sb @ sb.T) > 0
        # combined usage within both sides' alloc_cap seeds
        cand &= np.all(
            self.usage[:, None, :] + self.usage[None, :, :]
            <= np.minimum(self.alloc_cap[:, None, :], self.alloc_cap[None, :, :]),
            axis=-1,
        )
        # exact pairwise intersects via the interned fingerprint matrix
        # (fills the same imat / cross-solve cache the fallback uses)
        fps = np.unique(self.rec_fp[self.rec_fp >= 0])
        for fid in fps:
            self._intersects_row(solver, fps, int(fid))
        safe = np.clip(self.rec_fp, 0, None)
        cand &= self.imat[safe[:, None], safe[None, :]] > 0
        self.pair_cand = cand
        return cand

    # -- cluster state ---------------------------------------------------

    def add_cluster(self, m: dict, j: int) -> None:
        """Track a fresh cluster (seeded from bucket-record j) in the
        screenable window."""
        k = self.k
        self.cl_list.append(m)
        self.cl_usage[k] = self.usage[j]
        self.cl_alloc_cap[k] = self.alloc_cap[j]  # seed's — never updated,
        # matching the scalar engine's cheap-reject exactly
        self.cl_zone_ok[k] = self.zone_ok[j]
        self.cl_ct_ok[k] = self.ct_ok[j]
        self.cl_zid[k] = self.zid[j]
        self.cl_screen8[k] = self.screen8[j]
        self.cl_fp[k] = self.rec_fp[j]
        self.cl_seed[k] = j
        self.k = k + 1

    def absorb(self, k: int, j: int, m: dict) -> None:
        """Fold record j into cluster row k after a successful exact
        merge (m is the cluster dict _merge_pair_exact just updated)."""
        self.dirty = True  # cluster k no longer mirrors its seed record
        self.cl_usage[k] += self.usage[j]
        if self.cl_zid[k] < 0:
            self.cl_zid[k] = self.zid[j]
        self.cl_zone_ok[k] &= self.zone_ok[j]
        self.cl_ct_ok[k] &= self.ct_ok[j]
        self.cl_screen8[k] &= self.screen8[j]
        merged = m["merged"]
        self.cl_fp[k] = self._intern(merged.fingerprint(), merged)


@contract(
    "K", "K", "K Z", "K C", "K B", "K R", "K R", "()", "Z", "C", "B", "R", "R",
    out="K",
    eval_shape=False,
)
def screen_candidates(
    cl_zid: np.ndarray,
    cl_fp: np.ndarray,
    cl_zone_ok: np.ndarray,
    cl_ct_ok: np.ndarray,
    cl_screen8: np.ndarray,
    cl_usage: np.ndarray,
    cl_alloc_cap: np.ndarray,
    rz,
    zone_ok: np.ndarray,
    ct_ok: np.ndarray,
    screen8: np.ndarray,
    usage: np.ndarray,
    alloc_cap: np.ndarray,
) -> np.ndarray:
    """One record's full candidate row over K open clusters in one
    broadcast (every reject a *necessary* condition of the scalar
    accept — see module docstring) → (K,) bool."""
    K = cl_zid.shape[0]
    Z = zone_ok.shape[0]
    cand = ((cl_zid == -1) | (rz == -1) | (cl_zid == rz)) & (cl_fp >= 0)
    zinter = cl_zone_ok & zone_ok[None, :]
    cand &= zinter.any(axis=1)
    cand &= (cl_ct_ok & ct_ok[None, :]).any(axis=1)
    eff = np.where(cl_zid >= 0, cl_zid, rz)
    if Z and (eff >= 0).any():
        zbit = zinter[np.arange(K), np.clip(eff, 0, Z - 1)]
        cand &= (eff < 0) | zbit
    cand &= ((cl_screen8 & screen8[None, :]) != 0).any(axis=1)
    cand &= np.all(
        cl_usage + usage[None, :] <= np.minimum(cl_alloc_cap, alloc_cap[None, :]),
        axis=1,
    )
    return cand


def merge_records_vector(
    solver, records: List[dict], pods, scan_cap: int
) -> List[dict]:
    """Vectorized first-fit merge over pre-sorted records → the merged
    cluster list (same order and contents as the scalar engine)."""
    st = solver._merge_stats
    merged: List[dict] = []

    with tracer.span("pack.merge.bucket", records=len(records)):
        by_key: Dict[tuple, List[int]] = {}
        for i, r in enumerate(records):
            # daemon rides in the key so every record of a bucket shares
            # the _alloc_full table the fits screen precomputes against
            # (one daemon vector per pool makes this a no-op split)
            by_key.setdefault(
                (id(r["enc"]), id(r["pool"]), len(r["usage"]), r["daemon"].tobytes()),
                [],
            ).append(i)
        buckets: List[Optional[tuple]] = [None] * len(records)
        for idxs in by_key.values():
            b = _Bucket(solver, records, idxs, scan_cap)
            for j, i in enumerate(idxs):
                buckets[i] = (b, j)

    screened = 0
    applied = 0
    with tracer.span("pack.merge.screen", records=len(records)):
        for i, r in enumerate(records):
            b, j = buckets[i]
            placed = False
            # clusters past the global scan cap are emit-only, exactly
            # like the scalar engine's merged[:cap] window
            K = b.k
            if K and b.rec_fp[j] >= 0:
                screened += K
                if not b.dirty and b.usage.shape[0] <= _PAIR_MAX:
                    # optimistic path: every open cluster still mirrors
                    # its seed record, so the row is a gather from the
                    # pairwise matrix (intersects already folded in)
                    rows = np.flatnonzero(
                        b.pair_candidates(solver)[j, b.cl_seed[:K]]
                    )
                else:
                    cand = screen_candidates(
                        b.cl_zid[:K],
                        b.cl_fp[:K],
                        b.cl_zone_ok[:K],
                        b.cl_ct_ok[:K],
                        b.cl_screen8[:K],
                        b.cl_usage[:K],
                        b.cl_alloc_cap[:K],
                        b.zid[j],
                        b.zone_ok[j],
                        b.ct_ok[j],
                        b.screen8[j],
                        b.usage[j],
                        b.alloc_cap[j],
                    )
                    rows = np.flatnonzero(cand)
                    if rows.size:
                        ok = b._intersects_row(
                            solver, b.cl_fp[rows], int(b.rec_fp[j])
                        )
                        rows = rows[ok]
                if rows.size:
                    with tracer.span("pack.merge.apply", candidates=int(rows.size)):
                        for k in rows:
                            m = b.cl_list[int(k)]
                            if solver._merge_pair_exact(
                                m, r, pods, skip_intersects=True
                            ):
                                b.absorb(int(k), j, m)
                                applied += 1
                                placed = True
                                break
            if not placed:
                m = dict(r, members=list(r["members"]))
                merged.append(m)
                if len(merged) <= scan_cap:
                    b.add_cluster(m, j)

    st["merge_candidates_screened"] = st.get("merge_candidates_screened", 0) + screened
    st["merge_pairs_applied"] = st.get("merge_pairs_applied", 0) + applied
    return merged
