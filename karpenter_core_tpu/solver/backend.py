"""Backend resolution hardened against broken TPU plugins.

The deployment image may register a TPU PJRT plugin (e.g. the ``axon``
tunnel) at interpreter startup and pin ``jax_platforms`` to it. When the
chip is unreachable, ``jax.default_backend()`` raises — or hangs — instead
of falling back. The reference's design for this failure class is
"solver-sidecar healthcheck + automatic fallback to the CPU oracle path"
(SURVEY §5 failure-detection bullet), so the solver must degrade to the
CPU/XLA path rather than crash or block the provisioning loop.

This module is the single home for that logic: ``pin_cpu`` (env var alone
does not override a sitecustomize platform pin), ``probe_backend`` (an
in-process hang cannot be interrupted, so probe in a subprocess with a
timeout), and ``default_backend`` (cached resolution with fallback).
``KARPENTER_TPU_BACKEND`` forces a platform and skips probing.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
from typing import Optional

_BACKEND: Optional[str] = None

#: last probe failure diagnostics, for surfacing in bench artifacts
LAST_PROBE_ERROR: Optional[str] = None


#: resolved compile-cache state, set once by ``enable_compilation_cache``:
#: {"status": "enabled"|"disabled"|"unavailable:<why>", "dir": path|None}
_CACHE_STATUS: Optional[dict] = None


def _default_cache_dir() -> str:
    # XDG cache location: valid for both pip-installed deployments and
    # dev checkouts (a package-relative default would land the cache
    # beside site-packages)
    xdg = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(xdg, "karpenter-tpu", "jax-cache")


def enable_compilation_cache(backend: Optional[str] = None) -> dict:
    """Point JAX's persistent compilation cache at a managed directory so
    a provisioner restart replays cached XLA binaries instead of paying
    cold compiles (~7 s on the tunneled TPU in BENCH_r03). The directory
    is ``KARPENTER_TPU_COMPILE_CACHE_DIR`` (XDG default); the warmstore
    snapshot witnesses its content fingerprint like every other plane.

    On CPU the cache re-loads AOT results compiled for slightly
    different host-feature sets (XLA warns of SIGILL risk) and CPU
    compiles are cheap anyway, so CPU stays opt-in:
    ``KARPENTER_TPU_COMPILE_CACHE_CPU_OK=1`` (tests/bench — the tier-1
    suite runs pinned to cpu and needs the cache path exercisable).
    Idempotent; opt-out with ``KARPENTER_TPU_COMPILE_CACHE=off``. Returns
    and records the status dict — a cacheless process is a counted
    status, never a silent debug line."""
    global _CACHE_STATUS
    if _CACHE_STATUS is not None:
        return _CACHE_STATUS
    if os.environ.get("KARPENTER_TPU_COMPILE_CACHE") == "off":
        _CACHE_STATUS = {"status": "disabled", "why": "opt-out", "dir": None}
        return _CACHE_STATUS
    if backend == "cpu" and os.environ.get(
        "KARPENTER_TPU_COMPILE_CACHE_CPU_OK", "0"
    ) != "1":
        _CACHE_STATUS = {"status": "disabled", "why": "cpu-backend", "dir": None}
        return _CACHE_STATUS
    path = (
        os.environ.get("KARPENTER_TPU_COMPILE_CACHE_DIR")
        or os.environ.get("KARPENTER_TPU_COMPILE_CACHE")
        or _default_cache_dir()
    )
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _CACHE_STATUS = {"status": "enabled", "why": None, "dir": path}
    except Exception as e:  # noqa: BLE001 — older jax / unwritable dir
        import logging

        # every solve pays cold compiles from here on — surface it in
        # the status (stats device block, /debug/device), not just a log
        why = f"{type(e).__name__}: {e}"
        logging.getLogger("karpenter.solver").warning(
            "persistent compilation cache unavailable: %s", why
        )
        _CACHE_STATUS = {"status": f"unavailable:{why[:160]}", "why": why, "dir": None}
    return _CACHE_STATUS


def compile_cache_status() -> dict:
    """Live compile-cache status for /debug/device and the stats device
    block: resolution outcome, managed dir, and current entry count."""
    st = dict(_CACHE_STATUS or {"status": "disabled", "why": "not-initialized", "dir": None})
    st["entries"] = len(_cache_entries(st.get("dir")))
    return st


def _cache_entries(path: Optional[str]) -> list:
    if not path or not os.path.isdir(path):
        return []
    out = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            rel = os.path.relpath(os.path.join(root, f), path)
            out.append(rel)
    return sorted(out)


def compile_cache_fingerprint() -> Optional[dict]:
    """Content fingerprint of the managed executable cache, recorded in
    the warmstore snapshot header the way every other plane is witnessed:
    jax/jaxlib versions, resolved platform, and a digest manifest of the
    cache entries. ``None`` when the cache is not enabled (the snapshot
    then carries no compile-cache plane). Restore compares this against
    the live process — a mismatched jax/platform means the cached
    executables cannot be trusted and the plane is dropped counted."""
    st = _CACHE_STATUS
    if not st or st.get("status") != "enabled" or not st.get("dir"):
        return None
    import hashlib

    try:
        import jax

        jax_v = getattr(jax, "__version__", "unknown")
    except Exception:  # noqa: BLE001
        jax_v = "unknown"
    try:
        import jaxlib.version

        jaxlib_v = jaxlib.version.__version__
    except Exception:  # noqa: BLE001
        jaxlib_v = "unknown"
    path = st["dir"]
    entries = {}
    for rel in _cache_entries(path):
        try:
            with open(os.path.join(path, rel), "rb") as fh:
                entries[rel] = hashlib.sha256(fh.read()).hexdigest()[:16]
        except OSError:
            entries[rel] = "unreadable"
    return {
        "jax": jax_v,
        "jaxlib": jaxlib_v,
        "platform": _BACKEND or os.environ.get("JAX_PLATFORMS") or "unknown",
        "dir": path,
        "entries": entries,
    }


def pin_cpu() -> None:
    """Pin this process's JAX platform to CPU, overriding any plugin pin."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


@dataclasses.dataclass
class ProbeResult:
    """Outcome of one out-of-process backend probe."""

    platform: Optional[str]  # platform forced for the probe (None = image default)
    backend: Optional[str]  # reported jax.default_backend(), None on failure
    rc: Optional[int]  # subprocess return code, None on timeout
    timed_out: bool
    stderr_tail: str  # last ~800 chars of the probe's stderr

    @property
    def ok(self) -> bool:
        return self.backend is not None

    def describe(self) -> str:
        if self.ok:
            return f"platform={self.platform or 'default'} -> {self.backend}"
        mode = "timeout" if self.timed_out else f"rc={self.rc}"
        return (
            f"platform={self.platform or 'default'} {mode}: "
            f"{self.stderr_tail[-400:] or '<no stderr>'}"
        )


# The probe runs a real device matmul, not just backend init: a tunnel
# that initializes but cannot compile/execute (round-1 failure mode:
# "TPU backend setup/compile error" raised from inside the solve) must
# count as a failed probe, not crash the solve mid-run.
_PROBE_SCRIPT = """
import os, sys
plat = sys.argv[1]
if plat:
    os.environ["JAX_PLATFORMS"] = plat
import jax
if plat:
    jax.config.update("jax_platforms", plat)
import jax.numpy as jnp
x = jnp.ones((256, 256), dtype=jnp.bfloat16)
(x @ x).block_until_ready()
print("BACKEND=" + jax.default_backend())
"""


def probe_backend(timeout: float = 120.0, platform: Optional[str] = None) -> ProbeResult:
    """Probe which backend a fresh interpreter gets — with diagnostics.

    Runs init **plus a device matmul** in a subprocess so a hanging PJRT
    init (dead TPU tunnel) costs a bounded timeout instead of blocking
    the caller forever, and captures the stderr tail so artifacts can
    record *why* init failed (raise vs hang) instead of a bare fallback.
    """
    try:
        probe = subprocess.run(
            [sys.executable, "-c", _PROBE_SCRIPT, platform or ""],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        backend = None
        for line in probe.stdout.strip().splitlines():
            if line.startswith("BACKEND="):
                backend = line[len("BACKEND=") :]
        return ProbeResult(
            platform=platform,
            backend=backend if probe.returncode == 0 else None,
            rc=probe.returncode,
            timed_out=False,
            stderr_tail=(probe.stderr or "")[-800:],
        )
    except subprocess.TimeoutExpired as e:
        stderr = e.stderr
        if isinstance(stderr, bytes):
            stderr = stderr.decode("utf-8", "replace")
        return ProbeResult(
            platform=platform,
            backend=None,
            rc=None,
            timed_out=True,
            stderr_tail=(stderr or "")[-800:],
        )


def default_backend() -> str:
    """``jax.default_backend()`` with automatic CPU fallback.

    On TPU-plugin init failure (raise or hang) the platform is re-pinned
    to ``cpu`` and the failure is remembered, so every subsequent solve
    takes the CPU path without re-probing the dead plugin.
    """
    global _BACKEND
    if _BACKEND is not None:
        return _BACKEND
    forced = os.environ.get("KARPENTER_TPU_BACKEND")
    import jax

    if forced:
        enable_compilation_cache(backend=forced)
        jax.config.update("jax_platforms", forced)
        _BACKEND = jax.default_backend()
        return _BACKEND
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # already pinned (tests, bench fallback) — CPU init can't hang
        enable_compilation_cache(backend="cpu")
        jax.config.update("jax_platforms", "cpu")
        _BACKEND = jax.default_backend()
        return _BACKEND
    # an unpinned process may get a broken TPU plugin whose init hangs;
    # probe out-of-process first so the hang mode costs a timeout, not
    # a stuck provisioning loop
    global LAST_PROBE_ERROR
    try:
        timeout = float(os.environ.get("KARPENTER_TPU_PROBE_TIMEOUT", "60"))
    except ValueError:
        timeout = 60.0
    probe = probe_backend(timeout)
    if not probe.ok:
        LAST_PROBE_ERROR = probe.describe()
        _log_fallback(LAST_PROBE_ERROR)
        pin_cpu()
        enable_compilation_cache(backend="cpu")
        _BACKEND = jax.default_backend()
        return _BACKEND
    try:
        _BACKEND = jax.default_backend()
        enable_compilation_cache(backend=_BACKEND)
    except RuntimeError as e:  # plugin raced from probe-ok to unreachable
        LAST_PROBE_ERROR = str(e)
        _log_fallback(str(e))
        pin_cpu()
        _BACKEND = jax.default_backend()
    return _BACKEND


def _log_fallback(reason: str) -> None:
    import logging

    logging.getLogger("karpenter.solver").warning(
        "TPU backend unavailable (%s); falling back to CPU/XLA path", reason
    )


def reset_for_tests() -> None:
    global _BACKEND, LAST_PROBE_ERROR, _CACHE_STATUS
    _BACKEND = None
    LAST_PROBE_ERROR = None
    _CACHE_STATUS = None
