"""Backend resolution hardened against broken TPU plugins.

The deployment image may register a TPU PJRT plugin (e.g. the ``axon``
tunnel) at interpreter startup and pin ``jax_platforms`` to it. When the
chip is unreachable, ``jax.default_backend()`` raises — or hangs — instead
of falling back. The reference's design for this failure class is
"solver-sidecar healthcheck + automatic fallback to the CPU oracle path"
(SURVEY §5 failure-detection bullet), so the solver must degrade to the
CPU/XLA path rather than crash or block the provisioning loop.

This module is the single home for that logic: ``pin_cpu`` (env var alone
does not override a sitecustomize platform pin), ``probe_backend`` (an
in-process hang cannot be interrupted, so probe in a subprocess with a
timeout), and ``default_backend`` (cached resolution with fallback).
``KARPENTER_TPU_BACKEND`` forces a platform and skips probing.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_BACKEND: Optional[str] = None


def pin_cpu() -> None:
    """Pin this process's JAX platform to CPU, overriding any plugin pin."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def probe_backend(timeout: float = 120.0) -> Optional[str]:
    """Which backend does a fresh interpreter get? None on failure/hang.

    Runs ``jax.default_backend()`` in a subprocess so a hanging PJRT init
    (dead TPU tunnel) costs a bounded timeout instead of blocking the
    caller forever.
    """
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if probe.returncode == 0 and probe.stdout.strip():
            return probe.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return None


def default_backend() -> str:
    """``jax.default_backend()`` with automatic CPU fallback.

    On TPU-plugin init failure (raise or hang) the platform is re-pinned
    to ``cpu`` and the failure is remembered, so every subsequent solve
    takes the CPU path without re-probing the dead plugin.
    """
    global _BACKEND
    if _BACKEND is not None:
        return _BACKEND
    forced = os.environ.get("KARPENTER_TPU_BACKEND")
    import jax

    if forced:
        jax.config.update("jax_platforms", forced)
        _BACKEND = jax.default_backend()
        return _BACKEND
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # already pinned (tests, bench fallback) — CPU init can't hang
        jax.config.update("jax_platforms", "cpu")
        _BACKEND = jax.default_backend()
        return _BACKEND
    # an unpinned process may get a broken TPU plugin whose init hangs;
    # probe out-of-process first so the hang mode costs a timeout, not
    # a stuck provisioning loop
    timeout = float(os.environ.get("KARPENTER_TPU_PROBE_TIMEOUT", "60"))
    if probe_backend(timeout) is None:
        _log_fallback("probe failed or timed out")
        pin_cpu()
        _BACKEND = jax.default_backend()
        return _BACKEND
    try:
        _BACKEND = jax.default_backend()
    except RuntimeError as e:  # plugin raced from probe-ok to unreachable
        _log_fallback(str(e))
        pin_cpu()
        _BACKEND = jax.default_backend()
    return _BACKEND


def _log_fallback(reason: str) -> None:
    import logging

    logging.getLogger("karpenter.solver").warning(
        "TPU backend unavailable (%s); falling back to CPU/XLA path", reason
    )


def reset_for_tests() -> None:
    global _BACKEND
    _BACKEND = None
