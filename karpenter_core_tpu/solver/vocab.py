"""Per-key value vocabularies for requirement mask encoding.

Each label key gets an interned value list plus one reserved OTHER slot
standing in for every value not observed in the batch. Complement sets
(NotIn/Exists) mark OTHER=1; concrete sets (In/DoesNotExist) mark
OTHER=0. Set-intersection nonemptiness is then exactly mask overlap —
the contract the compat kernel relies on.

OTHER lives at slot 0 and interned values at 1.. so vocabularies can
grow *incrementally across solves* (SURVEY §6: "vocab interning
maintained incrementally with cluster state"): a mask encoded at an
older, narrower width stays valid at every later width — new slots are
values the requirement never listed, so In-masks extend with False and
complement masks extend per `Requirement.has` (see
encode.extend_encoded_masks). This is what makes the cached catalog
encoding reusable batch over batch.

Gt/Lt bounds are resolved against the observed vocab host-side (values
are filtered by the bound); OTHER stays 1 for bounded complements since
unseen integers satisfying the bound always exist.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..scheduling.requirement import Requirement

OTHER_SLOT = 0


class KeyVocab:
    __slots__ = ("key", "values", "index")

    def __init__(self, key: str):
        self.key = key
        self.values: List[str] = []
        self.index: Dict[str, int] = {}  # value → slot (1-based; 0 is OTHER)

    def intern(self, value: str) -> int:
        idx = self.index.get(value)
        if idx is None:
            self.values.append(value)
            idx = len(self.values)  # slot 0 is OTHER
            self.index[value] = idx
        return idx

    @property
    def size(self) -> int:
        """Mask width: OTHER + observed values."""
        return len(self.values) + 1

    @property
    def other_slot(self) -> int:
        return OTHER_SLOT


class Vocab:
    """All key vocabularies for one catalog lineage (grows across solves)."""

    def __init__(self) -> None:
        self.keys: Dict[str, KeyVocab] = {}
        self.key_order: List[str] = []

    def key_vocab(self, key: str) -> KeyVocab:
        kv = self.keys.get(key)
        if kv is None:
            kv = KeyVocab(key)
            self.keys[key] = kv
            self.key_order.append(key)
        return kv

    def observe_requirement(self, req: Requirement) -> None:
        kv = self.key_vocab(req.key)
        for v in req.values:
            kv.intern(v)

    def observe_label(self, key: str, value: str) -> None:
        self.key_vocab(key).intern(value)

    def encode_mask(self, req: Requirement, width: int) -> np.ndarray:
        """Requirement → bool mask of `width` (≥ vocab size) slots."""
        kv = self.keys[req.key]
        mask = np.zeros(width, dtype=bool)
        if req.complement:
            # NotIn/Exists (incl. Gt/Lt bounds): everything allowed except
            # excluded values, filtered by bounds; OTHER allowed
            for i, v in enumerate(kv.values):
                mask[i + 1] = req.has(v)
            mask[OTHER_SLOT] = True
        else:
            # In/DoesNotExist: only listed values within bounds
            for v in req.values:
                if req.has(v):
                    mask[kv.index[v]] = True
        return mask
