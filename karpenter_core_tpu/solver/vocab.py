"""Per-key value vocabularies for requirement mask encoding.

Each label key gets an interned value list plus one reserved OTHER slot
standing in for every value not observed in the batch. Complement sets
(NotIn/Exists) mark OTHER=1; concrete sets (In/DoesNotExist) mark
OTHER=0. Set-intersection nonemptiness is then exactly mask overlap —
the contract the compat kernel relies on.

Gt/Lt bounds are resolved against the observed vocab host-side (values
are filtered by the bound); OTHER stays 1 for bounded complements since
unseen integers satisfying the bound always exist.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

import numpy as np

from ..scheduling.requirement import Requirement


class KeyVocab:
    __slots__ = ("key", "values", "index")

    def __init__(self, key: str):
        self.key = key
        self.values: List[str] = []
        self.index: Dict[str, int] = {}

    def intern(self, value: str) -> int:
        idx = self.index.get(value)
        if idx is None:
            idx = len(self.values)
            self.values.append(value)
            self.index[value] = idx
        return idx

    @property
    def size(self) -> int:
        """Mask width: observed values + OTHER."""
        return len(self.values) + 1

    @property
    def other_slot(self) -> int:
        return len(self.values)


class Vocab:
    """All key vocabularies for one solve batch."""

    def __init__(self) -> None:
        self.keys: Dict[str, KeyVocab] = {}
        self.key_order: List[str] = []

    def key_vocab(self, key: str) -> KeyVocab:
        kv = self.keys.get(key)
        if kv is None:
            kv = KeyVocab(key)
            self.keys[key] = kv
            self.key_order.append(key)
        return kv

    def observe_requirement(self, req: Requirement) -> None:
        kv = self.key_vocab(req.key)
        for v in req.values:
            kv.intern(v)

    def observe_label(self, key: str, value: str) -> None:
        self.key_vocab(key).intern(value)

    def encode_mask(self, req: Requirement, width: int) -> np.ndarray:
        """Requirement → bool mask of `width` (≥ vocab size) slots."""
        kv = self.keys[req.key]
        mask = np.zeros(width, dtype=bool)
        if req.complement:
            # NotIn/Exists (incl. Gt/Lt bounds): everything allowed except
            # excluded values, filtered by bounds; OTHER allowed
            for i, v in enumerate(kv.values):
                mask[i] = req.has(v)
            mask[kv.other_slot] = True
        else:
            # In/DoesNotExist: only listed values within bounds
            for v in req.values:
                if req.has(v):
                    mask[kv.index[v]] = True
        return mask
